"""Cross-process trace assembly: merge spans from durable spools and/or
live ``/traces.json`` endpoints into trace trees (docs/observability.md
"The trace plane").

One request through the fleet produces spans in N processes — router,
replica, storage, and (for control-plane traffic) the stream updater and
job workers. Each process only ever sees its own fragment; this module is
the assembler behind ``pio-tpu trace list|show|slowest``:

- **Sources.** Spool directories (the :mod:`.spool` segments of every
  process that shares the dir — read with the live-writer-tolerant
  ``tail_frames`` contract) and server base URLs (their in-memory ring at
  ``GET /traces.json``). Spans are deduped on (traceId, spanId), so a span
  present both in a spool and a ring counts once.
- **Tree building.** Spans group by trace id; parent/child edges resolve
  by span id. Each assembled trace reports ``complete`` (root present, no
  dangling ``parentId``) and the ``orphans`` whose parents are missing —
  a ring-evicted or SIGKILLed fragment is visible as such, never silently
  passed off as a whole trace.
- **Clock skew.** ``startUnix`` comes from each process's wall clock.
  For every cross-service parent→child edge the child must nest inside
  its parent's window; when it does not, the child's service gets a skew
  estimate (relative to the root's service) that centres the child in the
  parent — enough to make a waterfall readable across hosts whose clocks
  disagree by more than a span duration.
- **Waterfall.** One line per span: offset (skew-corrected), duration,
  scaled bar, service, name, status.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Any, Iterable, Optional

from incubator_predictionio_tpu.obs.spool import spool_files
from incubator_predictionio_tpu.resilience.wal import tail_frames


# ---------------------------------------------------------------------------
# span sources
# ---------------------------------------------------------------------------

def read_spool_dir(directory: str) -> tuple[list[dict], list[str]]:
    """Every span record in every spool segment under ``directory``.
    Returns ``(spans, problems)``: a segment whose readable prefix ends in
    a corrupt frame contributes its good prefix plus one problem string —
    assembly is forensics, it must surface everything salvageable."""
    spans: list[dict] = []
    problems: list[str] = []
    for path in spool_files(directory):
        records, _, status = tail_frames(path)
        spans.extend(rec for _, rec in records)
        if status == "corrupt":
            problems.append(f"{path}: corrupt frame past "
                            f"{len(records)} readable span(s)")
        # "waiting" = racing a live writer mid-frame: normal, not a problem
    return spans, problems


def fetch_url_spans(url: str, timeout: float = 5.0,
                    limit: int = 500) -> list[dict]:
    """Spans from a live server's ``GET /traces.json`` ring."""
    base = url.rstrip("/")
    if not base.endswith("/traces.json"):
        base += "/traces.json"
    with urllib.request.urlopen(f"{base}?limit={limit}",
                                timeout=timeout) as resp:
        payload = json.loads(resp.read().decode())
    spans: list[dict] = []
    for tr in payload.get("traces", []):
        spans.extend(tr.get("spans", []))
    return spans


def gather_spans(spools: Iterable[str] = (), urls: Iterable[str] = (),
                 fetch=None, timeout: float = 5.0,
                 ) -> tuple[list[dict], list[str]]:
    """Union of all sources, deduped on (traceId, spanId) — first source
    wins (spools are listed first: the durable copy is authoritative).
    An unreachable URL is a problem string, never an exception — partial
    assembly beats none when half the fleet is down (the exact situation
    an operator assembles traces in)."""
    fetch = fetch or fetch_url_spans
    out: list[dict] = []
    problems: list[str] = []
    seen: set[tuple[str, str]] = set()

    def take(spans: Iterable[dict]) -> None:
        for s in spans:
            if not isinstance(s, dict):
                continue
            key = (s.get("traceId"), s.get("spanId"))
            if key[0] is None or key[1] is None or key in seen:
                continue
            seen.add(key)
            out.append(s)

    for d in spools:
        spans, probs = read_spool_dir(d)
        take(spans)
        problems.extend(probs)
    for url in urls:
        try:
            take(fetch(url, timeout))
        except Exception as e:  # noqa: BLE001 - a dead server is a finding
            problems.append(f"{url}: {e!r}")
    return out, problems


# ---------------------------------------------------------------------------
# assembly
# ---------------------------------------------------------------------------

def _estimate_skew(spans: list[dict],
                   root: Optional[dict]) -> dict[str, float]:
    """Per-service clock-skew estimate (seconds to ADD to a service's
    ``startUnix``), relative to the root span's service.

    Walks parent→child edges top-down (parents' skews settle before their
    children's). For each cross-service edge whose child interval does not
    nest inside its (skew-corrected) parent's window, the child service's
    skew is corrected by ``centered_start - observed_start``; a later edge
    into the same service refines the running estimate (an edge that
    already fits leaves it alone)."""
    if root is None:
        return {}
    skew: dict[str, float] = {root.get("service") or "": 0.0}
    children: dict[Optional[str], list[dict]] = {}
    for s in spans:
        children.setdefault(s.get("parentId"), []).append(s)
    queue = [root]
    while queue:
        parent = queue.pop(0)
        p_svc = parent.get("service") or ""
        p_start = parent.get("startUnix", 0.0) + skew.get(p_svc, 0.0)
        p_dur = parent.get("durationSec", 0.0)
        for child in children.get(parent.get("spanId"), []):
            queue.append(child)
            c_svc = child.get("service") or ""
            if c_svc == p_svc:
                continue
            c_start = child.get("startUnix", 0.0) + skew.get(c_svc, 0.0)
            c_dur = child.get("durationSec", 0.0)
            skew.setdefault(c_svc, 0.0)
            fits = (c_start >= p_start - 1e-6
                    and c_start + c_dur <= p_start + p_dur + 1e-6)
            if not fits:
                centered = p_start + max(0.0, (p_dur - c_dur) / 2.0)
                skew[c_svc] += centered - c_start
    return {svc: round(v, 6) for svc, v in skew.items()}


def assemble(spans: Iterable[dict]) -> list[dict]:
    """Group spans into trace trees, newest trace first. Each tree:

    ``{"traceId", "root" (span or None), "spans" (start-ordered, skew
    corrected under "offsetSec"), "spanCount", "services", "durationSec",
    "complete", "orphans" (spanIds whose parent is missing),
    "clockSkewSec" ({service: skew}), "startUnix"}``."""
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        tid = s.get("traceId")
        if tid:
            by_trace.setdefault(tid, []).append(s)
    out = []
    for tid, group in by_trace.items():
        ids = {s.get("spanId") for s in group}
        roots = [s for s in group if s.get("parentId") is None]
        root = min(roots, key=lambda s: s.get("startUnix", 0.0)) \
            if roots else None
        orphans = sorted(
            s.get("spanId") for s in group
            if s.get("parentId") is not None
            and s.get("parentId") not in ids)
        skew = _estimate_skew(group, root)
        corrected = []
        base = min(s.get("startUnix", 0.0)
                   + skew.get(s.get("service") or "", 0.0) for s in group)
        for s in group:
            c = dict(s)
            c["offsetSec"] = round(
                s.get("startUnix", 0.0)
                + skew.get(s.get("service") or "", 0.0) - base, 6)
            corrected.append(c)
        corrected.sort(key=lambda s: (s["offsetSec"], s.get("spanId") or ""))
        duration = (root.get("durationSec", 0.0) if root is not None
                    else max((s["offsetSec"] + s.get("durationSec", 0.0)
                              for s in corrected), default=0.0))
        out.append({
            "traceId": tid,
            "root": root,
            "spans": corrected,
            "spanCount": len(corrected),
            "services": sorted({s.get("service") or "?" for s in group}),
            "durationSec": duration,
            "complete": root is not None and not orphans,
            "orphans": orphans,
            "clockSkewSec": skew,
            "startUnix": min(s.get("startUnix", 0.0) for s in group),
        })
    out.sort(key=lambda t: t["startUnix"], reverse=True)
    return out


def find_trace(traces: list[dict], trace_id: str,
               ) -> tuple[Optional[dict], list[str]]:
    """``(tree, prefix_matches)``: exact match first, then unique-prefix
    (ids are long hex — operators paste prefixes). An ambiguous prefix
    returns ``(None, [matching ids...])`` so the caller can say "which of
    these" instead of the affirmatively-wrong "not found"."""
    for t in traces:
        if t["traceId"] == trace_id:
            return t, [t["traceId"]]
    prefixed = [t for t in traces if t["traceId"].startswith(trace_id)]
    ids = [t["traceId"] for t in prefixed]
    return (prefixed[0] if len(prefixed) == 1 else None), ids


def slowest(traces: list[dict], n: int = 10) -> list[dict]:
    return sorted(traces, key=lambda t: t["durationSec"], reverse=True)[:n]


# ---------------------------------------------------------------------------
# terminal rendering
# ---------------------------------------------------------------------------

def waterfall(tree: dict, width: int = 40) -> list[str]:
    """One line per span: offset, duration, a bar scaled to the trace's
    extent, service, name, status."""
    spans = tree["spans"]
    extent = max((s["offsetSec"] + s.get("durationSec", 0.0)
                  for s in spans), default=0.0) or 1e-9
    header = (f"trace {tree['traceId']}  spans={tree['spanCount']}  "
              f"services={','.join(tree['services'])}  "
              f"duration={tree['durationSec'] * 1e3:.1f}ms  "
              f"complete={str(tree['complete']).lower()}")
    lines = [header]
    if tree["orphans"]:
        lines.append(f"  ! {len(tree['orphans'])} orphan span(s) — parents "
                     "missing (ring eviction or a dead process's unwritten "
                     f"spans): {', '.join(tree['orphans'][:4])}")
    skews = {svc: sk for svc, sk in tree.get("clockSkewSec", {}).items()
             if abs(sk) > 1e-6}
    if skews:
        lines.append("  ~ clock skew corrected: " + ", ".join(
            f"{svc}{sk * 1e3:+.1f}ms" for svc, sk in sorted(skews.items())))
    for s in spans:
        off = s["offsetSec"]
        dur = s.get("durationSec", 0.0)
        lo = min(width - 1, int(round(off / extent * width)))
        ln = max(1, int(round(dur / extent * width)))
        bar = " " * lo + "█" * min(ln, width - lo)
        status = s.get("status", "?")
        mark = "" if status == "ok" else "  !! " + status
        lines.append(
            f"  {off * 1e3:>9.1f}ms {dur * 1e3:>9.1f}ms "
            f"|{bar:<{width}}| {s.get('service') or '?'}: "
            f"{s.get('name') or '?'}{mark}")
    return lines


def list_rows(traces: list[dict]) -> list[dict[str, Any]]:
    """Compact per-trace rows for ``pio-tpu trace list``."""
    rows = []
    for t in traces:
        root = t["root"]
        rows.append({
            "traceId": t["traceId"],
            "spans": t["spanCount"],
            "services": ",".join(t["services"]),
            "durationMs": round(t["durationSec"] * 1e3, 1),
            "complete": t["complete"],
            "root": (root.get("name") if root else "(no root)"),
            "errors": sum(1 for s in t["spans"]
                          if s.get("status", "ok") != "ok"),
        })
    return rows


__all__ = ["read_spool_dir", "fetch_url_spans", "gather_spans", "assemble",
           "find_trace", "slowest", "waterfall", "list_rows"]
