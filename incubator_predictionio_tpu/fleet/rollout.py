"""Fleet rolling deploys — ``pio-tpu fleet rollout``.

Drives each replica's existing crash-safe single-server machinery
(query_server ``/reload``: load-beside, smoke-query gate, probation
auto-rollback — docs/resilience.md) *in sequence* across the fleet, and
adds the fleet-wide invariant the single-server pieces cannot give:

    a deploy that trips ANY replica halts the rollout and rolls the
    already-updated replicas back to last-good, so the fleet never ends a
    failed deploy half-old/half-new.

Per replica: ``POST /reload`` (a 409 means the smoke gate rejected the
new instance — the replica never served it), then an observation window
polling ``/health`` for a probation auto-rollback (the replica itself
detects a breaker-trip burst from the new instance under live traffic
and restores the pinned previous engine). Either trip halts the rollout;
already-updated replicas are rolled back via ``POST /rollback`` (which
restores their pinned previous instance while probation still holds —
keep ``--observe`` well under the replicas' ``--reload-probation``).

The router keeps serving throughout: a reloading replica's live engine
serves until the atomic swap, and a swapped replica's previous instance
stays pinned — no client-visible downtime from the deploy itself (the
chaos rollout test asserts zero non-200s through the router).

HTTP and time are injected (``http(method, url, timeout)`` + ``Clock``)
so the halt/rollback state machine is unit-tested on ``FakeClock`` with
scripted responses and zero wall sleeps.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import urllib.error
import urllib.request
from typing import Callable, Optional

from incubator_predictionio_tpu.obs.metrics import REGISTRY
from incubator_predictionio_tpu.resilience.clock import SYSTEM_CLOCK, Clock

logger = logging.getLogger(__name__)

_ROLLOUTS = REGISTRY.counter(
    "pio_fleet_rollouts_total",
    "Fleet rollout outcomes (ok / halted)", labels=("outcome",))


def _http_json(method: str, url: str,
               timeout: float = 30.0) -> tuple[int, dict]:
    """Minimal JSON round trip (status, body) tolerant of error statuses —
    the default transport; tests inject scripted ones."""
    req = urllib.request.Request(url, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        payload = e.read()
        try:
            return e.code, json.loads(payload or b"null")
        except ValueError:
            return e.code, {"raw": payload.decode(errors="replace")}


@dataclasses.dataclass
class RolloutConfig:
    replicas: tuple = ()
    server_access_key: Optional[str] = None
    #: per-replica post-swap observation window: how long the orchestrator
    #: watches /health for a probation auto-rollback before moving on.
    #: Keep it well under the replicas' --reload-probation so a later halt
    #: can still roll THIS replica back.
    observe_sec: float = 5.0
    poll_sec: float = 0.5
    timeout_sec: float = 120.0   # per /reload request (load+warm+smoke)


@dataclasses.dataclass
class RolloutResult:
    ok: bool
    #: replicas serving the new instance when the rollout ended (empty
    #: after a successful fleet-wide rollback)
    updated: list
    #: replicas rolled back to last-good during the halt
    rolled_back: list
    halted_at: Optional[str] = None
    reason: Optional[str] = None
    #: human-readable timeline, one line per step (the CLI prints these)
    events: list = dataclasses.field(default_factory=list)


def _auth(url: str, key: Optional[str]) -> str:
    return f"{url}?accessKey={key}" if key else url


def run_rollout(config: RolloutConfig,
                http: Callable[..., tuple[int, dict]] = _http_json,
                clock: Clock = SYSTEM_CLOCK) -> RolloutResult:
    """Sequential fleet rollout with halt-and-rollback. Returns the full
    timeline; ``ok`` is False on any halt (even if the rollback repaired
    every replica)."""
    updated: list[str] = []
    result = RolloutResult(ok=True, updated=updated, rolled_back=[])

    def log(line: str) -> None:
        result.events.append(line)
        logger.info("fleet rollout: %s", line)

    def halt(at: str, reason: str) -> RolloutResult:
        result.ok = False
        result.halted_at = at
        result.reason = reason
        log(f"HALT at {at}: {reason}")
        # roll the already-updated replicas back, newest first (reverse
        # deploy order — the mirror image of how they were updated)
        for url in reversed(list(updated)):
            try:
                status, body = http(
                    "POST", _auth(f"{url}/rollback",
                                  config.server_access_key),
                    timeout=config.timeout_sec)
            except Exception as e:  # noqa: BLE001 - keep rolling back
                log(f"rollback {url}: FAILED ({e!r})")
                continue
            if status == 200:
                updated.remove(url)
                result.rolled_back.append(url)
                log(f"rollback {url}: restored "
                    f"{body.get('engineInstanceId')}")
            else:
                log(f"rollback {url}: refused ({status} "
                    f"{body.get('message')})")
        _ROLLOUTS.labels(outcome="halted").inc()
        return result

    for url in config.replicas:
        url = url.rstrip("/")
        # pre-reload state: which instance would a rollback restore to
        try:
            _, health = http("GET", f"{url}/health", timeout=10.0)
            pre = (health.get("deployment") or {}).get("instanceId")
        except Exception as e:  # noqa: BLE001
            return halt(url, f"health probe failed before reload: {e!r}")
        log(f"{url}: serving {pre}; reloading")
        try:
            status, body = http(
                "POST", _auth(f"{url}/reload", config.server_access_key),
                timeout=config.timeout_sec)
        except Exception as e:  # noqa: BLE001
            return halt(url, f"reload failed: {e!r}")
        if status != 200:
            # 409 = smoke gate rejected the new instance (it never served);
            # anything else = reload machinery failure. Either halts.
            return halt(url, f"reload answered {status}: "
                             f"{body.get('message') or body}")
        new_id = body.get("engineInstanceId")
        updated.append(url)
        log(f"{url}: swapped to {new_id}; observing probation")
        # observation window: the replica's own probation machinery is the
        # detector — a serving-breaker trip under live traffic rolls the
        # replica back and /health says so
        deadline = clock.monotonic() + config.observe_sec
        while clock.monotonic() < deadline:
            try:
                _, health = http("GET", f"{url}/health", timeout=10.0)
            except Exception as e:  # noqa: BLE001
                updated.remove(url)  # unknown state; don't "roll back" it
                return halt(url, f"health probe failed during "
                                 f"probation: {e!r}")
            last = (health.get("deployment") or {}).get("lastReload") or {}
            if last.get("status") == "rolled_back":
                updated.remove(url)  # the replica already restored itself
                return halt(url, "probation tripped: replica rolled back "
                                 f"to {last.get('instanceId')} "
                                 f"({last.get('reason')})")
            clock.sleep(config.poll_sec)
        log(f"{url}: probation clean")
    log(f"rollout complete: {len(updated)} replica(s) updated")
    _ROLLOUTS.labels(outcome="ok").inc()
    return result


__all__ = ["RolloutConfig", "RolloutResult", "run_rollout"]
