"""The fleet router server — ``pio-tpu fleet route``.

An async front that spreads ``/queries.json`` across N query-server
replicas (docs/serving.md "Fleet serving"). Same server conventions as
the other three servers (server/lifecycle.py drain, obs/ telemetry
middleware + ``/metrics`` + ``/traces.json``); pure asyncio — the native
front is a per-replica optimization, the router is I/O-bound fan-out.

Routing policy per request:

1. the experiment (if any) assigns an arm — control or candidate — by
   entity hash or weighted rotation (fleet/experiments.py);
2. the arm's balancer picks the least-loaded *available* replica
   (healthy, not draining, not inside a Retry-After backoff window);
3. the query is forwarded with ``X-PIO-Trace`` and ``X-PIO-Client``
   propagated (client → router → replica → storage is ONE trace, and the
   storage tier's in-flight caps see the true originating identity);
4. transport errors and replica-side 429/503 are retried on a *different*
   replica while the request deadline allows — queries are idempotent
   reads, so a retry is safe where the event-ingest path's would not be;
5. shadow experiments mirror the query to the candidate fire-and-forget
   and compare (never serve) the response.

Replica health state is fed by the concurrent health watcher
(fleet/health.py) plus the passive per-request signals; a replica that
dies mid-storm is ejected after consecutive transport errors and
re-admitted by the probe cycle.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
import time
from typing import Optional

from aiohttp import web

from incubator_predictionio_tpu.fleet.balancer import Balancer, Replica
from incubator_predictionio_tpu.fleet.experiments import (
    CANDIDATE,
    CONTROL,
    Experiment,
)
from incubator_predictionio_tpu.fleet.health import HealthWatcher
from incubator_predictionio_tpu.obs import trace
from incubator_predictionio_tpu.obs.http import (
    add_observability_routes,
    telemetry_middleware,
)
from incubator_predictionio_tpu.obs.metrics import REGISTRY, LatencyReservoir
from incubator_predictionio_tpu.resilience.clock import SYSTEM_CLOCK, Clock
from incubator_predictionio_tpu.server.lifecycle import (
    DrainState,
    drained_exit_deadline,
    install_signal_drain,
    wait_for,
)

logger = logging.getLogger(__name__)

_REQUESTS = REGISTRY.counter(
    "pio_fleet_requests_total",
    "Queries forwarded by the fleet router, by replica and status "
    "('error' = transport failure)", labels=("replica", "status"))
_RETRIES = REGISTRY.counter(
    "pio_fleet_retries_total",
    "Forwarding attempts retried on a different replica, by reason "
    "(error = transport failure, overload = replica 429/503)",
    labels=("reason",))
_UNROUTABLE = REGISTRY.counter(
    "pio_fleet_unroutable_total",
    "Queries the router could not place on any replica (all ejected, "
    "draining, or backing off) — answered 503 + Retry-After")
_G_AVAILABLE = REGISTRY.gauge(
    "pio_fleet_replicas_available",
    "Replicas currently routable, by experiment arm", labels=("arm",))
_PARTIAL = REGISTRY.counter(
    "pio_fleet_partial_answers_total",
    "Degraded scatter/gather answers served with one or more shard ranges "
    "missing (flagged X-PIO-Partial; docs/sharding.md \"Multi-host shard "
    "owners\")")

#: statuses that mean "this replica cannot take the query right now, but
#: another one might": the idempotent-retry set. 504 is excluded — the
#: replica spent the request's deadline; there is nothing left to retry
#: with. 4xx/5xx engine answers pass through untouched.
_RETRYABLE_STATUSES = (429, 503)


@dataclasses.dataclass
class RouterConfig:
    """``pio-tpu fleet route`` flags over ``PIO_FLEET_*`` env defaults
    (docs/configuration.md)."""

    replicas: tuple = ()
    #: candidate-arm pool (a different engine version, deployed beside the
    #: control fleet); empty = no experiment routing possible
    candidates: tuple = ()
    ip: str = "0.0.0.0"
    port: int = 8200
    #: total per-query budget across every forwarding attempt; the hard
    #: wall the retry loop respects
    deadline_sec: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_FLEET_DEADLINE", "3.0")))
    #: forwarding attempts per query (distinct replicas)
    max_attempts: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_FLEET_MAX_ATTEMPTS", "2")))
    #: consecutive transport errors before a replica is ejected
    eject_threshold: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_FLEET_EJECT_THRESHOLD", "3")))
    #: health-watcher probe cadence / per-probe timeout
    health_interval_sec: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_FLEET_HEALTH_INTERVAL", "2.0")))
    probe_timeout_sec: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_FLEET_PROBE_TIMEOUT", "2.0")))
    #: outbound connection-pool cap across all replicas; 0 = unbounded.
    #: aiohttp's default pool of 100 is an invisible throughput ceiling at
    #: fleet scale (offered_qps x replica latency in-flight connections);
    #: the replicas' own admission control is the real backpressure, so
    #: the router does not queue at an arbitrary pool size by default
    max_outbound: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_FLEET_MAX_OUTBOUND", "0")))
    #: what a scatter/gather answer does when a shard range stays missing
    #: after retries within the deadline: "degrade" = serve the merged
    #: answer from the live ranges, flagged ``X-PIO-Partial`` and counted
    #: in pio_fleet_partial_answers_total; "fail" = 504. Never an
    #: unflagged short answer (docs/sharding.md).
    partial_policy: str = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "PIO_FLEET_PARTIAL_POLICY", "degrade"))
    #: guards POST /experiment; also presented as ``accessKey`` when the
    #: router drives a shard owner's /shard/promote during failover
    server_access_key: Optional[str] = None
    experiment: Optional[Experiment] = None

    def __post_init__(self):
        if self.partial_policy not in ("degrade", "fail"):
            raise ValueError(
                f"PIO_FLEET_PARTIAL_POLICY must be 'degrade' or 'fail', "
                f"got {self.partial_policy!r}")


class RouterServer:
    def __init__(self, config: RouterConfig, clock: Clock = SYSTEM_CLOCK,
                 fetch_health=None):
        if not config.replicas:
            raise ValueError("fleet router needs at least one --replica")
        self.config = config
        self._clock = clock
        # the router is the fleet's EDGE: it roots each query's trace, so
        # the head sampling decision (PIO_TRACE_SAMPLE) is minted here and
        # rides X-PIO-Trace as `:s=` to every downstream hop; the spool
        # (PIO_TRACE_SPOOL_DIR) makes this process's fragment durable
        from incubator_predictionio_tpu.obs import spool as trace_spool
        from incubator_predictionio_tpu.obs.plane import (
            configure_perf_plane_from_env,
        )

        trace_spool.configure_export_from_env("fleet_router")
        # continuous performance plane (obs/plane.py): procstats +
        # profiler + metrics history + SLO burn-rate engine
        configure_perf_plane_from_env("fleet_router")
        self.balancer = Balancer(config.replicas, clock=clock,
                                 eject_threshold=config.eject_threshold)
        self.candidate_balancer = Balancer(
            config.candidates, clock=clock,
            eject_threshold=config.eject_threshold)
        self.experiment = config.experiment
        self.watcher = HealthWatcher(
            [*self.balancer.replicas, *self.candidate_balancer.replicas],
            interval_sec=config.health_interval_sec,
            timeout=config.probe_timeout_sec,
            fetch=fetch_health, clock=clock)
        self.request_count = 0
        self.retry_count = 0
        self.unroutable_count = 0
        self.latency = LatencyReservoir()
        self._inflight = 0
        self._drain_state = DrainState("fleet_router")
        self._session = None  # lazy: needs the running loop
        self._runner: Optional[web.AppRunner] = None
        self._stop_event = asyncio.Event()
        self._shadow_tasks: set[asyncio.Task] = set()  # strong refs
        self._start_time = self._clock.monotonic()
        REGISTRY.add_collector("fleet_router", self._collect_metrics)

    def _collect_metrics(self) -> None:
        now = self._clock.monotonic()
        _G_AVAILABLE.labels(arm=CONTROL).set(sum(
            1 for r in self.balancer.replicas if r.available(now)))
        _G_AVAILABLE.labels(arm=CANDIDATE).set(sum(
            1 for r in self.candidate_balancer.replicas if r.available(now)))
        topo = self._topology()
        if topo.is_sharded:
            topo.down_ranges(now)  # publishes pio_fleet_shard_ranges_down
        else:
            from incubator_predictionio_tpu.fleet import topology as _topo

            _topo._G_RANGES_DOWN.set(0)

    # -- routes -------------------------------------------------------
    def make_app(self) -> web.Application:
        app = web.Application(
            middlewares=[telemetry_middleware("fleet_router")])
        app.router.add_get("/", self.handle_status)
        app.router.add_get("/health", self.handle_health)
        add_observability_routes(app)
        app.router.add_post("/queries.json", self.handle_query)
        # tenant-addressed queries (docs/tenancy.md): same handler — the
        # path names the engine, the pick filters on (tenant, load)
        app.router.add_post(
            "/engines/{tenant}/queries.json", self.handle_query)
        app.router.add_get("/experiment.json", self.handle_experiment_get)
        app.router.add_post("/experiment", self.handle_experiment_set)
        return app

    async def handle_status(self, request: web.Request) -> web.Response:
        topo = self._topology()
        return web.json_response({
            "status": "alive",
            "requestCount": self.request_count,
            "retries": self.retry_count,
            "unroutable": self.unroutable_count,
            "latencySecPercentiles": self.latency.percentiles(),
            "replicas": self.balancer.snapshot(),
            "candidates": self.candidate_balancer.snapshot(),
            "sharding": topo.snapshot() if topo.is_sharded else None,
            "experiment": (self.experiment.summary()
                           if self.experiment else None),
            "uptimeSec": self._clock.monotonic() - self._start_time,
        })

    async def handle_health(self, request: web.Request) -> web.Response:
        now = self._clock.monotonic()
        available = [r for r in self.balancer.replicas if r.available(now)]
        degraded = len(available) < len(self.balancer.replicas)
        status = self._drain_state.health_status(degraded)
        if not available and not self._drain_state.draining:
            status = "unroutable"
        topo = self._topology()
        sharding = None
        if topo.is_sharded:
            sharding = topo.snapshot()
            if sharding["downRanges"] and not self._drain_state.draining:
                # a shard range with zero live owners means partial (or
                # failed) answers — red, even while other replicas are up
                status = "shard-down"
        from incubator_predictionio_tpu.obs import slo as _slo

        return web.json_response({
            "status": status,
            "draining": self._drain_state.draining,
            # SLO burn-rate verdicts (obs/slo.py; None when no PIO_SLO_CONFIG)
            "slo": _slo.health_block(),
            "availableReplicas": len(available),
            "replicas": self.balancer.snapshot(),
            "candidates": self.candidate_balancer.snapshot(),
            "sharding": sharding,
            "experiment": (self.experiment.summary()
                           if self.experiment else None),
            "retries": self.retry_count,
            "unroutable": self.unroutable_count,
        }, status=200)

    # -- experiment control (pio-tpu fleet experiment) -----------------
    def _authorized(self, request: web.Request) -> bool:
        import hmac

        key = self.config.server_access_key
        if not key:
            return True
        return hmac.compare_digest(
            request.query.get("accessKey", "").encode(), key.encode())

    async def handle_experiment_get(
            self, request: web.Request) -> web.Response:
        return web.json_response({
            "experiment": (self.experiment.summary()
                           if self.experiment else None),
            "candidates": self.candidate_balancer.snapshot(),
        })

    async def handle_experiment_set(
            self, request: web.Request) -> web.Response:
        """Start (JSON body: name/mode/weight/hashField) or stop
        (``{"stop": true}``) the experiment at runtime — a promotion or
        abort must not need a router restart."""
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        try:
            body = json.loads(await request.read())
        except ValueError:
            return web.json_response(
                {"message": "invalid JSON"}, status=400)
        if body.get("stop"):
            self.experiment = None
            return web.json_response({"message": "experiment stopped"})
        if not self.candidate_balancer.replicas:
            return web.json_response(
                {"message": "no candidate replicas configured "
                            "(--candidate)"}, status=409)
        try:
            self.experiment = Experiment(
                name=body.get("name", "candidate"),
                mode=body.get("mode", "ab"),
                weight=float(body.get("weight", 0.1)),
                hash_field=body.get("hashField"))
        except (TypeError, ValueError) as e:
            return web.json_response({"message": str(e)}, status=400)
        return web.json_response(
            {"message": "experiment started",
             "experiment": self.experiment.summary()})

    # -- the hot path ---------------------------------------------------
    def _forward_headers(self, request: web.Request) -> dict:
        """Headers every hop (serve, retry, shadow mirror) carries: the
        current trace identity (the middleware adopted the client's or
        rooted one) and the ORIGINATING client identity — the storage
        tier's per-client in-flight caps must meter the real caller, not
        collapse the whole fleet's traffic into the router's identity."""
        headers = {"Content-Type": "application/json"}
        trace.inject(headers)
        client = request.headers.get("X-PIO-Client") or request.remote
        if client:
            headers["X-PIO-Client"] = client
        return headers

    async def _session_or_start(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(
                    limit=max(self.config.max_outbound, 0)))
        return self._session

    @staticmethod
    def _retry_after_sec(headers) -> Optional[float]:
        try:
            return float(headers.get("Retry-After", ""))
        except ValueError:
            return None

    async def _post_replica(self, replica: Replica, body: bytes,
                            headers: dict, timeout_sec: float,
                            path: str = "/queries.json"):
        """One forwarding attempt → (status, body, headers). Transport
        errors propagate to the retry loop; the passive balancer signals
        (EWMAs, backoff, ejection) are recorded here either way. Each
        attempt gets its own span (child of the route span) with the trace
        header re-injected under it — a replica that dies mid-request
        leaves THIS span, status `error:<Type>`, in the router's spool:
        the forensic record the chaos suite assembles."""
        import aiohttp

        session = await self._session_or_start()
        replica.inflight += 1
        t0 = self._clock.monotonic()
        try:
            with trace.span("forward", service="fleet_router",
                            replica=replica.url) as fsp:
                headers = dict(headers)
                trace.inject(headers)
                async with session.post(
                        replica.url + path, data=body,
                        headers=headers,
                        timeout=aiohttp.ClientTimeout(
                            total=timeout_sec)) as resp:
                    payload = await resp.read()
                    status, resp_headers = resp.status, resp.headers
                fsp.set_attr("status", status)
                if status >= 500:
                    # keep the edge in tail-kept traces: the replica's 5xx
                    # span is kept, and without this its parent (THIS
                    # span) would be head-dropped at s=0, orphaning the
                    # replica subtree in the assembled tree
                    fsp.status = f"error:http{status}"
        except asyncio.CancelledError:
            raise
        except Exception:
            _REQUESTS.labels(replica=replica.url, status="error").inc()
            replica.on_error()
            raise
        finally:
            replica.inflight -= 1
        _REQUESTS.labels(replica=replica.url, status=str(status)).inc()
        if status in _RETRYABLE_STATUSES:
            replica.on_overload(self._retry_after_sec(resp_headers))
        elif status >= 500:
            replica.on_failure_status()
        else:
            replica.on_success(self._clock.monotonic() - t0)
        return status, payload, resp_headers

    def _passthrough(self, status: int, payload: bytes,
                     resp_headers, replica: Replica) -> web.Response:
        headers = {"X-PIO-Fleet-Replica": replica.url}
        for h in ("X-PIO-Server-Timing", "Retry-After"):
            if h in resp_headers:
                headers[h] = resp_headers[h]
        return web.Response(
            body=payload, status=status,
            content_type="application/json", headers=headers)

    def _shadow_mirror(self, body: bytes, headers: dict,
                       served_status: int, served_body: bytes) -> None:
        """Fire-and-forget candidate mirror: the response is compared,
        never served, and a candidate outage costs nothing but a counter."""
        replica = self.candidate_balancer.pick()
        if replica is None:
            from incubator_predictionio_tpu.fleet.experiments import (
                SHADOW_MIRRORS,
            )

            SHADOW_MIRRORS.labels(outcome="error").inc()
            return

        async def mirror():
            from incubator_predictionio_tpu.fleet.experiments import (
                SHADOW_MIRRORS,
            )

            t0 = self._clock.monotonic()
            try:
                status, payload, _ = await self._post_replica(
                    replica, body, headers, self.config.deadline_sec)
            except Exception:  # noqa: BLE001 - shadow must never surface
                SHADOW_MIRRORS.labels(outcome="error").inc()
                return
            Experiment.observe(CANDIDATE, status,
                               self._clock.monotonic() - t0)
            Experiment.compare_shadow(served_status, served_body,
                                      status, payload)

        task = asyncio.get_running_loop().create_task(mirror())
        self._shadow_tasks.add(task)
        task.add_done_callback(self._shadow_tasks.discard)

    # -- shard-owner scatter/gather (docs/sharding.md) -------------------
    def _topology(self):
        from incubator_predictionio_tpu.fleet.topology import ShardTopology

        return ShardTopology(self.balancer.replicas, self._clock)

    async def _promote_owner(self, owner: Replica, rng) -> None:
        """Failover promotion: durably bump a standby's fencing epoch past
        the highest this router has observed for the range, so the deposed
        owner's rows can never re-enter a merged answer. Best-effort — a
        failed promote only delays fencing, never the query."""
        import aiohttp

        session = await self._session_or_start()
        key = self.config.server_access_key or ""
        try:
            async with session.post(
                    f"{owner.url}/shard/promote?accessKey={key}",
                    json={"epoch": rng.max_epoch},
                    timeout=aiohttp.ClientTimeout(
                        total=self.config.probe_timeout_sec)) as resp:
                if resp.status != 200:
                    return
                payload = await resp.json()
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 - best-effort
            return
        epoch = int(payload.get("epoch") or 0)
        if epoch > rng.max_epoch:
            rng.max_epoch = epoch
        if isinstance(owner.shard_owner, dict):
            owner.shard_owner["epoch"] = max(
                epoch, int(owner.shard_owner.get("epoch") or 0))
        owner.fenced = False
        logger.warning("fleet: promoted shard owner %s for rows "
                       "[%d, %d) to epoch %d", owner.url, rng.lo, rng.hi,
                       epoch)

    async def _fetch_shard(self, topo, rng, body: bytes, headers: dict,
                           deadline_at: float):
        """One shard range's partial → ``(partial dict | None,
        passthrough-response | None)``. Retries on the range's OTHER
        owners (the failover path) within the deadline; a failed-over-to
        standby is promoted first so the deposed owner is fenced. Partials
        carrying a stale epoch are discarded, never merged."""
        tried: set[str] = set()
        retry_reason: Optional[str] = None
        promote_next = False
        for _attempt in range(max(self.config.max_attempts,
                                  len(rng.owners))):
            owner = topo.pick(rng, exclude=tried)
            if owner is None:
                break
            tried.add(owner.url)
            remaining = deadline_at - self._clock.monotonic()
            if remaining <= 0:
                break
            if retry_reason is not None:
                _RETRIES.labels(reason=retry_reason).inc()
                self.retry_count += 1
                retry_reason = None
            if promote_next:
                promote_next = False
                await self._promote_owner(owner, rng)
            try:
                status, payload, resp_headers = await self._post_replica(
                    owner, body, headers, remaining,
                    path="/shard/queries.json")
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - transport failure
                # the owner is gone (SIGKILL, reset, timeout): the next
                # pick is a failover — promote it past the dead owner
                retry_reason = "error"
                promote_next = True
                continue
            if status == 200:
                try:
                    part = json.loads(payload)
                    shard = part.get("shard") or {}
                    epoch = int(shard.get("epoch") or 0)
                    part["candidates"]["ids"]  # shape check
                except (ValueError, TypeError, KeyError):
                    retry_reason = "error"
                    continue
                if epoch < rng.max_epoch:
                    # a deposed owner answered with stale rows — discard
                    # the partial outright and fence it
                    topo.fence(owner, rng.max_epoch)
                    retry_reason = "fenced"
                    continue
                if epoch > rng.max_epoch:
                    rng.max_epoch = epoch
                    if isinstance(owner.shard_owner, dict):
                        owner.shard_owner["epoch"] = epoch
                return part, None
            if status == 400:
                # query-semantic rejection: identical on every owner, the
                # client's error — pass the first one through
                return None, (status, payload, resp_headers, owner)
            retry_reason = ("overload" if status in _RETRYABLE_STATUSES
                            else "error")
        return None, None

    async def _serve_sharded(self, body: bytes, headers: dict,
                             topo) -> web.Response:
        """Scatter a query to one live owner per shard range, merge the
        partials with ``merge_topk`` (ranges ascending by lo — the
        shard-major tie discipline), assemble the /queries.json response
        shape. Missing ranges follow the declared partial policy: degrade
        (flagged + counted) or fail (504) — never an unflagged short
        answer."""
        import numpy as np

        from incubator_predictionio_tpu.serving.topk import merge_topk

        try:
            query = json.loads(body)
            if not isinstance(query, dict):
                raise ValueError("query must be a JSON object")
        except ValueError as e:
            return web.json_response(
                {"message": f"bad query: {e}"}, status=400)
        self._inflight += 1
        t0 = self._clock.monotonic()
        deadline_at = t0 + self.config.deadline_sec
        try:
            results = await asyncio.gather(*[
                self._fetch_shard(topo, rng, body, headers, deadline_at)
                for rng in topo.ranges])
            for _part, err in results:
                if err is not None:
                    status, payload, resp_headers, owner = err
                    return self._passthrough(status, payload, resp_headers,
                                             owner)
            missing = [rng for rng, (part, _e) in zip(topo.ranges, results)
                       if part is None]
            parts = [part for part, _e in results if part is not None]
            if not parts:
                self.unroutable_count += 1
                _UNROUTABLE.inc()
                return web.json_response(
                    {"message": "fleet router: no shard owner available "
                                "for any range (docs/sharding.md)"},
                    status=503, headers={"Retry-After": "1"})
            missing_rows = [[rng.lo, rng.hi] for rng in missing]
            if missing and self.config.partial_policy == "fail":
                _PARTIAL.inc()
                return web.json_response({
                    "message": "fleet router: shard range(s) unavailable "
                               "and PIO_FLEET_PARTIAL_POLICY=fail",
                    "missingRows": missing_rows,
                }, status=504)
            # merge: candidates arrive ordered by the owners' block-local
            # chains; ranges are ascending by lo, so the concatenation is
            # exactly _search_host's shard-major candidate layout. Scores
            # round-tripped f32→JSON→f64 are cast back to f32 (exact), so
            # the merge sees the owners' tie structure bit-for-bit.
            cand_ids = np.concatenate([
                np.asarray(p["candidates"]["ids"], np.int64)
                for p in parts])
            cand_sc = np.concatenate([
                np.asarray(p["candidates"]["scores"], np.float64)
                for p in parts]).astype(np.float32)
            names: dict[int, str] = {}
            for p in parts:
                names.update(zip((int(i) for i in p["candidates"]["ids"]),
                                 p["candidates"]["items"]))
            num = max(int(p["num"]) for p in parts)
            if len(cand_ids) and num > 0:
                ids, sc = merge_topk(cand_ids[None, :], cand_sc[None, :],
                                     num)
                item_scores = [
                    {"item": names[int(i)], "score": float(s)}
                    for i, s in zip(ids[0], sc[0])]
            else:
                item_scores = []
            out: dict = {"itemScores": item_scores}
            resp_headers = {"X-PIO-Fleet-Sharded": str(len(parts))}
            if missing:
                _PARTIAL.inc()
                out["partial"] = {"missingRows": missing_rows}
                resp_headers["X-PIO-Partial"] = ",".join(
                    f"rows={lo}-{hi}" for lo, hi in missing_rows)
            dt = self._clock.monotonic() - t0
            self.request_count += 1
            self.latency.record(dt)
            return web.json_response(out, headers=resp_headers)
        finally:
            self._inflight -= 1

    async def handle_query(self, request: web.Request) -> web.Response:
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        body = await request.read()
        headers = self._forward_headers(request)
        # (tenant, load) routing (docs/tenancy.md): the engine id from the
        # path or the X-PIO-Engine header narrows the pick to replicas
        # that serve it; the id forwards as the header so both multi-
        # tenant and classic single-engine replicas accept the request
        tenant = (request.match_info.get("tenant")
                  or request.headers.get("X-PIO-Engine"))
        if tenant is not None:
            headers["X-PIO-Engine"] = tenant
        # shard-owner fleets route by range, not by interchangeable pick
        topo = self._topology()
        if topo.is_sharded:
            return await self._serve_sharded(body, headers, topo)
        exp = self.experiment
        arm = CONTROL
        if exp is not None:
            payload = None
            if exp.hash_field:
                try:
                    payload = json.loads(body)
                except ValueError:
                    payload = None  # replica answers the 400; control arm
            arm = exp.assign(payload)
        serve_candidate = (arm == CANDIDATE and exp is not None
                           and exp.mode == "ab"
                           and self.candidate_balancer.replicas)
        balancer = self.candidate_balancer if serve_candidate \
            else self.balancer
        self._inflight += 1
        t0 = self._clock.monotonic()
        deadline_at = t0 + self.config.deadline_sec
        tried: set[str] = set()
        last_unroutable = False
        #: why the PREVIOUS attempt failed; counted as a retry only once a
        #: new attempt actually starts (a failed final attempt is not a
        #: retry — during a full outage nothing retries, and the metric
        #: must say so)
        retry_reason: Optional[str] = None
        #: the last orderly 429/503 a replica DID answer; if the planned
        #: retry finds no alternate replica, this passes through instead
        #: of a router-fabricated 503 (the replica's pressure-derived
        #: Retry-After is real signal; "no replica available" is not)
        last_retryable = None
        try:
            for attempt in range(self.config.max_attempts):
                replica = balancer.pick(exclude=tried, tenant=tenant)
                if replica is None and serve_candidate:
                    # candidate pool exhausted: the experiment must not
                    # cost a user their answer — fall back to control
                    balancer, arm = self.balancer, CONTROL
                    replica = balancer.pick(exclude=tried, tenant=tenant)
                if replica is None:
                    last_unroutable = True
                    break
                tried.add(replica.url)
                remaining = deadline_at - self._clock.monotonic()
                if remaining <= 0:
                    break
                if retry_reason is not None:
                    _RETRIES.labels(reason=retry_reason).inc()
                    self.retry_count += 1
                    retry_reason = None
                try:
                    status, payload, resp_headers = await self._post_replica(
                        replica, body, headers, remaining)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 - transport failure
                    retry_reason = "error"
                    continue
                if (status in _RETRYABLE_STATUSES
                        and attempt + 1 < self.config.max_attempts
                        and self._clock.monotonic() < deadline_at):
                    retry_reason = "overload"
                    last_retryable = (status, payload, resp_headers,
                                      replica)
                    continue
                dt = self._clock.monotonic() - t0
                self.request_count += 1
                self.latency.record(dt)
                if exp is not None:
                    if exp.mode == "shadow" and arm == CANDIDATE:
                        # served from control; candidate gets the mirror
                        Experiment.observe(CONTROL, status, dt)
                        self._shadow_mirror(body, headers, status, payload)
                    else:
                        Experiment.observe(arm, status, dt)
                return self._passthrough(status, payload, resp_headers,
                                         replica)
            if last_retryable is not None:
                # a replica answered an orderly 429/503 and the planned
                # retry had nowhere to go — its answer (with the real
                # pressure-derived Retry-After) beats fabricating a 503
                status, payload, resp_headers, replica = last_retryable
                dt = self._clock.monotonic() - t0
                self.request_count += 1
                self.latency.record(dt)
                if exp is not None:
                    Experiment.observe(arm, status, dt)
                return self._passthrough(status, payload, resp_headers,
                                         replica)
            # every attempt failed or nothing was routable
            self.unroutable_count += 1
            _UNROUTABLE.inc()
            reason = ("no replica available"
                      if last_unroutable else "all replicas failed")
            return web.json_response(
                {"message": f"fleet router: {reason} "
                            "(docs/serving.md \"Fleet serving\")"},
                status=503, headers={"Retry-After": "1"})
        finally:
            self._inflight -= 1

    # -- lifecycle ------------------------------------------------------
    async def start(self) -> None:
        from incubator_predictionio_tpu.obs import procstats

        # loop-lag gauge rides this server's loop (pio_process_loop_lag_*)
        self._loop_lag = procstats.start_loop_lag("fleet_router")
        self._runner = web.AppRunner(self.make_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.config.ip, self.config.port)
        await site.start()
        self.watcher.start()
        logger.info("fleet router listening on %s:%d over %d replica(s)",
                    self.config.ip, self.config.port,
                    len(self.balancer.replicas))

    async def wait_stopped(self) -> None:
        await self._stop_event.wait()
        await self.drain_and_shutdown()

    async def drain_and_shutdown(
            self, deadline_sec: Optional[float] = None) -> None:
        """New queries 503, in-flight forwards (and shadow mirrors)
        complete, then shut down within the drain deadline."""
        self._drain_state.begin()
        deadline = (drained_exit_deadline()
                    if deadline_sec is None else deadline_sec)
        await wait_for(
            lambda: self._inflight == 0 and not self._shadow_tasks,
            deadline)
        await self.shutdown()

    async def shutdown(self) -> None:
        # unregister from the process-wide registry: a later exposition
        # must not re-publish this dead router's gauges (or retain its
        # whole object graph) — bench_fleet builds several routers in one
        # process
        REGISTRY.remove_collector("fleet_router")
        lag = getattr(self, "_loop_lag", None)
        if lag is not None:
            lag.cancel()
        await self.watcher.stop()
        for task in list(self._shadow_tasks):
            task.cancel()
        if self._runner is not None:
            await self._runner.cleanup()
        if self._session is not None:
            await self._session.close()
            self._session = None
        from incubator_predictionio_tpu.obs import spool as trace_spool

        trace_spool.flush_export()


def serve_forever(config: RouterConfig) -> None:
    """Blocking entry for the CLI ``fleet route`` verb."""

    async def main():
        server = RouterServer(config)
        await server.start()
        install_signal_drain(asyncio.get_running_loop(), server._stop_event,
                             "fleet router")
        await server.wait_stopped()

    asyncio.run(main())


__all__ = ["RouterConfig", "RouterServer", "serve_forever"]
