"""Shard topology the fleet router scatter/gathers over (docs/sharding.md).

Built fresh from the balancer's replica set on each routing decision —
the watcher mutates replica state concurrently, and a derived view is
cheaper than keeping a second structure consistent. A fleet is *sharded*
when any replica announces ``/health.deployment.shardOwner``; the router
then fans every query to one live owner per shard range and merges the
partials (``merge_topk``), instead of treating replicas as
interchangeable — ejecting the last owner of a range must surface as a
down range (red fleet health + partial-answer policy), never as traffic
silently load-balanced onto owners of the *wrong* rows.

Epoch fencing: the highest epoch ever observed per shard id is sticky
(kept on the ``Replica`` objects via ``fenced``); a replica announcing or
answering with a lower epoch is a deposed owner restarted with stale
rows — its partials are discarded and it gets no traffic for the range
until it re-promotes past the fence.
"""

from __future__ import annotations

import logging
from typing import Iterable, Optional

from incubator_predictionio_tpu.fleet.balancer import Replica
from incubator_predictionio_tpu.obs.metrics import REGISTRY

logger = logging.getLogger(__name__)

_FENCED = REGISTRY.counter(
    "pio_fleet_shard_fenced_total",
    "Shard-owner replicas fenced for announcing or answering with a stale "
    "epoch (a deposed owner may never contribute rows to a merged answer)",
    labels=("replica",))
_G_RANGES_DOWN = REGISTRY.gauge(
    "pio_fleet_shard_ranges_down",
    "Shard ranges with zero live (available, unfenced) owners right now — "
    "any nonzero value means partial or failed answers")


class ShardRange:
    """One shard id's row range and its candidate owners."""

    def __init__(self, shard_id: int, lo: int, hi: int):
        self.shard_id = shard_id
        self.lo = lo
        self.hi = hi
        self.max_epoch = 0
        self.owners: list[Replica] = []

    def live_owners(self, now: float) -> list[Replica]:
        return [r for r in self.owners
                if r.available(now) and not r.fenced]

    def snapshot(self, now: float) -> dict:
        return {
            "shardId": self.shard_id,
            "rows": [self.lo, self.hi],
            "maxEpoch": self.max_epoch,
            "owners": [r.url for r in self.owners],
            "liveOwners": [r.url for r in self.live_owners(now)],
        }


class ShardTopology:
    """Derived scatter/gather view over a balancer's replicas."""

    def __init__(self, replicas: Iterable[Replica], clock):
        self._clock = clock
        self.ranges: list[ShardRange] = []
        by_id: dict[int, ShardRange] = {}
        for r in replicas:
            owner = r.shard_owner
            if not isinstance(owner, dict):
                continue
            rows = owner.get("rows")
            sid = owner.get("shardId")
            if sid is None or not rows or len(rows) != 2:
                continue
            sid = int(sid)
            rng = by_id.get(sid)
            if rng is None:
                rng = by_id[sid] = ShardRange(
                    sid, int(rows[0]), int(rows[1]))
                self.ranges.append(rng)
            else:
                # standby owners restored from the same artifacts announce
                # the same bounds; a disagreeing announcement means a
                # mid-resize fleet — take the widest view so no row is
                # silently unrouted
                rng.lo = min(rng.lo, int(rows[0]))
                rng.hi = max(rng.hi, int(rows[1]))
            epoch = int(owner.get("epoch") or 0)
            if epoch > rng.max_epoch:
                rng.max_epoch = epoch
            rng.owners.append(r)
        self.ranges.sort(key=lambda g: (g.lo, g.shard_id))
        # sticky fencing: any owner announcing below its range's max epoch
        # is deposed until it re-promotes past the fence
        for rng in self.ranges:
            for r in rng.owners:
                epoch = int((r.shard_owner or {}).get("epoch") or 0)
                if epoch < rng.max_epoch and not r.fenced:
                    self.fence(r, rng.max_epoch)

    @property
    def is_sharded(self) -> bool:
        return bool(self.ranges)

    def fence(self, replica: Replica, max_epoch: int) -> None:
        """Mark a deposed owner: no traffic, partials discarded, until a
        health probe shows it re-promoted past ``max_epoch``."""
        replica.fenced = True
        _FENCED.labels(replica=replica.url).inc()
        logger.warning(
            "fleet: fenced shard owner %s (announced epoch %s < fleet "
            "max %d for shard %s)", replica.url,
            (replica.shard_owner or {}).get("epoch"), max_epoch,
            (replica.shard_owner or {}).get("shardId"))

    def down_ranges(self, now: Optional[float] = None) -> list[ShardRange]:
        if now is None:
            now = self._clock.monotonic()
        down = [g for g in self.ranges if not g.live_owners(now)]
        _G_RANGES_DOWN.set(len(down))
        return down

    def pick(self, rng: ShardRange,
             exclude: Iterable[str] = ()) -> Optional[Replica]:
        """Least-score live owner of ``rng`` not yet tried this request —
        the Balancer.pick discipline restricted to one shard range."""
        now = self._clock.monotonic()
        skip = set(exclude)
        best: Optional[Replica] = None
        best_score = float("inf")
        for r in rng.live_owners(now):
            if r.url in skip:
                continue
            s = r.score(now)
            if s < best_score:
                best, best_score = r, s
        if best is not None:
            return best
        # backoff-relax fallback (Balancer.pick): a 429 burst must not
        # fabricate a missing shard — fenced/ejected owners stay out
        for r in rng.owners:
            if r.url in skip or r.fenced:
                continue
            if not (r.healthy and not r.draining):
                continue
            s = r.score(now)
            if s < best_score:
                best, best_score = r, s
        return best

    def snapshot(self) -> dict:
        now = self._clock.monotonic()
        down = self.down_ranges(now)
        return {
            "sharded": True,
            "nRanges": len(self.ranges),
            "downRanges": [[g.lo, g.hi] for g in down],
            "ranges": [g.snapshot(now) for g in self.ranges],
        }


__all__ = ["ShardRange", "ShardTopology"]
