"""Fleet serving tier (docs/serving.md "Fleet serving").

One query-server process serves one engine; "millions of users" need a
*fleet*. This package is the routing front over N query-server replicas:

- :mod:`balancer` — replica registry + health/admission-aware picking
  (least-loaded weighted by each replica's live admission limit, passive
  latency/error EWMAs, consecutive-error ejection, Retry-After backoff);
- :mod:`health` — the concurrent ``/health`` prober (shared with
  ``pio-tpu health``) and the watcher that folds probe results into the
  balancer's replica states, including the ejected-replica probe cycle;
- :mod:`router` — the async router server: ``/queries.json`` in,
  health-aware replica choice, idempotent retry on a different replica
  within the request deadline, A/B and shadow experiment routing; when
  replicas announce shard-owner claims, scatter/gather over the shard
  topology instead of load balancing;
- :mod:`topology` — the shard-ownership map built from ``/health``
  claims (docs/sharding.md "Multi-host shard owners"): one live owner
  per ``[lo, hi)`` row range, epoch fencing of deposed owners, and the
  down-range accounting behind partial answers;
- :mod:`rollout` — the fleet rolling-deploy orchestrator driving each
  replica's versioned ``/reload`` + smoke gate + probation hot-swap in
  sequence, halting and rolling the fleet back on a tripped replica;
- :mod:`experiments` — weighted / entity-hashed A/B arm assignment and
  fire-and-forget shadow mirroring with per-arm ``pio_fleet_*`` metrics.
"""

from incubator_predictionio_tpu.fleet.balancer import Balancer, Replica
from incubator_predictionio_tpu.fleet.experiments import Experiment
from incubator_predictionio_tpu.fleet.health import (
    HealthWatcher,
    fetch_health,
    probe_health_urls,
)
from incubator_predictionio_tpu.fleet.rollout import (
    RolloutConfig,
    RolloutResult,
    run_rollout,
)
from incubator_predictionio_tpu.fleet.topology import (
    ShardRange,
    ShardTopology,
)

__all__ = [
    "Balancer", "Replica", "Experiment", "HealthWatcher",
    "fetch_health", "probe_health_urls",
    "RolloutConfig", "RolloutResult", "run_rollout",
    "ShardRange", "ShardTopology",
]
