"""Replica registry + health/admission-aware balancing for the fleet router.

A :class:`Replica` mirrors what one query server tells the fleet about
itself — the machine-readable ``/health`` surface (draining, brownout,
``admission.inflightLimit``, deployed instance/engine version) — plus what
the router *observes* passively on every forwarded request (latency EWMA,
error EWMA, consecutive transport errors, ``Retry-After`` backoff).

The :class:`Balancer` picks the least-loaded available replica, where
"load" is in-flight requests normalized by the replica's own live
admission limit: a replica whose AIMD limiter shrank to 1 slot is half as
attractive as one holding 2, so the fleet respects each process's
self-reported capacity instead of spraying uniformly. Brownout and error
history multiply the score — a degraded replica keeps serving (degraded
200s beat sheds) but only picks up traffic the healthy replicas cannot.

Everything is clock-injected; tests script ejection/backoff/probe
timelines on ``FakeClock`` with zero wall sleeps (the resilience-layer
pattern, resilience/clock.py).
"""

from __future__ import annotations

import logging
from typing import Iterable, Optional

from incubator_predictionio_tpu.obs.metrics import REGISTRY
from incubator_predictionio_tpu.resilience.clock import SYSTEM_CLOCK, Clock

logger = logging.getLogger(__name__)

_G_HEALTHY = REGISTRY.gauge(
    "pio_fleet_replica_healthy",
    "1 while the router considers the replica routable (healthy, not "
    "draining, not ejected), 0 otherwise", labels=("replica",))
_EJECTIONS = REGISTRY.counter(
    "pio_fleet_ejections_total",
    "Replicas ejected from rotation after consecutive transport errors "
    "(re-admitted by a successful health probe)", labels=("replica",))

#: EWMA smoothing factor for the passive latency/error estimates: ~20
#: requests of memory — fast enough to notice a replica going bad, slow
#: enough that one outlier doesn't reshuffle the fleet.
_EWMA_ALPHA = 0.1
#: Retry-After values above this are clamped: a replica asking the fleet
#: to stay away for minutes is better served by ejection + probe.
_BACKOFF_CAP_SEC = 30.0


class Replica:
    """One query-server replica as the router sees it."""

    def __init__(self, url: str, clock: Clock = SYSTEM_CLOCK,
                 eject_threshold: int = 3):
        self.url = url.rstrip("/")
        self._clock = clock
        self.eject_threshold = eject_threshold
        # -- watcher-fed state (fleet/health.py) --------------------------
        self.healthy = True          # False = ejected from rotation
        self.draining = False
        self.brownout = False
        self.inflight_limit = 2      # admission.inflightLimit from /health
        self.instance_id: Optional[str] = None
        self.engine_version: Optional[str] = None
        self.last_delta_seq: Optional[int] = None   # streaming chain pos
        self.staleness_sec: Optional[float] = None  # model freshness lag
        self.last_probe_ok: Optional[bool] = None
        # -- multi-host shard ownership (docs/sharding.md) ----------------
        # /health.deployment.shardOwner: {"shardId", "shardCount",
        # "epoch", "rows": [lo, hi]}. None = whole-catalog replica.
        # ``fenced`` is router-side state: True once a HIGHER epoch has
        # been observed for this replica's shard — a deposed owner must
        # never contribute rows to a merged answer (fleet/topology.py).
        self.shard_owner: Optional[dict] = None
        self.fenced = False
        # -- multi-tenant deployment (docs/tenancy.md) --------------------
        # /health.deployment.engines: the engine ids this replica is
        # REGISTERED to serve (it can cold-load any of them);
        # deployment.resident: the subset currently loaded. Empty set =
        # classic single-engine replica (serves everything it's asked).
        self.engines: set[str] = set()
        self.resident: set[str] = set()
        # -- passive per-request state (router observations) --------------
        self.inflight = 0
        self.lat_ewma: Optional[float] = None
        self.err_ewma = 0.0
        self.consecutive_errors = 0
        self.backoff_until = 0.0     # Retry-After honor (monotonic)
        self.requests = 0
        self.errors = 0
        self._publish()

    def _publish(self) -> None:
        _G_HEALTHY.labels(replica=self.url).set(
            1 if (self.healthy and not self.draining) else 0)

    # -- availability -----------------------------------------------------
    def available(self, now: Optional[float] = None) -> bool:
        if now is None:
            now = self._clock.monotonic()
        return (self.healthy and not self.draining
                and now >= self.backoff_until)

    def serves(self, tenant: Optional[str]) -> bool:
        """Can this replica answer for ``tenant``? Single-engine replicas
        (no advertised engine set) serve whatever they're asked — the
        pre-tenancy fleet shape keeps working unchanged."""
        return tenant is None or not self.engines or tenant in self.engines

    def score(self, now: Optional[float] = None,
              tenant: Optional[str] = None) -> float:
        """Lower is better. Load per admitted slot, inflated by the error
        EWMA and (heavily) by brownout — a browned-out replica is a last
        resort, not a peer. A multi-tenant replica that would have to
        COLD-LOAD the tenant (registered but not resident) carries a
        moderate penalty: a warm peer wins, but a cold load still beats
        an unroutable 503."""
        load = (self.inflight + 1) / max(1, self.inflight_limit)
        s = load * (1.0 + 4.0 * self.err_ewma)
        if self.brownout:
            s *= 8.0
        if (tenant is not None and self.engines
                and tenant not in self.resident):
            s *= 3.0
        return s

    # -- passive observations (router request path) -----------------------
    def on_success(self, latency_sec: float) -> None:
        self.requests += 1
        self.consecutive_errors = 0
        self.err_ewma *= (1.0 - _EWMA_ALPHA)
        self.lat_ewma = (latency_sec if self.lat_ewma is None else
                         (1.0 - _EWMA_ALPHA) * self.lat_ewma
                         + _EWMA_ALPHA * latency_sec)

    def on_failure_status(self) -> None:
        """Replica-side 5xx that is neither overload nor a transport
        failure (an engine 500, a burned-deadline 504): the answer passes
        through to the client, but the error EWMA must rise — a broken
        replica failing in ~2ms would otherwise look like the fastest,
        least-loaded pick and the balancer would concentrate traffic on
        it. No ejection (its /health probe still succeeds and would
        re-admit it instantly); the score penalty does the shunning."""
        self.requests += 1
        self.errors += 1
        self.err_ewma = (1.0 - _EWMA_ALPHA) * self.err_ewma + _EWMA_ALPHA

    def on_overload(self, retry_after_sec: Optional[float]) -> None:
        """429/503 from the replica: honor its Retry-After — stop offering
        it traffic for that window instead of hammering a server that just
        told us its queue is full."""
        self.requests += 1
        backoff = min(_BACKOFF_CAP_SEC,
                      retry_after_sec if retry_after_sec else 1.0)
        self.backoff_until = self._clock.monotonic() + backoff
        self.err_ewma = (1.0 - _EWMA_ALPHA) * self.err_ewma + _EWMA_ALPHA

    def on_error(self) -> bool:
        """Transport-level failure (refused, reset, timeout). Returns True
        when this error crossed the ejection threshold."""
        self.requests += 1
        self.errors += 1
        self.consecutive_errors += 1
        self.err_ewma = (1.0 - _EWMA_ALPHA) * self.err_ewma + _EWMA_ALPHA
        if self.healthy and self.consecutive_errors >= self.eject_threshold:
            self.healthy = False
            _EJECTIONS.labels(replica=self.url).inc()
            self._publish()
            logger.warning("fleet: ejected replica %s after %d consecutive "
                           "errors (probe cycle will re-admit)", self.url,
                           self.consecutive_errors)
            return True
        return False

    # -- watcher updates (fleet/health.py) --------------------------------
    def update_from_health(self, health: dict) -> None:
        """Fold one successful ``/health`` probe in. A reachable replica
        re-enters rotation (the probe IS the half-open probe of the
        ejection cycle); draining/brownout/admission-limit ride along."""
        self.last_probe_ok = True
        self.draining = bool(health.get("draining"))
        adm = health.get("admission") or {}
        limit = adm.get("inflightLimit")
        if isinstance(limit, (int, float)) and limit >= 1:
            self.inflight_limit = int(limit)
        self.brownout = bool(adm.get("brownoutActive"))
        dep = health.get("deployment") or {}
        self.instance_id = dep.get("instanceId", self.instance_id)
        self.engine_version = dep.get("engineVersion", self.engine_version)
        # streaming update lag (docs/streaming.md): which delta chain
        # position this replica serves and how stale its model is —
        # surfaced on the router's /health so operators spot a replica the
        # updater can't reach
        stream = dep.get("streaming") or {}
        self.last_delta_seq = stream.get("lastDeltaSeq")
        self.staleness_sec = stream.get("stalenessSeconds")
        # shard-owner claim: adopt the announced range/epoch; an epoch
        # BUMP on this replica clears any fence (it re-promoted)
        # multi-tenant replicas advertise their registered + resident
        # engine sets; the (tenant, load) pick and `pio-tpu tenants` read
        # them off the snapshot
        engines = dep.get("engines")
        self.engines = (set(engines)
                        if isinstance(engines, (list, set)) else set())
        resident = dep.get("resident")
        self.resident = (set(resident)
                         if isinstance(resident, (list, set)) else set())
        owner = dep.get("shardOwner")
        if isinstance(owner, dict):
            prev = self.shard_owner or {}
            if (owner.get("epoch") or 0) > (prev.get("epoch") or 0):
                self.fenced = False
            self.shard_owner = owner
        else:
            self.shard_owner = None
            self.fenced = False
        if not self.healthy:
            logger.info("fleet: probe succeeded — re-admitting replica %s",
                        self.url)
        self.healthy = True
        self.consecutive_errors = 0
        self._publish()

    def mark_unreachable(self) -> None:
        """Failed health probe: out of rotation until a probe succeeds."""
        self.last_probe_ok = False
        if self.healthy:
            _EJECTIONS.labels(replica=self.url).inc()
            logger.warning("fleet: health probe failed — ejecting replica "
                           "%s", self.url)
        self.healthy = False
        self._publish()

    def snapshot(self) -> dict:
        now = self._clock.monotonic()
        return {
            "url": self.url,
            "healthy": self.healthy,
            "draining": self.draining,
            "brownout": self.brownout,
            "available": self.available(now),
            "inFlight": self.inflight,
            "inflightLimit": self.inflight_limit,
            "backoffSec": round(max(0.0, self.backoff_until - now), 3),
            "latencyEwmaMs": (round(self.lat_ewma * 1e3, 2)
                              if self.lat_ewma is not None else None),
            "errorEwma": round(self.err_ewma, 4),
            "requests": self.requests,
            "errors": self.errors,
            "instanceId": self.instance_id,
            "engineVersion": self.engine_version,
            "lastDeltaSeq": self.last_delta_seq,
            "stalenessSec": self.staleness_sec,
            "shardOwner": self.shard_owner,
            "fenced": self.fenced,
            "engines": sorted(self.engines) or None,
            "resident": sorted(self.resident) or None,
        }


class Balancer:
    """Least-score pick over a fixed replica set (one pool — the router
    holds one balancer per experiment arm)."""

    def __init__(self, replicas: Iterable, clock: Clock = SYSTEM_CLOCK,
                 eject_threshold: int = 3):
        self._clock = clock
        self.replicas: list[Replica] = [
            r if isinstance(r, Replica)
            else Replica(r, clock=clock, eject_threshold=eject_threshold)
            for r in replicas
        ]

    def pick(self, exclude: Iterable[str] = (),
             tenant: Optional[str] = None) -> Optional[Replica]:
        """The available replica with the lowest load score (ties broken by
        registration order — deterministic for tests). ``exclude`` names
        replicas already tried this request, so a retry lands elsewhere.
        ``tenant`` restricts the pick to replicas that serve that engine
        (docs/tenancy.md): multi-tenant replicas advertise their engine
        set via /health; replicas with no set serve everything. Among the
        eligible, a replica holding the tenant RESIDENT outranks one that
        would cold-load it.

        ``Retry-After`` backoff is a routing *preference*, not a hard gate:
        when every otherwise-healthy replica sits inside a backoff window
        (a transient 429 burst — e.g. the retry wave right after a replica
        dies — can put the whole remaining fleet there at once), the
        least-loaded one is picked anyway. Worst case the replica answers
        its own orderly 429; fabricating a router 503 below capacity is
        strictly worse. Ejected/draining replicas are never relaxed in."""
        now = self._clock.monotonic()
        skip = set(exclude)
        best = self._best(now, skip, ignore_backoff=False, tenant=tenant)
        if best is None:
            best = self._best(now, skip, ignore_backoff=True, tenant=tenant)
        return best

    def _best(self, now: float, skip: set, ignore_backoff: bool,
              tenant: Optional[str] = None) -> Optional[Replica]:
        best: Optional[Replica] = None
        best_score = float("inf")
        for r in self.replicas:
            if r.url in skip or not r.serves(tenant):
                continue
            if ignore_backoff:
                if not (r.healthy and not r.draining):
                    continue
            elif not r.available(now):
                continue
            s = r.score(now, tenant=tenant)
            if s < best_score:
                best, best_score = r, s
        return best

    def snapshot(self) -> list[dict]:
        return [r.snapshot() for r in self.replicas]


__all__ = ["Balancer", "Replica"]
