"""Concurrent ``/health`` probing — ONE implementation behind both the
``pio-tpu health`` CLI verb and the fleet router's health watcher.

The probe fans out over a thread pool: a fleet with one slow or dead
replica answers in ~one probe timeout, not O(N × timeout) (the serial
``_fetch_health`` loop the CLI used to run). The router's
:class:`HealthWatcher` drives the same ``fetch`` concurrently from its
async loop (per-URL ``run_in_executor`` on a persistent pool it owns),
then folds the results into the balancer's replica states:

- unreachable probe  → replica ejected from rotation;
- reachable probe    → replica (re-)admitted — the probe IS the half-open
  step of the ejection cycle — and its draining/brownout flags, live
  ``admission.inflightLimit``, and deployed instance/engine version are
  adopted.

``apply_results`` is pure and synchronous, so the ejection/probe cycle is
unit-testable on ``FakeClock`` with zero wall sleeps.
"""

from __future__ import annotations

import asyncio
import json
import logging
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Optional

from incubator_predictionio_tpu.resilience.clock import SYSTEM_CLOCK, Clock

logger = logging.getLogger(__name__)


def fetch_health(url: str, timeout: float = 5.0) -> dict:
    """GET ``<url>/health``, parsed (the probe the thread pool runs)."""
    import urllib.request

    base = url.rstrip("/")
    if not base.endswith("/health"):
        base += "/health"
    with urllib.request.urlopen(base, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def probe_health_urls(
    urls: Iterable[str], timeout: float = 5.0,
    fetch: Optional[Callable[[str, float], dict]] = None,
    max_workers: int = 16,
) -> dict[str, tuple[Optional[dict], Optional[str]]]:
    """Probe every URL concurrently. Returns ``{url: (health, error)}``
    where exactly one of the pair is None — reachable probes carry the
    parsed /health dict, failures carry ``repr(exception)``. The
    synchronous one-shot fan-out (the CLI verb); the long-lived watcher
    drives the same ``fetch`` through its own persistent pool."""
    urls = list(urls)
    if not urls:
        return {}
    fetch = fetch or fetch_health
    results: dict[str, tuple[Optional[dict], Optional[str]]] = {}
    with ThreadPoolExecutor(
            max_workers=min(max_workers, len(urls))) as pool:
        futures = {url: pool.submit(fetch, url, timeout) for url in urls}
        for url, fut in futures.items():
            try:
                results[url] = (fut.result(), None)
            except Exception as e:  # noqa: BLE001 - unreachable is a result
                results[url] = (None, repr(e))
    return results


def replication_flags(health: Optional[dict]) -> Optional[dict]:
    """Storage-replication reading of a ``/health`` payload
    (docs/replication.md): role, epoch, lag, and whether the replica
    should turn a fleet probe RED — fenced (a deposed primary every
    write bounces off) or lag-exceeded (the async bound is blown and the
    sole-copy window is growing). Returns None for servers without a
    replication section (query/event servers, unreplicated stores) so
    callers can thread it straight into their row fold."""
    if not health:
        return None
    repl = health.get("replication")
    if not isinstance(repl, dict):
        return None
    fenced = bool(repl.get("fenced"))
    lag_exceeded = bool(repl.get("lagExceeded"))
    return {
        "role": repl.get("role"),
        "epoch": repl.get("epoch"),
        "fenced": fenced,
        "lagBytes": repl.get("lagBytes"),
        "lagExceeded": lag_exceeded,
        "fencedWrites": repl.get("fencedWrites"),
        "contactAgeSeconds": repl.get("contactAgeSeconds"),
        "red": fenced or lag_exceeded,
    }


class HealthWatcher:
    """Periodic concurrent probe of every fleet replica, folding results
    into the balancer state (fleet/balancer.py)."""

    def __init__(self, replicas, interval_sec: float = 2.0,
                 timeout: float = 2.0,
                 fetch: Optional[Callable[[str, float], dict]] = None,
                 clock: Clock = SYSTEM_CLOCK):
        #: the Replica objects to keep current (shared with the balancers)
        self.replicas = list(replicas)
        self.interval_sec = interval_sec
        self.timeout = timeout
        self._fetch = fetch
        self._clock = clock
        self._task: Optional[asyncio.Task] = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self.probes = 0

    # -- pure state transitions (unit-tested on FakeClock) ----------------
    def apply_results(
            self, results: dict[str, tuple[Optional[dict], Optional[str]]],
    ) -> None:
        self.probes += 1
        for replica in self.replicas:
            got = results.get(replica.url)
            if got is None:
                continue
            health, err = got
            if health is None:
                replica.mark_unreachable()
            else:
                replica.update_from_health(health)

    # -- async loop (the router's background task) ------------------------
    async def tick(self) -> None:
        """One concurrent probe round on the watcher's own persistent
        pool — per-URL ``run_in_executor`` + gather, so no per-tick
        executor churn and no default-executor thread burned just to
        join futures."""
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=min(16, len(self.replicas) or 1),
                thread_name_prefix="fleet-probe")
        loop = asyncio.get_running_loop()
        fetch = self._fetch or fetch_health

        async def probe(url: str):
            try:
                health = await loop.run_in_executor(
                    self._pool, fetch, url, self.timeout)
                return url, (health, None)
            except Exception as e:  # noqa: BLE001 - unreachable is a result
                return url, (None, repr(e))

        results = dict(await asyncio.gather(
            *(probe(r.url) for r in self.replicas)))
        self.apply_results(results)

    async def _run(self) -> None:
        while True:
            try:
                await self.tick()
            except Exception:  # noqa: BLE001 - the watcher must survive
                logger.exception("fleet health watcher tick failed")
            await asyncio.sleep(self.interval_sec)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None


__all__ = ["HealthWatcher", "fetch_health", "probe_health_urls",
           "replication_flags"]
