"""A/B and shadow experiment routing (docs/serving.md "Fleet serving").

An :class:`Experiment` splits ``/queries.json`` traffic between the
*control* pool (the live engine version) and a *candidate* pool (the
version under evaluation):

- **ab** mode routes a slice of traffic to the candidate and serves its
  answer. The slice is *entity-hashed* when ``hash_field`` names a query
  field (the same user always lands on the same arm — session-stable, and
  stable across router restarts because the hash is derived, not stored),
  else a deterministic weighted rotation.
- **shadow** mode serves every query from control and mirrors the slice
  to the candidate fire-and-forget; the mirrored response is *compared*
  (status + body) but never served — zero user risk, live parity
  evidence.

Per-arm ``pio_fleet_arm_*`` metrics (request/status counts, latency
histograms) and the shadow match counters are the promote-or-abort
evidence ``pio-tpu fleet experiment`` renders.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Optional

from incubator_predictionio_tpu.obs.metrics import REGISTRY

CONTROL = "control"
CANDIDATE = "candidate"

ARM_REQUESTS = REGISTRY.counter(
    "pio_fleet_arm_requests_total",
    "Routed queries by experiment arm and response status",
    labels=("arm", "status"))
ARM_LATENCY = REGISTRY.histogram(
    "pio_fleet_arm_latency_seconds",
    "Client-observed latency through the router, by experiment arm",
    labels=("arm",))
SHADOW_MIRRORS = REGISTRY.counter(
    "pio_fleet_shadow_total",
    "Shadow-mirrored queries by comparison outcome (matched / mismatched "
    "/ error — the candidate's answer is compared, never served)",
    labels=("outcome",))

#: hash-bucket resolution: 1/2^32 granularity on the weight split
_BUCKETS = float(0xFFFFFFFF)


@dataclasses.dataclass
class Experiment:
    """One live experiment's routing policy + bookkeeping."""

    name: str = "candidate"
    #: "ab" (serve the candidate's answers) or "shadow" (mirror + compare)
    mode: str = "ab"
    #: fraction of traffic assigned to the candidate arm, 0..1
    weight: float = 0.1
    #: query field whose value hashes to a sticky arm assignment (e.g.
    #: "user"); None/absent field falls back to a weighted rotation
    hash_field: Optional[str] = None

    def __post_init__(self):
        if self.mode not in ("ab", "shadow"):
            raise ValueError(f"experiment mode must be ab|shadow, "
                             f"got {self.mode!r}")
        self.weight = min(1.0, max(0.0, float(self.weight)))
        self._rotation_credit = 0.0
        self.assigned = {CONTROL: 0, CANDIDATE: 0}

    # -- assignment -------------------------------------------------------
    def bucket(self, entity: str) -> float:
        """Stable [0, 1) bucket for an entity: sha1 over name+entity, so
        the split is reproducible across routers and restarts but
        decorrelated between experiments (a user in experiment A's 10%
        is not automatically in experiment B's)."""
        digest = hashlib.sha1(
            f"{self.name}:{entity}".encode()).hexdigest()[:8]
        return int(digest, 16) / _BUCKETS

    def assign(self, payload: Optional[dict]) -> str:
        """Arm for one query. Entity-hashed when ``hash_field`` resolves;
        otherwise a deterministic weighted rotation (accumulated credit —
        no RNG, so tests and replays are exact)."""
        arm = CONTROL
        entity = None
        if self.hash_field and isinstance(payload, dict):
            entity = payload.get(self.hash_field)
        if entity is not None:
            if self.bucket(str(entity)) < self.weight:
                arm = CANDIDATE
        else:
            self._rotation_credit += self.weight
            if self._rotation_credit >= 1.0:
                self._rotation_credit -= 1.0
                arm = CANDIDATE
        self.assigned[arm] += 1
        return arm

    # -- evidence ---------------------------------------------------------
    @staticmethod
    def observe(arm: str, status: int, latency_sec: float) -> None:
        ARM_REQUESTS.labels(arm=arm, status=str(status)).inc()
        ARM_LATENCY.labels(arm=arm).observe(latency_sec)

    @staticmethod
    def compare_shadow(served_status: int, served_body: bytes,
                       shadow_status: int, shadow_body: bytes) -> str:
        """Outcome label for one mirrored response. Body comparison is on
        canonical JSON (key order must not count as drift); non-JSON
        bodies compare raw."""
        if served_status != shadow_status:
            outcome = "mismatched"
        else:
            try:
                outcome = ("matched"
                           if json.loads(served_body) == json.loads(shadow_body)
                           else "mismatched")
            except ValueError:
                outcome = ("matched" if served_body == shadow_body
                           else "mismatched")
        SHADOW_MIRRORS.labels(outcome=outcome).inc()
        return outcome

    def summary(self) -> dict:
        return {
            "name": self.name,
            "mode": self.mode,
            "weight": self.weight,
            "hashField": self.hash_field,
            "assigned": dict(self.assigned),
        }


__all__ = ["CANDIDATE", "CONTROL", "Experiment",
           "ARM_LATENCY", "ARM_REQUESTS", "SHADOW_MIRRORS"]
