"""e2 — evaluation helper library (reference e2/src/main/scala/.../e2/).

Pure helpers usable by any template: categorical NaiveBayes, Markov chain,
binary one-hot vectorizer, k-fold cross-validation. The reference versions
are Spark-RDD helpers; these are host-side numpy (this is metadata-scale
math; the TPU path lives in models/)."""

from incubator_predictionio_tpu.e2.naive_bayes import (
    CategoricalNaiveBayes,
    CategoricalNaiveBayesModel,
    LabeledPoint,
)
from incubator_predictionio_tpu.e2.markov_chain import MarkovChain, MarkovChainModel
from incubator_predictionio_tpu.e2.vectorizer import BinaryVectorizer
from incubator_predictionio_tpu.e2.cross_validation import k_fold_split

__all__ = [
    "BinaryVectorizer",
    "CategoricalNaiveBayes",
    "CategoricalNaiveBayesModel",
    "LabeledPoint",
    "MarkovChain",
    "MarkovChainModel",
    "k_fold_split",
]
