"""Categorical Naive Bayes over string features.

Behavioral parity with the reference (e2/.../engine/CategoricalNaiveBayes.scala:29-154):
log-space priors and per-position feature likelihoods, a pluggable default
likelihood for unseen feature values (defaults to -inf, i.e. veto), and
``predict`` returning the argmax label.
"""

from __future__ import annotations

import dataclasses
import math
from collections import Counter, defaultdict
from typing import Callable, Iterable, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class LabeledPoint:
    """(CategoricalNaiveBayes.scala:156)"""

    label: str
    features: tuple[str, ...]


@dataclasses.dataclass
class CategoricalNaiveBayesModel:
    """(CategoricalNaiveBayes.scala:87-154)"""

    priors: dict[str, float]  # label → log prior
    likelihoods: dict[str, list[dict[str, float]]]  # label → per-position {value: log p}

    def log_score(
        self,
        point: LabeledPoint,
        default_likelihood: Callable[[Sequence[float]], float] = lambda ls: -math.inf,
    ) -> Optional[float]:
        """Log score of (features, label); None when label unseen (:101-113)."""
        if point.label not in self.priors:
            return None
        return self._log_score_internal(point.label, point.features, default_likelihood)

    def _log_score_internal(self, label, features, default_likelihood) -> float:
        feature_likelihoods = self.likelihoods[label]
        score = self.priors[label]
        for position, value in enumerate(features):
            table = feature_likelihoods[position] if position < len(feature_likelihoods) else {}
            if value in table:
                score += table[value]
            else:
                score += default_likelihood(list(table.values()))
        return score

    def predict(self, features: Sequence[str]) -> str:
        """Label with the highest log score (:140-152); unseen feature values
        score -inf, vetoing the label (the reference predict's default)."""
        best_label, best_score = None, None
        for label in self.priors:
            score = self._log_score_internal(
                label, features, lambda ls: -math.inf
            )
            if best_score is None or score > best_score:
                best_label, best_score = label, score
        assert best_label is not None
        return best_label


class CategoricalNaiveBayes:
    @staticmethod
    def train(points: Iterable[LabeledPoint]) -> CategoricalNaiveBayesModel:
        """(CategoricalNaiveBayes.scala:29-85)"""
        points = list(points)
        if not points:
            raise ValueError("no labeled points")
        n = len(points)
        label_counts = Counter(p.label for p in points)
        priors = {lb: math.log(c / n) for lb, c in label_counts.items()}
        likelihoods: dict[str, list[dict[str, float]]] = {}
        for label, count in label_counts.items():
            positions: defaultdict[int, Counter] = defaultdict(Counter)
            for p in points:
                if p.label == label:
                    for i, v in enumerate(p.features):
                        positions[i][v] += 1
            n_pos = max(positions) + 1 if positions else 0
            likelihoods[label] = [
                {v: math.log(c / count) for v, c in positions[i].items()}
                for i in range(n_pos)
            ]
        return CategoricalNaiveBayesModel(priors, likelihoods)
