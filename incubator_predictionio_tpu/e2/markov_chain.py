"""Markov chain over state-transition tallies.

Behavioral parity with the reference (e2/.../engine/MarkovChain.scala:32-86):
``train`` keeps each state's top-N outgoing transitions normalized by the
state's total tally; ``predict`` propagates a current-state probability
vector one step.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable

import numpy as np


@dataclasses.dataclass
class MarkovChainModel:
    """(MarkovChain.scala:62-86): sparse transition rows, top-N per state."""

    n_states: int
    n: int
    # state → (target indices ascending, probabilities)
    rows: dict[int, tuple[np.ndarray, np.ndarray]]

    def predict(self, current_state: Iterable[float]) -> np.ndarray:
        current = np.asarray(list(current_state), np.float64)
        out = np.zeros(self.n_states, np.float64)
        for i, (idx, probs) in self.rows.items():
            out[idx] += probs * current[i]
        return out

    def transition_matrix(self) -> np.ndarray:
        m = np.zeros((self.n_states, self.n_states), np.float64)
        for i, (idx, probs) in self.rows.items():
            m[i, idx] = probs
        return m


class MarkovChain:
    @staticmethod
    def train(entries: Iterable[tuple[int, int, float]], n_states: int,
              top_n: int) -> MarkovChainModel:
        """``entries``: (from_state, to_state, tally) triples — the
        CoordinateMatrix entries of the reference (MarkovChain.scala:32)."""
        by_row: dict[int, dict[int, float]] = {}
        for i, j, value in entries:
            by_row.setdefault(i, {})
            by_row[i][j] = by_row[i].get(j, 0.0) + value
        rows = {}
        for i, targets in by_row.items():
            total = sum(targets.values())
            top = sorted(targets.items(), key=lambda t: -t[1])[:top_n]
            top.sort(key=lambda t: t[0])  # indices ascending (SparseVector form)
            idx = np.asarray([j for j, _ in top], np.int64)
            probs = np.asarray([v / total for _, v in top], np.float64)
            rows[i] = (idx, probs)
        return MarkovChainModel(n_states=n_states, n=top_n, rows=rows)
