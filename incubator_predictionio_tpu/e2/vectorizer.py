"""Binary one-hot vectorizer over (property, value) pairs.

Behavioral parity with the reference (e2/.../engine/BinaryVectorizer.scala:26-69):
a fixed (property, value) → column index map; ``to_binary`` sets 1.0 for each
known pair. Output is a dense numpy vector (feature counts here are
metadata-sized; the model layer re-shards as needed).
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

import numpy as np


class BinaryVectorizer:
    def __init__(self, property_map: Mapping[tuple[str, str], int]):
        self.property_map = dict(property_map)
        self.num_features = len(self.property_map)
        self.properties = [
            pair for pair, _ in sorted(self.property_map.items(), key=lambda t: t[1])
        ]

    def __repr__(self) -> str:  # pragma: no cover
        pairs = ",".join(f"({p}, {v})" for p, v in self.properties)
        return f"BinaryVectorizer({self.num_features}): {pairs}"

    def to_binary(self, pairs: Sequence[tuple[str, str]]) -> np.ndarray:
        vec = np.zeros(self.num_features, np.float32)
        for pair in pairs:
            idx = self.property_map.get(pair)
            if idx is not None:
                vec[idx] = 1.0
        return vec

    # -- constructors (BinaryVectorizer.scala:47-68) ----------------------
    @staticmethod
    def from_maps(maps: Iterable[Mapping[str, str]],
                  properties: set[str]) -> "BinaryVectorizer":
        """Distinct (property, value) pairs restricted to ``properties``,
        indexed in first-seen order."""
        seen: dict[tuple[str, str], int] = {}
        for m in maps:
            for k, v in m.items():
                if k in properties and (k, v) not in seen:
                    seen[(k, v)] = len(seen)
        return BinaryVectorizer(seen)

    @staticmethod
    def from_pairs(pairs: Sequence[tuple[str, str]]) -> "BinaryVectorizer":
        return BinaryVectorizer({p: i for i, p in enumerate(pairs)})
