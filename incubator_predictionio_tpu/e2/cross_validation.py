"""k-fold cross-validation splitting.

Behavioral parity with the reference
(e2/.../evaluation/CrossValidation.scala:36-73 ``splitData``): fold membership
by index mod k; returns ``[(TD, EI, [(Q, A)])]`` ready for ``read_eval``.
"""

from __future__ import annotations

from typing import Callable, Iterable, Sequence, TypeVar

D = TypeVar("D")
TD = TypeVar("TD")
EI = TypeVar("EI")
Q = TypeVar("Q")
A = TypeVar("A")


def k_fold_split(
    eval_k: int,
    dataset: Iterable[D],
    evaluator_info: EI,
    training_data_creator: Callable[[Sequence[D]], TD],
    query_creator: Callable[[D], Q],
    actual_creator: Callable[[D], A],
) -> list[tuple[TD, EI, list[tuple[Q, A]]]]:
    points = list(dataset)
    folds = []
    for fold_idx in range(eval_k):
        training = [p for i, p in enumerate(points) if i % eval_k != fold_idx]
        testing = [p for i, p in enumerate(points) if i % eval_k == fold_idx]
        folds.append((
            training_data_creator(training),
            evaluator_info,
            [(query_creator(d), actual_creator(d)) for d in testing],
        ))
    return folds
