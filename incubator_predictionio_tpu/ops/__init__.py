"""Hand-written TPU kernels (Pallas) for the framework's hot ops.

Everything here has a pure-XLA fallback; kernels engage on TPU (or in Pallas
interpret mode for CPU tests)."""
