"""Small-head causal attention — a Pallas TPU kernel for the shapes the
sequential recommender actually runs.

The stock flash-attention kernel tiles for LONG sequences: its grid is one
program per (batch, head) and it pays per-program pipeline overhead that
dwarfs the arithmetic when heads are small (d_head 64) and L fits VMEM
whole. Measured on the benched config (B 64, H 8, L 512, DH 64, v5e):
attention was 44 of the 84 ms step — more than half the step on <3% of its
FLOPs (identity-attention A/B: MFU 0.55 with attention removed).

This kernel instead processes ONE BATCH ROW per program — all heads, the
full sequence — entirely in VMEM:

- grid ``(B,)``; block [1, H, L, D] for q/k/v/o (~0.5 MB each in bf16);
- per head: scores ``[L, L]`` fp32 live only in VMEM/registers (1 MB),
  causal mask via iota, rowwise softmax, then ``p @ v`` back on the MXU;
- backward recomputes scores per head (nothing but q/k/v saved) and emits
  dq/dk/dv in one kernel — same grid, same residency.

Constraint: ``H · L · D`` and the per-head ``[L, L]`` score block must fit
VMEM (~16 MB/core) — enforced by :func:`fits_small_head_kernel`; callers
fall back to the stock flash kernel / materializing reference otherwise
(parallel/ring.py picks the path).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def fits_small_head_kernel(b: int, l: int, h: int, d: int) -> bool:
    """Shapes this kernel beats the stock flash kernel on: whole-sequence
    VMEM residency for one batch row, lane-aligned tiles."""
    if l % 128 or d % 64 or l < 128:
        return False
    # budget the BACKWARD kernel (the bigger one): 7 [1, H, L, D] bf16
    # blocks (q/k/v/do/dq/dk/dv) plus ~4 live [L, L] fp32 per-head
    # intermediates (s/p/dp/ds) — a forward-only budget admits shapes whose
    # first training step then dies in Mosaic VMEM allocation
    vmem_bytes = 7 * h * l * d * 2 + 4 * l * l * 4
    return vmem_bytes <= 12 * 1024 * 1024  # leave headroom of the ~16 MB


def _causal_mask(l: int):
    row = jax.lax.broadcasted_iota(jnp.int32, (l, l), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (l, l), 1)
    return jnp.where(row >= col, 0.0, -jnp.inf).astype(jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, h: int, scale: float):
    mask = _causal_mask(q_ref.shape[2])
    for i in range(h):
        q = q_ref[0, i].astype(jnp.bfloat16)          # [L, D]
        k = k_ref[0, i].astype(jnp.bfloat16)
        v = v_ref[0, i].astype(jnp.bfloat16)
        s = jax.lax.dot_general(                      # [L, L] fp32
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale + mask
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        p = p / jnp.sum(p, axis=1, keepdims=True)
        o_ref[0, i] = jax.lax.dot(
            p.astype(jnp.bfloat16), v,
            preferred_element_type=jnp.float32,
        ).astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref,
                *, h: int, scale: float):
    mask = _causal_mask(q_ref.shape[2])
    for i in range(h):
        q = q_ref[0, i].astype(jnp.bfloat16)
        k = k_ref[0, i].astype(jnp.bfloat16)
        v = v_ref[0, i].astype(jnp.bfloat16)
        do = do_ref[0, i].astype(jnp.bfloat16)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale + mask
        m = jnp.max(s, axis=1, keepdims=True)
        p = jnp.exp(s - m)
        p = p / jnp.sum(p, axis=1, keepdims=True)     # [L, L] fp32
        p_bf = p.astype(jnp.bfloat16)
        dv_ref[0, i] = jax.lax.dot_general(           # pᵀ @ do
            p_bf, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(                     # do @ vᵀ [L, L]
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - jnp.sum(dp * p, axis=1, keepdims=True))
        ds_bf = (ds * scale).astype(jnp.bfloat16)
        dq_ref[0, i] = jax.lax.dot(
            ds_bf, k, preferred_element_type=jnp.float32,
        ).astype(dq_ref.dtype)
        dk_ref[0, i] = jax.lax.dot_general(           # dsᵀ @ q
            ds_bf, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dk_ref.dtype)


def _block_specs(b: int, h: int, l: int, d: int, n: int):
    spec = pl.BlockSpec((1, h, l, d), lambda i: (i, 0, 0, 0),
                        memory_space=pltpu.VMEM)
    return [spec] * n


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def causal_mha_small_head(q, k, v, interpret=False):
    """Causal multi-head attention, [B, H, L, D] bf16 in → bf16 out."""
    return _mha_fwd(q, k, v, interpret)[0]


def _mha_fwd(q, k, v, interpret):
    b, h, l, d = q.shape
    scale = 1.0 / math.sqrt(d)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, h=h, scale=scale),
        grid=(b,),
        in_specs=_block_specs(b, h, l, d, 3),
        out_specs=_block_specs(b, h, l, d, 1)[0],
        out_shape=jax.ShapeDtypeStruct((b, h, l, d), q.dtype),
        interpret=interpret,
    )(q, k, v)
    return out, (q, k, v)


def _mha_bwd(interpret, res, do):
    q, k, v = res
    b, h, l, d = q.shape
    scale = 1.0 / math.sqrt(d)
    shape = jax.ShapeDtypeStruct((b, h, l, d), q.dtype)
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_kernel, h=h, scale=scale),
        grid=(b,),
        in_specs=_block_specs(b, h, l, d, 4),
        out_specs=_block_specs(b, h, l, d, 3),
        out_shape=(shape, shape, shape),
        interpret=interpret,
    )(q, k, v, do.astype(q.dtype))
    return dq, dk, dv


causal_mha_small_head.defvjp(_mha_fwd, _mha_bwd)
