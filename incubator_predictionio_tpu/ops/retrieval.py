"""Quantized full-catalog retrieval scoring — a Pallas TPU kernel.

The recommendation serving hot path scores a user batch against the whole
item catalog: ``scores[B, N] = (q[B, D] @ items[N, D]ᵀ) * scale + bias + mask``
then top-k. At large N the item table dominates HBM traffic, so the catalog
is stored **int8 row-quantized** (4× smaller than fp32) and dequantization is
fused into the matmul inside VMEM: each grid step streams one item block
HBM→VMEM, upcasts to bf16, hits the MXU against the (resident) query block,
and applies scale/bias/mask on the VPU — the [B, N] score matrix is the only
fp32 HBM write.

Fallback: the same math in plain jnp (CPU tests run the kernel in interpret
mode as the correctness oracle of the *kernel*, and the jnp path serves
non-TPU deployments).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ITEM_BLOCK = 512  # catalog rows per grid step (int8 [512, D] ≤ 128KB for D≤256)

#: Widest rank for which int8×int8 products summed over a row fit a float32
#: mantissa EXACTLY: every partial product is ≤ 127² = 16129, so a D-dim dot
#: is ≤ 127²·D < 2²⁴ for D ≤ 1040 — f32 BLAS over the int8-valued operands
#: therefore computes the int32 accumulation bit-exactly (every intermediate
#: sum is an integer below the mantissa limit, associativity-free). This is
#: what lets the CPU host path share the TPU kernel's int8×int8→int32
#: contract without an int8 GEMM in numpy.
INT8_EXACT_MAX_RANK = (1 << 24) // (127 * 127)


def quantize_rows(items: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric per-row int8 quantization: returns (int8 rows, fp32 scales)."""
    amax = np.abs(items).max(axis=1, keepdims=True)
    scale = (amax / 127.0 + 1e-12).astype(np.float32)
    q = np.clip(np.round(items / scale), -127, 127).astype(np.int8)
    return q, scale[:, 0]


@jax.jit
def quantize_catalog_device(
    item_emb: jax.Array, item_bias: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Device-side :func:`quantize_rows` + :func:`pad_catalog` in one jitted
    program — the deploy path for device-resident towers never round-trips
    the catalog through host numpy. Returns ``(items_q, scales, bias, mask)``
    padded to the :data:`ITEM_BLOCK` multiple (padding masked with -inf)."""
    n, _ = item_emb.shape
    amax = jnp.abs(item_emb).max(axis=1, keepdims=True)
    scale = (amax / 127.0 + 1e-12).astype(jnp.float32)
    q = jnp.clip(jnp.round(item_emb / scale), -127, 127).astype(jnp.int8)
    pad = (-n) % ITEM_BLOCK
    return (
        jnp.pad(q, ((0, pad), (0, 0))),
        jnp.pad(scale[:, 0], (0, pad)),
        jnp.pad(item_bias.astype(jnp.float32), (0, pad)),
        jnp.pad(jnp.zeros(n, jnp.float32), (0, pad),
                constant_values=-jnp.inf),
    )


def _score_kernel(q_ref, items_ref, scale_ref, bias_ref, mask_ref, out_ref):
    q = q_ref[:].astype(jnp.bfloat16)                    # [B, D] resident
    block = items_ref[:].astype(jnp.bfloat16)            # [NB, D] int8→bf16
    scores = jax.lax.dot_general(
        q, block, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # [B, NB] on the MXU
    scores = scores * scale_ref[:] + bias_ref[:] + mask_ref[:]
    out_ref[:] = scores


def _score_kernel_rowmask(q_ref, items_ref, scale_ref, bias_ref, mask_ref,
                          rowmask_ref, out_ref):
    """The rule-filtered variant: a per-row [B, NB] mask block streams in
    alongside the catalog block — each query in the batch carries its own
    business-rule filter (whitelist/blacklist/category/seen) while the
    shared [NB] mask keeps covering catalog padding."""
    q = q_ref[:].astype(jnp.bfloat16)
    block = items_ref[:].astype(jnp.bfloat16)
    scores = jax.lax.dot_general(
        q, block, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    scores = scores * scale_ref[:] + bias_ref[:] + mask_ref[:] + rowmask_ref[:]
    out_ref[:] = scores


@functools.partial(jax.jit, static_argnames=("interpret",))
def score_catalog_quantized(q, items_q, scales, bias, mask, row_mask=None, *,
                            interpret=False):
    """q [B, D] fp32; items_q [N, D] int8; scales/bias/mask [N] fp32;
    optional row_mask [B, N] fp32 (per-query -inf filters) → [B, N]."""
    b, d = q.shape
    n = items_q.shape[0]
    if n % ITEM_BLOCK:
        raise ValueError(f"catalog rows ({n}) must be padded to {ITEM_BLOCK}")
    if row_mask is not None and row_mask.shape != (b, n):
        raise ValueError(
            f"row_mask shape {row_mask.shape} != (batch, catalog) {(b, n)}")
    grid = (n // ITEM_BLOCK,)
    row = lambda j: (j, 0)
    col = lambda j: (0, j)
    in_specs = [
        pl.BlockSpec((b, d), lambda j: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((ITEM_BLOCK, d), row, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, ITEM_BLOCK), col, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, ITEM_BLOCK), col, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, ITEM_BLOCK), col, memory_space=pltpu.VMEM),
    ]
    args = [q, items_q, scales.reshape(1, n), bias.reshape(1, n),
            mask.reshape(1, n)]
    kernel = _score_kernel
    if row_mask is not None:
        in_specs.append(
            pl.BlockSpec((b, ITEM_BLOCK), col, memory_space=pltpu.VMEM))
        args.append(row_mask)
        kernel = _score_kernel_rowmask
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, ITEM_BLOCK), col,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.float32),
        interpret=interpret,
    )(*args)


def score_catalog_reference(q, items_q, scales, bias, mask, row_mask=None):
    """Same math in plain jnp (the non-TPU serving path + test oracle)."""
    deq = items_q.astype(jnp.bfloat16)
    scores = jax.lax.dot_general(
        q.astype(jnp.bfloat16), deq, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    scores = scores * scales[None, :] + bias[None, :] + mask[None, :]
    if row_mask is not None:
        scores = scores + row_mask
    return scores


def int8_matmul_exact(a_q: np.ndarray, b_q: np.ndarray) -> np.ndarray:
    """Exact ``a_q [M, D] int8 @ b_q [N, D] int8 ᵀ → [M, N]`` accumulation on
    host, returned as f32 holding exact integer values.

    For D ≤ :data:`INT8_EXACT_MAX_RANK` the f32 BLAS GEMM over the upcast
    operands IS the int32 result (see the constant's docstring) — and being
    exact integers, the result is identical no matter how BLAS blocks the
    reduction, so batched (GEMM) and per-query (GEMV) reranks score
    bit-identically. Wider ranks fall back to f64 (exact to 2⁵³)."""
    d = a_q.shape[1]
    acc_dtype = np.float32 if d <= INT8_EXACT_MAX_RANK else np.float64
    out = a_q.astype(acc_dtype) @ b_q.astype(acc_dtype).T
    return out.astype(np.float32, copy=False)


# -- int8 coarse stage (centroid scoring) ------------------------------------
#
# The IVF coarse stage scores each query against the bias-augmented centroid
# table (serving/ann.py). With the catalog already int8 row-quantized, the
# centroid embeddings quantize the same way (quantize_rows per-row scales);
# the mean-member-bias column stays fp32 and is added AFTER the one rescale,
# so bias precision never rides an int8 scale. The kernel runs int8×int8 on
# the MXU with an int32 accumulator — the true quantized-retrieval contract —
# and the host/reference paths reproduce it exactly via int8_matmul_exact.


def _coarse_kernel(q_ref, cent_ref, qs_ref, cs_ref, cb_ref, out_ref):
    q = q_ref[:]                                         # [B, D] int8 resident
    block = cent_ref[:]                                  # [CB, D] int8
    acc = jax.lax.dot_general(
        q, block, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )                                                    # [B, CB] int32 MXU
    scores = acc.astype(jnp.float32) * (qs_ref[:] * cs_ref[:]) + cb_ref[:]
    out_ref[:] = scores


@functools.partial(jax.jit, static_argnames=("interpret",))
def score_centroids_quantized(q_q, q_scales, cent_q, cent_scales, cent_bias,
                              *, interpret=False):
    """q_q [B, D] int8; q_scales [B] f32; cent_q [C, D] int8;
    cent_scales/cent_bias [C] f32 → [B, C] f32 coarse scores.

    ``C`` must be padded to the :data:`ITEM_BLOCK` multiple
    (:func:`pad_centroids` — padding carries -inf bias so padded centroids
    are never probed)."""
    b, d = q_q.shape
    c = cent_q.shape[0]
    if c % ITEM_BLOCK:
        raise ValueError(
            f"centroid rows ({c}) must be padded to {ITEM_BLOCK}")
    grid = (c // ITEM_BLOCK,)
    col = lambda j: (0, j)
    return pl.pallas_call(
        _coarse_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((b, d), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((ITEM_BLOCK, d), lambda j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((b, 1), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ITEM_BLOCK), col, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ITEM_BLOCK), col, memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, ITEM_BLOCK), col,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, c), jnp.float32),
        interpret=interpret,
    )(q_q, cent_q, q_scales.reshape(b, 1), cent_scales.reshape(1, c),
      cent_bias.reshape(1, c))


def score_centroids_reference(q_q, q_scales, cent_q, cent_scales, cent_bias):
    """Same int8×int8→int32 math in plain jnp (non-TPU path + test oracle)."""
    acc = jax.lax.dot_general(
        q_q, cent_q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (acc.astype(jnp.float32)
            * (q_scales[:, None] * cent_scales[None, :])
            + cent_bias[None, :])


def pad_centroids(cent_q: np.ndarray, cent_scales: np.ndarray,
                  cent_bias: np.ndarray, block: int = ITEM_BLOCK):
    """Pad the quantized centroid table to the kernel block multiple.
    Padded rows carry zero embeddings/scales and **-inf bias**, so they can
    never win a probe slot."""
    c = cent_q.shape[0]
    pad = (-c) % block
    if not pad:
        return cent_q, cent_scales, cent_bias
    return (
        np.concatenate([cent_q, np.zeros((pad, cent_q.shape[1]), np.int8)]),
        np.concatenate([cent_scales, np.zeros(pad, np.float32)]),
        np.concatenate([cent_bias, np.full(pad, -np.inf, np.float32)]),
    )


def pad_catalog(items_q: np.ndarray, *vectors: np.ndarray,
                block: int = ITEM_BLOCK):
    """Pad catalog rows to the block multiple; padded mask rows get -inf."""
    n = items_q.shape[0]
    n_pad = ((n + block - 1) // block) * block
    if n_pad == n:
        return (items_q, *vectors)
    pad = n_pad - n
    out = [np.concatenate([items_q, np.zeros((pad, items_q.shape[1]), items_q.dtype)])]
    for i, v in enumerate(vectors):
        fill = -np.inf if i == len(vectors) - 1 else 0.0  # last vector = mask
        out.append(np.concatenate([v, np.full(pad, fill, v.dtype)]))
    return tuple(out)
