"""Fused gather→adam→scatter for sparse touched-row updates.

The streaming fold (streaming/trainer.py) updates only the embedding rows a
micro-batch names. The reference path pays three passes per touched-row
batch — a per-key row gather, a per-key adam step, a per-key scatter back
into the working state. This module fuses them:

- :func:`fused_adam_rows` — the host numpy engine: ONE stacked gather, one
  vectorized adam over the ``[R, D]`` stack, one scatter. The math is the
  per-row ``DeltaTrainer._adam`` / ``utils/optim.adam_apply`` fp32 recipe
  reproduced **bit-for-bit**: every op is elementwise IEEE f32 in the same
  order, and the per-row bias corrections are computed with the same scalar
  ``b1 ** t`` double pow (:func:`adam_bias_corrections`), so fused and
  three-pass folds produce identical bytes (tests/test_streaming.py pins
  this).
- :func:`fused_gather_adam_scatter` — the device engine: gather, adam and
  scatter-back compiled into ONE dispatch (a Pallas kernel runs the adam
  core on TPU; plain jnp elsewhere). XLA may contract multiply-add into
  FMA, so the compiled engines are pinned to fp32 roundoff of the host
  pass rather than bytes — pick one engine per stream and replay
  determinism holds.

Per-row step counts ride along unchanged: a row's ``t`` advances only when
the row trains, exactly like the sparse-adam convention the trainer keeps.
"""

from __future__ import annotations

import functools

import numpy as np

ADAM_B1 = 0.9
ADAM_B2 = 0.999
ADAM_EPS = 1e-8

#: Rows per grid step for the Pallas adam kernel (f32 [256, D+1] blocks).
ROW_BLOCK = 256


def adam_bias_corrections(
    t: np.ndarray, b1: float = ADAM_B1, b2: float = ADAM_B2,
) -> tuple[np.ndarray, np.ndarray]:
    """Per-row ``(1 - b1**t, 1 - b2**t)`` as f32, computed with the scalar
    double ``**`` the per-row reference uses — one pow per UNIQUE step count
    (a fold batch holds few distinct ``t`` values), so the fused path cannot
    drift from the reference by a libm-vs-ufunc pow difference."""
    t = np.asarray(t, np.int64)
    bc1 = np.empty(len(t), np.float32)
    bc2 = np.empty(len(t), np.float32)
    for tv in np.unique(t):
        sel = t == tv
        bc1[sel] = np.float32(1.0 - b1 ** int(tv))
        bc2[sel] = np.float32(1.0 - b2 ** int(tv))
    return bc1, bc2


def fused_adam_rows(
    rows: np.ndarray,        # [R, D] f32 current row values (will not mutate)
    m: np.ndarray,           # [R, D] f32 first moments
    v: np.ndarray,           # [R, D] f32 second moments
    g: np.ndarray,           # [R, D] f32 accumulated gradients
    t: np.ndarray,           # [R] int step counts AFTER this step (t >= 1)
    lr: float,
    b1: float = ADAM_B1, b2: float = ADAM_B2, eps: float = ADAM_EPS,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One vectorized adam step over a stacked touched-row batch. Returns
    new ``(rows, m, v)``; op-for-op the ``DeltaTrainer._adam`` fp32 math."""
    bc1, bc2 = adam_bias_corrections(t, b1, b2)
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * (g * g)
    rows = rows - lr * (m / bc1[:, None]) / (
        np.sqrt(v / bc2[:, None]) + eps)
    return rows, m, v


# -- device engine -----------------------------------------------------------


def _adam_rows_kernel(rows_ref, m_ref, v_ref, g_ref, bc1_ref, bc2_ref,
                      out_rows, out_m, out_v, *, lr, b1, b2, eps):
    import jax.numpy as jnp

    g = g_ref[:]
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * (g * g)
    out_m[:] = m
    out_v[:] = v
    out_rows[:] = rows_ref[:] - lr * (m / bc1_ref[:]) / (
        jnp.sqrt(v / bc2_ref[:]) + eps)


def _pallas_adam_rows(rows, m, v, g, bc1, bc2, lr, b1, b2, eps, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r, d = rows.shape
    if r % ROW_BLOCK:
        pad = (-r) % ROW_BLOCK
        rows, m, v, g = (jnp.pad(a, ((0, pad), (0, 0)))
                         for a in (rows, m, v, g))
        # padded bc rows are 1.0 — the padded lanes divide by one, not zero
        bc1 = jnp.pad(bc1, (0, pad), constant_values=1.0)
        bc2 = jnp.pad(bc2, (0, pad), constant_values=1.0)
    rp = rows.shape[0]
    grid = (rp // ROW_BLOCK,)
    row = lambda j: (j, 0)
    mat = pl.BlockSpec((ROW_BLOCK, d), row, memory_space=pltpu.VMEM)
    col = pl.BlockSpec((ROW_BLOCK, 1), row, memory_space=pltpu.VMEM)
    kernel = functools.partial(
        _adam_rows_kernel, lr=lr, b1=b1, b2=b2, eps=eps)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[mat, mat, mat, mat, col, col],
        out_specs=(mat, mat, mat),
        out_shape=tuple(
            jax.ShapeDtypeStruct((rp, d), jnp.float32) for _ in range(3)),
        interpret=interpret,
    )(rows, m, v, g, bc1.reshape(rp, 1), bc2.reshape(rp, 1))
    return tuple(a[:r] for a in out)


#: Lazily-built jitted adam-core executable over padded row stacks — built
#: on first use so importing this module (the host fold does) never
#: imports jax.
_ROWS_JIT = None


def _adam_rows_jit():
    global _ROWS_JIT
    if _ROWS_JIT is not None:
        return _ROWS_JIT
    import jax

    def step(rows, m, v, g, bc1, bc2, *, lr, b1, b2, eps, interpret):
        on_tpu = jax.devices()[0].platform == "tpu"
        if on_tpu or interpret:
            return _pallas_adam_rows(
                rows, m, v, g, bc1, bc2, lr, b1, b2, eps, interpret)
        import jax.numpy as jnp

        m = b1 * m + (1.0 - b1) * g
        v = b2 * v + (1.0 - b2) * (g * g)
        rows = rows - lr * (m / bc1[:, None]) / (
            jnp.sqrt(v / bc2[:, None]) + eps)
        return rows, m, v

    _ROWS_JIT = jax.jit(
        step, static_argnames=("lr", "b1", "b2", "eps", "interpret"))
    return _ROWS_JIT


def fused_adam_rows_device(
    rows: np.ndarray, m: np.ndarray, v: np.ndarray, g: np.ndarray,
    t: np.ndarray, lr: float,
    b1: float = ADAM_B1, b2: float = ADAM_B2, eps: float = ADAM_EPS,
    interpret: bool = False,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The one-dispatch device twin of :func:`fused_adam_rows`: the whole
    touched-row micro-batch runs as a single compiled adam step (Pallas
    kernel on TPU). Row counts are padded to :data:`ROW_BLOCK` buckets so a
    stream of varying batch sizes shares a bounded executable set; padded
    rows carry zero gradients and unit bias corrections, and are sliced off
    before return. Bias corrections come from :func:`adam_bias_corrections`
    — the bitwise contract is the same as the host path's."""
    import jax

    r, d = rows.shape
    bc1, bc2 = adam_bias_corrections(t, b1, b2)
    pad = (-r) % ROW_BLOCK
    if pad:
        z = np.zeros((pad, d), np.float32)
        rows, m, v, g = (np.concatenate([a, z]) for a in (rows, m, v, g))
        bc1 = np.concatenate([bc1, np.ones(pad, np.float32)])
        bc2 = np.concatenate([bc2, np.ones(pad, np.float32)])
    out = _adam_rows_jit()(
        rows, m, v, g, bc1, bc2,
        lr=float(lr), b1=float(b1), b2=float(b2), eps=float(eps),
        interpret=interpret)
    rows2, m2, v2 = jax.device_get(out)
    return rows2[:r], m2[:r], v2[:r]


#: The lazily-built jitted gather→adam→scatter executable — built on first
#: use so importing this module (the host fold does) never imports jax.
_FUSED_JIT = None


def _fused_fn():
    global _FUSED_JIT
    if _FUSED_JIT is not None:
        return _FUSED_JIT
    import jax

    def fused(table, m_tab, v_tab, idx, g, bc1, bc2,
              *, lr, b1, b2, eps, interpret):
        rows = table[idx]
        m = m_tab[idx]
        v = v_tab[idx]
        on_tpu = jax.devices()[0].platform == "tpu"
        if on_tpu or interpret:
            rows, m, v = _pallas_adam_rows(
                rows, m, v, g, bc1, bc2, lr, b1, b2, eps, interpret)
        else:
            import jax.numpy as jnp

            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * (g * g)
            rows = rows - lr * (m / bc1[:, None]) / (
                jnp.sqrt(v / bc2[:, None]) + eps)
        return (table.at[idx].set(rows), m_tab.at[idx].set(m),
                v_tab.at[idx].set(v))

    _FUSED_JIT = jax.jit(
        fused, static_argnames=("lr", "b1", "b2", "eps", "interpret"))
    return _FUSED_JIT


def fused_gather_adam_scatter(
    table, m_tab, v_tab, idx, g, bc1, bc2,
    *, lr, b1=ADAM_B1, b2=ADAM_B2, eps=ADAM_EPS, interpret=False,
):
    """ONE dispatch for a touched-row batch against device-resident tables:
    gather ``table/m/v`` rows at ``idx``, run the adam core (Pallas on TPU,
    jnp elsewhere), scatter the results back. Returns new
    ``(table, m_tab, v_tab)`` — functional, the inputs are never mutated.

    ``bc1``/``bc2`` are the per-row bias corrections, precomputed host-side
    by :func:`adam_bias_corrections` so the double-precision ``b1 ** t``
    stays bit-identical to the reference path."""
    return _fused_fn()(
        table, m_tab, v_tab, idx, g, bc1, bc2,
        lr=lr, b1=b1, b2=b2, eps=eps, interpret=interpret)
