"""Chunked softmax cross-entropy — the LM-head loss without the logits wall.

The sequential recommender's loss is next-item cross-entropy over the item
vocabulary. The naive form materializes fp32 logits ``[B, L, V]`` (1.3 GB at
the benched shapes) plus log-softmax temporaries and a same-sized dlogits in
the backward — several GB of HBM traffic and the peak-memory wall for long
sequences (VERDICT r3 weak #4).

:func:`chunked_xent_sum` computes the same weighted loss **per token chunk**
under a ``custom_vjp``:

- forward: for each chunk of tokens, logits ``[C, V]`` come off the MXU in
  bfloat16 with fp32 accumulation, reduce to (logsumexp − correct-logit)
  immediately, and are DISCARDED — nothing of size ``[tokens, V]`` survives
  the chunk, in any dtype;
- backward: logits are recomputed per chunk (one extra head matmul — cheaper
  than round-tripping stored logits through HBM) and fold straight into
  ``dh`` and ``dW``.

Peak transient memory drops from O(tokens × V) fp32 to O(chunk × V), and
total HBM traffic roughly halves. Gradients match
``optax.softmax_cross_entropy_with_integer_labels`` to fp32-accumulation
tolerance (tests/test_sequential_template.py parity test).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


#: Above this many logits elements (tokens × vocab) the loss switches from
#: full bf16 logits to the chunked custom-vjp path: measured on v5e, full
#: bf16 logits win while they fit (fewer, bigger MXU calls; no scan carry),
#: chunking wins when the logits matrix stops fitting comfortably in HBM.
#: At 2^29 the small path's transient peak is ~1 GB bf16 logits + ~2 GB
#: fp32 dlogits in backward — comfortable on a 16 GB chip; 2^30 would
#: double that on top of params/activations and can OOM.
CHUNKED_THRESHOLD = 1 << 29


def weighted_xent_sum(h, w_emb, targets, weights):
    """``Σ_t weights[t] · xent(h[t] @ w_embᵀ, targets[t])`` — the LM-head
    loss entry point. Never materializes fp32 logits: small problems take
    one bf16-logits pass (fp32 logsumexp), large ones the chunked
    custom-vjp (:func:`chunked_xent_sum`)."""
    s = h.shape[0]
    if s * w_emb.shape[0] <= CHUNKED_THRESHOLD:
        logits = _chunk_logits(h, w_emb).astype(jnp.bfloat16)
        lse = jax.scipy.special.logsumexp(
            logits.astype(jnp.float32), axis=-1)
        correct = jnp.take_along_axis(
            logits, targets[:, None], axis=-1)[:, 0].astype(jnp.float32)
        return jnp.sum(weights * (lse - correct))
    return chunked_xent_sum(h, w_emb, targets, weights)


def _pad_chunks(h, targets, weights, chunk):
    """Pad the token dim up to a whole number of ``chunk``-sized rows.

    Pad rows carry weight 0 (they contribute nothing to the loss or any
    cotangent) and target 0; requiring chunk | S instead would degenerate to
    chunk 1-2 for divisor-poor token counts (e.g. 2 × prime) and explode the
    scan length."""
    s, d = h.shape
    c = min(s, chunk)
    pad = (-s) % c
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)])
        targets = jnp.concatenate(
            [targets, jnp.zeros(pad, targets.dtype)])
        weights = jnp.concatenate(
            [weights, jnp.zeros(pad, weights.dtype)])
    return (h.reshape(-1, c, h.shape[-1]), targets.reshape(-1, c),
            weights.reshape(-1, c))


def _chunk_logits(h_c, w_emb):
    """[C, d] × [d, V] on the MXU: bf16 inputs, fp32 accumulation."""
    return jax.lax.dot(
        h_c.astype(jnp.bfloat16), w_emb.T.astype(jnp.bfloat16),
        precision=jax.lax.Precision.DEFAULT,
        preferred_element_type=jnp.float32,
    )


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def chunked_xent_sum(h, w_emb, targets, weights, chunk=4096):
    """``Σ_t weights[t] · xent(h[t] @ w_embᵀ, targets[t])`` without ever
    materializing the full logits matrix.

    h: [S, d] activations; w_emb: [V, d] tied embedding table;
    targets: [S] int32; weights: [S] fp32. Returns a scalar fp32 sum
    (callers divide by Σweights).
    """
    loss, _ = _xent_fwd(h, w_emb, targets, weights, chunk)
    return loss


def _xent_fwd(h, w_emb, targets, weights, chunk):
    hc, tc, wc = _pad_chunks(h, targets, weights, chunk)

    def body(acc, args):
        h_c, t_c, w_c = args
        logits = _chunk_logits(h_c, w_emb)               # [C, V] fp32
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        correct = jnp.take_along_axis(
            logits, t_c[:, None], axis=-1)[:, 0]
        return acc + jnp.sum(w_c * (lse - correct)), None

    loss, _ = jax.lax.scan(body, jnp.float32(0.0), (hc, tc, wc))
    return loss, (h, w_emb, targets, weights)


def _xent_bwd(chunk, res, g):
    h, w_emb, targets, weights = res
    s, d = h.shape
    hc, tc, wc = _pad_chunks(h, targets, weights, chunk)

    w_bf = w_emb.astype(jnp.bfloat16)
    v = w_emb.shape[0]

    def body(dw_acc, args):
        h_c, t_c, w_c = args
        logits = _chunk_logits(h_c, w_emb)               # recompute [C, V]
        m = jnp.max(logits, axis=-1, keepdims=True)
        e = jnp.exp(logits - m)
        z = jnp.sum(e, axis=-1, keepdims=True)
        p = e / z
        lse = jnp.log(z[:, 0]) + m[:, 0]
        correct = jnp.take_along_axis(logits, t_c[:, None], axis=-1)[:, 0]
        sc = w_c * g
        # dlogits = (p − onehot(t))·sc, but scatter is slow on TPU; split:
        #   dh = p·sc @ W − W[t]·sc        (matmul + gather)
        #   dW = (p·sc)ᵀ @ h − onehotᵀ·sc @ h  (two MXU matmuls, no scatter)
        p_sc = (p * sc[:, None]).astype(jnp.bfloat16)
        h_bf = h_c.astype(jnp.bfloat16)
        dh_c = jax.lax.dot(p_sc, w_bf, preferred_element_type=jnp.float32) \
            - w_emb[t_c] * sc[:, None]
        onehot = jax.nn.one_hot(t_c, v, dtype=jnp.bfloat16) \
            * sc[:, None].astype(jnp.bfloat16)
        dw_c = (
            jax.lax.dot(p_sc.T, h_bf, preferred_element_type=jnp.float32)
            - jax.lax.dot(onehot.T, h_bf, preferred_element_type=jnp.float32)
        )
        dweights_c = (lse - correct) * g  # d(loss)/d(weights[t]) = per-token CE
        return dw_acc + dw_c, (dh_c, dweights_c)

    dw, (dh, dweights) = jax.lax.scan(
        body, jnp.zeros_like(w_emb, jnp.float32), (hc, tc, wc))
    return (dh.reshape(-1, d)[:s].astype(h.dtype), dw.astype(w_emb.dtype),
            np.zeros(targets.shape, jax.dtypes.float0),
            dweights.reshape(-1)[:s].astype(weights.dtype))


chunked_xent_sum.defvjp(_xent_fwd, _xent_bwd)
