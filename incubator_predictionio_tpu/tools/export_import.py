"""`pio export` / `pio import`: events ↔ JSON-lines files.

Parity targets: tools/export/EventsToFile.scala:36-114 and
tools/imprt/FileToEvents.scala:36-112 (minus the Spark job wrapping — the
event store's sharded readers and batch inserts do the parallel work).
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from incubator_predictionio_tpu.data.event import Event, validate_event
from incubator_predictionio_tpu.data.storage.registry import Storage, get_storage

logger = logging.getLogger(__name__)


def export_events(
    app_id: int,
    output_path: str,
    channel_id: Optional[int] = None,
    storage: Optional[Storage] = None,
) -> int:
    storage = storage or get_storage()
    n = 0
    with open(output_path, "w") as f:
        for event in storage.get_events().find(app_id, channel_id):
            f.write(event.to_json() + "\n")
            n += 1
    logger.info("exported %d events from app %s to %s", n, app_id, output_path)
    return n


def import_events(
    app_id: int,
    input_path: str,
    channel_id: Optional[int] = None,
    storage: Optional[Storage] = None,
    batch_size: int = 1000,
) -> int:
    storage = storage or get_storage()
    events_store = storage.get_events()
    events_store.init(app_id, channel_id)
    n = 0
    batch: list[Event] = []
    with open(input_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            event = validate_event(Event.from_json(line))
            batch.append(event)
            if len(batch) >= batch_size:
                events_store.insert_batch(batch, app_id, channel_id)
                n += len(batch)
                batch = []
    if batch:
        events_store.insert_batch(batch, app_id, channel_id)
        n += len(batch)
    logger.info("imported %d events into app %s", n, app_id)
    return n
