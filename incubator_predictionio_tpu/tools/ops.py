"""Ops verbs: daemon supervision (start-all/stop-all) and the redeploy loop.

Parity targets:

- ``bin/pio-start-all`` / ``bin/pio-stop-all`` (reference bin/pio-start-all:1-60):
  boot the serving stack. The reference also boots external storage services
  (PGSQL/HBase/ES); this framework's builtin backends (sqlite/eventlog/memory)
  are in-process, so start-all supervises only the framework's own servers —
  event server always, dashboard/admin server opt-in.
- ``bin/pio-daemon`` (nohup + pidfile): each server runs as a detached
  subprocess with a pidfile under ``$PIO_FS_BASEDIR/pids`` and a log under
  ``$PIO_FS_BASEDIR/logs``.
- ``examples/redeploy-script/redeploy.sh``: the blessed cron retrain+redeploy
  loop — train with retries, then hot-reload the deployed engine via its
  ``POST /reload`` endpoint (the MasterActor ReloadServer analogue,
  core/.../workflow/CreateServer.scala:317-343).
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger(__name__)

_DAEMONS = ("eventserver", "dashboard", "adminserver", "storageserver")


def _base_dir() -> str:
    return os.environ.get("PIO_FS_BASEDIR", os.path.expanduser("~/.pio_store"))


def _pid_dir() -> str:
    d = os.path.join(_base_dir(), "pids")
    os.makedirs(d, exist_ok=True)
    return d


def _log_dir() -> str:
    d = os.path.join(_base_dir(), "logs")
    os.makedirs(d, exist_ok=True)
    return d


def _pid_file(name: str) -> str:
    return os.path.join(_pid_dir(), f"{name}.pid")


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return False
    return True


def _read_pid(name: str) -> Optional[int]:
    try:
        with open(_pid_file(name)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def _http_ok(url: str, timeout: float = 2.0) -> bool:
    try:
        with urllib.request.urlopen(url, timeout=timeout):
            return True
    except (urllib.error.URLError, OSError, ValueError):
        # ValueError covers http.client.InvalidURL (malformed host/port)
        return False


@dataclass
class StartAllConfig:
    ip: str = "0.0.0.0"
    event_server_port: int = 7070
    with_dashboard: bool = False
    dashboard_port: int = 9000
    with_adminserver: bool = False
    adminserver_port: int = 7071
    # shared networked store for multi-host jobs (clients use TYPE=remote)
    with_storageserver: bool = False
    storageserver_port: int = 7072
    storageserver_access_key: Optional[str] = None  # shared client secret
    stats: bool = False
    wait_secs: float = 60.0  # first-boot waits may pay a jax import


def _spawn(name: str, argv: list[str]) -> int:
    """Start one daemon: detached subprocess + pidfile + logfile."""
    log_path = os.path.join(_log_dir(), f"{name}.log")
    with open(log_path, "ab") as log:
        proc = subprocess.Popen(
            [sys.executable, "-m", "incubator_predictionio_tpu.tools.cli", *argv],
            stdout=log,
            stderr=subprocess.STDOUT,
            start_new_session=True,  # survives the parent CLI exiting
        )
    with open(_pid_file(name), "w") as f:
        f.write(str(proc.pid))
    return proc.pid


def start_all(config: StartAllConfig) -> tuple[dict[str, int], list[str]]:
    """Start the serving stack. Idempotent per daemon.

    Returns ``(started, unhealthy)``: {daemon: pid} for newly spawned daemons
    and the names among them that never answered their health check.
    """
    started: dict[str, int] = {}
    # daemons bound to a wildcard address answer on loopback; a specific
    # --ip must be health-checked at that address (IPv6 literals need brackets)
    if config.ip in ("0.0.0.0", "::"):
        health_host = "127.0.0.1"
    elif ":" in config.ip:
        health_host = f"[{config.ip}]"
    else:
        health_host = config.ip
    plan: list[tuple[str, list[str], str]] = [(
        "eventserver",
        ["eventserver", "--ip", config.ip, "--port", str(config.event_server_port)]
        + (["--stats"] if config.stats else []),
        f"http://{health_host}:{config.event_server_port}/",
    )]
    if config.with_dashboard:
        plan.append((
            "dashboard",
            ["dashboard", "--ip", config.ip, "--port", str(config.dashboard_port)],
            f"http://{health_host}:{config.dashboard_port}/",
        ))
    if config.with_adminserver:
        plan.append((
            "adminserver",
            ["adminserver", "--ip", config.ip, "--port", str(config.adminserver_port)],
            f"http://{health_host}:{config.adminserver_port}/",
        ))
    if config.with_storageserver:
        plan.append((
            "storageserver",
            ["storageserver", "--ip", config.ip,
             "--port", str(config.storageserver_port)]
            + (["--server-access-key", config.storageserver_access_key]
               if config.storageserver_access_key else []),
            f"http://{health_host}:{config.storageserver_port}/",
        ))

    health_urls: list[tuple[str, str]] = []
    for name, argv, url in plan:
        pid = _read_pid(name)
        if pid is not None and _alive(pid):
            print(f"{name} already running (pid {pid}).")
            continue
        pid = _spawn(name, argv)
        started[name] = pid
        health_urls.append((name, url))
        print(f"Started {name} (pid {pid}), log: {os.path.join(_log_dir(), name + '.log')}")

    deadline = time.monotonic() + config.wait_secs
    pending = dict(health_urls)
    while pending and time.monotonic() < deadline:
        for name, url in list(pending.items()):
            if _http_ok(url):
                print(f"{name} is up.")
                del pending[name]
        if pending:
            time.sleep(0.5)
    for name in pending:
        print(f"WARNING: {name} did not answer health check within "
              f"{config.wait_secs:.0f}s — check its log.", file=sys.stderr)
    return started, list(pending)


def stop_all(timeout: float = 10.0) -> list[str]:
    """Stop every pidfile-tracked daemon; returns the names stopped."""
    stopped = []
    for name in _DAEMONS:
        pid = _read_pid(name)
        if pid is None:
            continue
        if _alive(pid):
            os.kill(pid, signal.SIGTERM)
            deadline = time.monotonic() + timeout
            while _alive(pid) and time.monotonic() < deadline:
                time.sleep(0.1)
            if _alive(pid):
                os.kill(pid, signal.SIGKILL)
            print(f"Stopped {name} (pid {pid}).")
            stopped.append(name)
        try:
            os.remove(_pid_file(name))
        except OSError:
            pass
    if not stopped:
        print("No running daemons found.")
    return stopped


# ---------------------------------------------------------------------------
# redeploy loop (examples/redeploy-script/redeploy.sh)
# ---------------------------------------------------------------------------

@dataclass
class RedeployConfig:
    engine_variant: str = "engine.json"
    batch: str = ""
    retries: int = 3
    retry_wait_secs: float = 30.0
    # where the deployed engine server answers /reload; None skips the reload
    server_url: Optional[str] = "http://127.0.0.1:8000"
    server_access_key: Optional[str] = None
    # run forever every interval_secs when set (cron-in-process)
    interval_secs: Optional[float] = None
    mesh_axes: Optional[dict] = None


def redeploy_once(config: RedeployConfig, storage=None) -> Optional[str]:
    """One train-with-retries + hot-reload pass.

    Returns the new engine instance id, or None if every attempt failed.
    """
    from incubator_predictionio_tpu.core.workflow.create_workflow import (
        WorkflowConfig,
        create_workflow,
    )
    from incubator_predictionio_tpu.data.storage import get_storage

    storage = storage or get_storage()
    instance_id: Optional[str] = None
    for attempt in range(1, config.retries + 1):
        try:
            instance_id = create_workflow(
                WorkflowConfig(
                    engine_variant=config.engine_variant,
                    batch=config.batch or "redeploy",
                    mesh_axes=config.mesh_axes,
                ),
                storage,
            )
            break
        except Exception:  # noqa: BLE001 — retry loop must survive anything
            # full traceback, not just str(e): a silently-swallowed train
            # failure is how a cron redeploy rots unnoticed for weeks —
            # and the attempt lands in pio_jobs_attempt_failures_total
            # next to the orchestrated workers' failures
            from incubator_predictionio_tpu.jobs.job_metrics import (
                ATTEMPT_FAILURES,
            )

            ATTEMPT_FAILURES.inc()
            logger.exception("train attempt %d/%d failed", attempt,
                             config.retries)
            if attempt < config.retries:
                time.sleep(config.retry_wait_secs)
    if instance_id is None:
        print(f"Training failed after {config.retries} attempts.", file=sys.stderr)
        return None
    print(f"Training completed. Engine instance ID: {instance_id}")

    if config.server_url:
        url = config.server_url.rstrip("/") + "/reload"
        if config.server_access_key:
            url += "?" + urllib.parse.urlencode(
                {"accessKey": config.server_access_key}
            )
        try:
            req = urllib.request.Request(url, method="POST")
            with urllib.request.urlopen(req, timeout=30) as resp:
                body = resp.read().decode()
            print(f"Reloaded deployed engine: {body}")
        except (urllib.error.URLError, OSError) as e:
            print(f"WARNING: reload failed ({e}); the deployed engine keeps "
                  "serving the previous instance.", file=sys.stderr)
    return instance_id


def redeploy(config: RedeployConfig, storage=None) -> Optional[str]:
    """The LEGACY in-process loop (``pio-tpu redeploy --legacy``): run the
    redeploy pass once, or forever at ``interval_secs``. The default
    ``pio-tpu redeploy`` path is :func:`redeploy_via_jobs` — the same
    outcome through the durable orchestrator (docs/jobs.md)."""
    if config.interval_secs is None:
        return redeploy_once(config, storage)
    last = None
    while True:
        last = redeploy_once(config, storage)
        time.sleep(config.interval_secs)
    return last  # pragma: no cover — loop exits only by signal


def redeploy_via_jobs(config: RedeployConfig, storage=None) -> Optional[str]:
    """``pio-tpu redeploy`` as a thin wrapper over the control plane: submit
    a train job (interval-triggered when ``interval_secs`` is set) and run
    an in-process worker to execute it — same train→gate→/reload outcome as
    the legacy loop, but crash-safe (durable queue, checkpoint-resumed
    retries, eval-gated promotion) and visible in ``pio-tpu jobs list``.

    One-shot mode returns the new instance id (None if the job failed or
    the gate refused the candidate). Interval mode runs the trigger loop +
    worker forever, exactly like the old cron-in-process."""
    from incubator_predictionio_tpu.data.storage import get_storage
    from incubator_predictionio_tpu.jobs import (
        JobWorker,
        Orchestrator,
        TriggerConfig,
        TriggerLoop,
        WorkerConfig,
    )

    storage = storage or get_storage()
    orch = Orchestrator(storage.get_meta_data_jobs())
    worker = JobWorker(orch, storage, WorkerConfig.from_env())
    params = {
        "engine_variant": config.engine_variant,
        "batch": config.batch or "redeploy",
    }
    if config.server_url:
        params["server_url"] = config.server_url
    if config.server_access_key:
        params["server_access_key"] = config.server_access_key
    if config.mesh_axes:
        params["mesh_axes"] = config.mesh_axes
    if config.interval_secs is None:
        job = orch.submit("train", params, trigger="manual",
                          max_attempts=max(1, config.retries))
        # drain the queue until OUR job is terminal (another queued job may
        # be claimed first; keep working through them)
        while True:
            done = orch.jobs.get(job.id)
            if done is None or not done.active:
                break
            if worker.run_once() is None:
                time.sleep(0.2)
        if done is None:
            print("Redeploy job vanished from the queue.", file=sys.stderr)
            return None
        if done.status != "COMPLETED":
            tail = done.failure.splitlines()[-1] if done.failure else ""
            print(f"Redeploy job {done.status}: {tail}", file=sys.stderr)
            return None
        instance_id = done.result.get("instanceId")
        gate = (done.result.get("gate") or {}).get("verdict")
        deploy = (done.result.get("deploy") or {}).get("mode")
        print(f"Redeploy completed. Engine instance ID: {instance_id} "
              f"(gate={gate}, deploy={deploy}).")
        return instance_id
    loop = TriggerLoop(orch, storage, TriggerConfig(
        engine_variant=config.engine_variant,
        server_url=config.server_url,
        server_access_key=config.server_access_key,
        interval_sec=config.interval_secs,
        max_attempts=max(1, config.retries),
    ))
    while True:  # pragma: no cover — loop exits only by signal
        loop.run_once()
        worker.run_once()
        time.sleep(min(config.interval_secs, 5.0))
