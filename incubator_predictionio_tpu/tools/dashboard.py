"""Evaluation dashboard — lists completed evaluation instances.

Parity target: tools/dashboard/Dashboard.scala:44-160 + the twirl index page:
an HTML index of completed EvaluationInstances (newest first) with per-
instance evaluator results served as txt/html/json. TLS + key auth mirror
the reference's SSLConfiguration.scala:30 (JKS keystore → PEM pair here) and
KeyAuthentication.scala:28 (``accessKey`` query param); CORS headers mirror
CorsSupport.scala:31-81.
"""

from __future__ import annotations

import dataclasses
import hmac
import html
from typing import Optional

from aiohttp import web

from incubator_predictionio_tpu.data.storage.registry import Storage, get_storage


@dataclasses.dataclass
class DashboardConfig:
    ip: str = "127.0.0.1"
    port: int = 9000
    ssl_cert: Optional[str] = None  # PEM pair (SSLConfiguration.scala:30)
    ssl_key: Optional[str] = None
    server_access_key: Optional[str] = None  # KeyAuthentication.scala:28


_CORS_ALLOW_HEADERS = (
    "Origin, X-Requested-With, Content-Type, Accept, Accept-Encoding, "
    "Accept-Language, Host, Referer, User-Agent"
)


def cors_middleware():
    """CORS on every route (CorsSupport.scala:31-81): allow-all origin on
    responses; OPTIONS preflight answered with the allowed methods and the
    reference's header list + 20-day max-age."""

    @web.middleware
    async def cors(request: web.Request, handler):
        if request.method == "OPTIONS":
            resp = web.Response(status=200)
            resp.headers["Access-Control-Allow-Methods"] = "OPTIONS, GET"
            resp.headers["Access-Control-Allow-Headers"] = _CORS_ALLOW_HEADERS
            resp.headers["Access-Control-Max-Age"] = "1728000"
        else:
            try:
                resp = await handler(request)
            except web.HTTPException as e:
                # 404/405 are raised, not returned — CORS decorates those too
                e.headers["Access-Control-Allow-Origin"] = "*"
                raise
        resp.headers["Access-Control-Allow-Origin"] = "*"
        return resp

    return cors


def key_auth_middleware(server_access_key: Optional[str]):
    """aiohttp middleware enforcing the reference's ``accessKey`` query-param
    auth on every route (constant-time compare). No key configured = open."""

    @web.middleware
    async def check(request: web.Request, handler):
        # bytes operands: compare_digest rejects non-ASCII str (a non-ASCII
        # guess must 401, not 500)
        if server_access_key and not hmac.compare_digest(
            request.query.get("accessKey", "").encode(),
            server_access_key.encode(),
        ):
            return web.json_response({"message": "Unauthorized"}, status=401)
        return await handler(request)

    return check


class Dashboard:
    def __init__(self, config: DashboardConfig = DashboardConfig(),
                 storage: Optional[Storage] = None):
        self.config = config
        self.storage = storage or get_storage()

    async def handle_index(self, request: web.Request) -> web.Response:
        instances = self.storage.get_meta_data_evaluation_instances().get_completed()
        rows = "".join(
            "<tr>"
            f"<td>{html.escape(i.id)}</td>"
            f"<td>{html.escape(i.evaluation_class)}</td>"
            f"<td>{i.start_time.isoformat()}</td>"
            f"<td>{i.end_time.isoformat() if i.end_time else ''}</td>"
            f"<td>{html.escape(i.evaluator_results)}</td>"
            f"<td><a href='/engine_instances/{i.id}/evaluator_results.txt'>txt</a> "
            f"<a href='/engine_instances/{i.id}/evaluator_results.html'>html</a> "
            f"<a href='/engine_instances/{i.id}/evaluator_results.json'>json</a></td>"
            "</tr>"
            for i in instances
        )
        page = (
            "<html><head><title>Evaluation Dashboard</title></head><body>"
            "<h1>Completed Evaluations</h1>"
            "<table border=1><tr><th>ID</th><th>Evaluation</th><th>Started</th>"
            f"<th>Finished</th><th>Result</th><th>Details</th></tr>{rows}</table>"
            "</body></html>"
        )
        return web.Response(text=page, content_type="text/html")

    async def handle_results(self, request: web.Request) -> web.Response:
        instance_id = request.match_info["instance_id"]
        fmt = request.match_info["fmt"]
        inst = self.storage.get_meta_data_evaluation_instances().get(instance_id)
        if inst is None:
            return web.json_response({"message": "Not Found"}, status=404)
        if fmt == "txt":
            return web.Response(text=inst.evaluator_results, content_type="text/plain")
        if fmt == "html":
            return web.Response(text=inst.evaluator_results_html,
                                content_type="text/html")
        return web.Response(text=inst.evaluator_results_json,
                            content_type="application/json")

    def make_app(self) -> web.Application:
        app = web.Application(
            middlewares=[cors_middleware(),
                         key_auth_middleware(self.config.server_access_key)])
        app.router.add_get("/", self.handle_index)
        app.router.add_get(
            "/engine_instances/{instance_id}/evaluator_results.{fmt:txt|html|json}",
            self.handle_results,
        )
        return app


def serve_forever(config: DashboardConfig = DashboardConfig(),
                  storage: Optional[Storage] = None) -> None:
    from incubator_predictionio_tpu.server.event_server import _ssl_context

    web.run_app(Dashboard(config, storage).make_app(),
                host=config.ip, port=config.port,
                ssl_context=_ssl_context(config))
