"""Admin REST API (experimental in the reference).

Parity target: tools/admin/AdminAPI.scala:39-161 + CommandClient.scala:
GET ``/`` status, ``/cmd/app`` CRUD used by external dashboards.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from aiohttp import web

from incubator_predictionio_tpu.data.storage.base import AccessKey, App
from incubator_predictionio_tpu.data.storage.registry import Storage, get_storage


@dataclasses.dataclass
class AdminConfig:
    ip: str = "127.0.0.1"
    port: int = 7071
    ssl_cert: Optional[str] = None  # PEM pair (common/SSLConfiguration.scala:30)
    ssl_key: Optional[str] = None
    server_access_key: Optional[str] = None  # KeyAuthentication.scala:28


class AdminAPI:
    def __init__(self, config: AdminConfig = AdminConfig(),
                 storage: Optional[Storage] = None):
        self.config = config
        self.storage = storage or get_storage()

    async def handle_root(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "alive"})

    async def handle_app_list(self, request: web.Request) -> web.Response:
        apps = self.storage.get_meta_data_apps().get_all()
        keys = self.storage.get_meta_data_access_keys()
        return web.json_response([
            {"name": a.name, "id": a.id, "description": a.description,
             "accessKeys": [k.key for k in keys.get_by_app_id(a.id)]}
            for a in sorted(apps, key=lambda a: a.name)
        ])

    async def handle_app_new(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except Exception:  # noqa: BLE001
            return web.json_response({"message": "invalid JSON"}, status=400)
        name = body.get("name")
        if not name:
            return web.json_response({"message": "name is required"}, status=400)
        apps = self.storage.get_meta_data_apps()
        if apps.get_by_name(name) is not None:
            return web.json_response(
                {"message": f"App {name} already exists."}, status=409)
        app_id = apps.insert(App(int(body.get("id", 0)), name, body.get("description")))
        self.storage.get_events().init(app_id)
        key = self.storage.get_meta_data_access_keys().insert(AccessKey("", app_id, ()))
        return web.json_response(
            {"name": name, "id": app_id, "accessKey": key}, status=201)

    async def handle_app_delete(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        apps = self.storage.get_meta_data_apps()
        app = apps.get_by_name(name)
        if app is None:
            return web.json_response({"message": f"App {name} does not exist."},
                                     status=404)
        self.storage.get_events().remove(app.id)
        for k in self.storage.get_meta_data_access_keys().get_by_app_id(app.id):
            self.storage.get_meta_data_access_keys().delete(k.key)
        apps.delete(app.id)
        return web.json_response({"message": f"App {name} deleted."})

    async def handle_app_data_delete(self, request: web.Request) -> web.Response:
        name = request.match_info["name"]
        app = self.storage.get_meta_data_apps().get_by_name(name)
        if app is None:
            return web.json_response({"message": f"App {name} does not exist."},
                                     status=404)
        self.storage.get_events().remove(app.id)
        self.storage.get_events().init(app.id)
        return web.json_response({"message": f"Removed data of app {name}."})

    def make_app(self) -> web.Application:
        from incubator_predictionio_tpu.tools.dashboard import key_auth_middleware

        app = web.Application(
            middlewares=[key_auth_middleware(self.config.server_access_key)])
        app.router.add_get("/", self.handle_root)
        app.router.add_get("/cmd/app", self.handle_app_list)
        app.router.add_post("/cmd/app", self.handle_app_new)
        app.router.add_delete("/cmd/app/{name}", self.handle_app_delete)
        app.router.add_delete("/cmd/app/{name}/data", self.handle_app_data_delete)
        return app


def serve_forever(config: AdminConfig = AdminConfig(),
                  storage: Optional[Storage] = None) -> None:
    from incubator_predictionio_tpu.server.event_server import _ssl_context

    web.run_app(AdminAPI(config, storage).make_app(),
                host=config.ip, port=config.port,
                ssl_context=_ssl_context(config))
