"""``pio-tpu`` console — the ``pio`` CLI counterpart.

Parity target: tools/console/Console.scala:134-623 and commands/*. Verbs:

  version, status,
  app {new,list,show,delete,data-delete,channel-new,channel-delete},
  accesskey {new,list,delete},
  template {list,get} (commands/Template.scala — the gallery collapses to
  the in-package template registry; ``get`` scaffolds a ready-to-train
  engine.json),
  train, eval, deploy, undeploy, batchpredict, eventserver, storageserver,
  export, import, metrics (scrape + pretty-print any server's Prometheus
  /metrics page, docs/observability.md),
  wal (inspect/verify/--replay an event-server spill WAL directory,
  docs/resilience.md),
  shell (bin/pio-shell: interactive console with the
  storage/event-store/mesh bootstrap preloaded),
  start-all, stop-all (bin/pio-start-all / pio-stop-all: daemonize the
  serving stack with pidfiles), redeploy (examples/redeploy-script: cron-able
  train-with-retries + hot /reload of the deployed engine)

Differences by design: no ``build``/``unregister`` verbs (Python engines
need no sbt/assembly step or manifest registry — the variant JSON's
``engineFactory`` import path replaces the built jar), ``run``'s
spark-submit plumbing is unnecessary (everything runs in-process on the
mesh; ``launch`` covers multi-process), and ``upgrade`` (0.8-era HBase
data migration) has no legacy stores to migrate.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import logging
import os
import sys
from dataclasses import replace as dataclasses_replace
from typing import Optional

import incubator_predictionio_tpu as piotpu
from incubator_predictionio_tpu.data.storage.base import AccessKey, App, Channel
from incubator_predictionio_tpu.data.storage.registry import Storage, get_storage


def _out(msg: str) -> None:
    print(msg)


def _err(msg: str) -> None:
    print(msg, file=sys.stderr)


# ---------------------------------------------------------------------------
# app / accesskey commands (commands/App.scala:31-363, AccessKey.scala)
# ---------------------------------------------------------------------------

def cmd_app_new(args, storage: Storage) -> int:
    apps = storage.get_meta_data_apps()
    if apps.get_by_name(args.name) is not None:
        _err(f"App {args.name} already exists. Aborting.")
        return 1
    app_id = apps.insert(App(args.id or 0, args.name, args.description))
    if app_id is None:
        _err("Unable to create new app.")
        return 1
    storage.get_events().init(app_id)
    key = storage.get_meta_data_access_keys().insert(
        AccessKey(args.access_key or "", app_id, ())
    )
    _out("Initialized Event Store for this app ID: {}.".format(app_id))
    _out(f"Created new app:")
    _out(f"      Name: {args.name}")
    _out(f"        ID: {app_id}")
    _out(f"Access Key: {key}")
    return 0


def cmd_app_list(args, storage: Storage) -> int:
    apps = sorted(storage.get_meta_data_apps().get_all(), key=lambda a: a.name)
    keys = storage.get_meta_data_access_keys()
    _out(f"{'Name':<20} | {'ID':<4} | Access Key | Allowed Event(s)")
    for app in apps:
        for k in keys.get_by_app_id(app.id):
            events = ", ".join(k.events) if k.events else "(all)"
            _out(f"{app.name:<20} | {app.id:<4} | {k.key} | {events}")
    _out(f"Finished listing {len(apps)} app(s).")
    return 0


def cmd_app_show(args, storage: Storage) -> int:
    app = storage.get_meta_data_apps().get_by_name(args.name)
    if app is None:
        _err(f"App {args.name} does not exist. Aborting.")
        return 1
    _out(f"    App Name: {app.name}")
    _out(f"      App ID: {app.id}")
    _out(f" Description: {app.description or ''}")
    for k in storage.get_meta_data_access_keys().get_by_app_id(app.id):
        events = ", ".join(k.events) if k.events else "(all)"
        _out(f"  Access Key: {k.key} | {events}")
    for c in storage.get_meta_data_channels().get_by_app_id(app.id):
        _out(f"     Channel: {c.name} (ID {c.id})")
    return 0


def cmd_app_delete(args, storage: Storage) -> int:
    app = storage.get_meta_data_apps().get_by_name(args.name)
    if app is None:
        _err(f"App {args.name} does not exist. Aborting.")
        return 1
    if not args.force and not _confirm(f"Delete app {args.name}?"):
        return 1
    for c in storage.get_meta_data_channels().get_by_app_id(app.id):
        storage.get_events().remove(app.id, c.id)
        storage.get_meta_data_channels().delete(c.id)
    storage.get_events().remove(app.id)
    for k in storage.get_meta_data_access_keys().get_by_app_id(app.id):
        storage.get_meta_data_access_keys().delete(k.key)
    storage.get_meta_data_apps().delete(app.id)
    _out(f"Deleted app {args.name}.")
    return 0


def cmd_app_data_delete(args, storage: Storage) -> int:
    app = storage.get_meta_data_apps().get_by_name(args.name)
    if app is None:
        _err(f"App {args.name} does not exist. Aborting.")
        return 1
    if not args.force and not _confirm(f"Delete data of app {args.name}?"):
        return 1
    if args.channel:
        channels = storage.get_meta_data_channels().get_by_app_id(app.id)
        channel = next((c for c in channels if c.name == args.channel), None)
        if channel is None:
            _err(f"Channel {args.channel} does not exist.")
            return 1
        storage.get_events().remove(app.id, channel.id)
        storage.get_events().init(app.id, channel.id)
    else:
        storage.get_events().remove(app.id)
        storage.get_events().init(app.id)
    _out("Done.")
    return 0


def cmd_channel_new(args, storage: Storage) -> int:
    app = storage.get_meta_data_apps().get_by_name(args.app_name)
    if app is None:
        _err(f"App {args.app_name} does not exist. Aborting.")
        return 1
    if not Channel.is_valid_name(args.channel):
        _err(f"Unable to create new channel. The channel name {args.channel} is "
             "invalid (alphanumeric/dash, 1-16 chars).")
        return 1
    channels = storage.get_meta_data_channels()
    if any(c.name == args.channel for c in channels.get_by_app_id(app.id)):
        _err(f"Unable to create new channel. Channel {args.channel} already exists.")
        return 1
    channel_id = channels.insert(Channel(0, args.channel, app.id))
    storage.get_events().init(app.id, channel_id)
    _out(f"Channel {args.channel} (ID {channel_id}) created for app {args.app_name}.")
    return 0


def cmd_channel_delete(args, storage: Storage) -> int:
    app = storage.get_meta_data_apps().get_by_name(args.app_name)
    if app is None:
        _err(f"App {args.app_name} does not exist. Aborting.")
        return 1
    channels = storage.get_meta_data_channels()
    channel = next((c for c in channels.get_by_app_id(app.id)
                    if c.name == args.channel), None)
    if channel is None:
        _err(f"Channel {args.channel} does not exist.")
        return 1
    if not args.force and not _confirm(f"Delete channel {args.channel}?"):
        return 1
    storage.get_events().remove(app.id, channel.id)
    channels.delete(channel.id)
    _out(f"Deleted channel {args.channel}.")
    return 0


def cmd_accesskey_new(args, storage: Storage) -> int:
    app = storage.get_meta_data_apps().get_by_name(args.app_name)
    if app is None:
        _err(f"App {args.app_name} does not exist. Aborting.")
        return 1
    key = storage.get_meta_data_access_keys().insert(
        AccessKey(args.access_key or "", app.id, tuple(args.event or ()))
    )
    _out(f"Created new access key: {key}")
    return 0


def cmd_accesskey_list(args, storage: Storage) -> int:
    keys = storage.get_meta_data_access_keys()
    if args.app_name:
        app = storage.get_meta_data_apps().get_by_name(args.app_name)
        if app is None:
            _err(f"App {args.app_name} does not exist. Aborting.")
            return 1
        listed = keys.get_by_app_id(app.id)
    else:
        listed = keys.get_all()
    for k in listed:
        events = ", ".join(k.events) if k.events else "(all)"
        _out(f"{k.key} | app {k.app_id} | {events}")
    _out(f"Finished listing {len(listed)} access key(s).")
    return 0


def cmd_accesskey_delete(args, storage: Storage) -> int:
    if storage.get_meta_data_access_keys().delete(args.key):
        _out(f"Deleted access key {args.key}.")
        return 0
    _err(f"Error deleting access key {args.key}.")
    return 1


def _confirm(prompt: str) -> bool:
    answer = input(f"{prompt} (Y/n) ")
    return answer.strip().lower() in ("", "y", "yes")


# ---------------------------------------------------------------------------
# train / eval / deploy / batchpredict / servers
# ---------------------------------------------------------------------------

def cmd_train(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.core.workflow.create_workflow import (
        WorkflowConfig,
        create_workflow,
    )

    axes = json.loads(args.mesh_axes) if args.mesh_axes else None
    config = WorkflowConfig(
        engine_variant=args.engine_variant,
        batch=args.batch,
        verbose=args.verbose,
        skip_sanity_check=args.skip_sanity_check,
        stop_after_read=args.stop_after_read,
        stop_after_prepare=args.stop_after_prepare,
        mesh_axes=axes,
        distributed=getattr(args, "distributed", False),
    )
    if getattr(args, "profile_dir", None):
        from incubator_predictionio_tpu.utils.tracing import profile_trace

        trace = profile_trace(args.profile_dir)
    else:
        trace = contextlib.nullcontext()
    with trace:
        instance_id = create_workflow(config, storage)
    if getattr(args, "profile_dir", None):
        _out(f"Profiler trace written to {args.profile_dir} "
             "(TensorBoard 'profile' plugin layout).")
    if instance_id == "<secondary>":
        _out("Training completed (secondary process; the primary wrote the "
             "engine instance).")
    else:
        _out(f"Training completed. Engine instance ID: {instance_id}")
    return 0


def cmd_eval(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.core.workflow.create_workflow import (
        WorkflowConfig,
        create_workflow,
    )

    axes = json.loads(args.mesh_axes) if getattr(args, "mesh_axes", None) else None
    config = WorkflowConfig(
        engine_variant=args.engine_variant,
        evaluation_class=args.evaluation_class,
        engine_params_generator_class=args.engine_params_generator_class,
        batch=args.batch,
        mesh_axes=axes,
        distributed=getattr(args, "distributed", False),
        fast_eval=not getattr(args, "no_fast_eval", False),
    )
    instance_id = create_workflow(config, storage)
    if instance_id == "<secondary>":
        _out("Evaluation completed (secondary process; the primary wrote "
             "the evaluation instance).")
        return 0
    inst = storage.get_meta_data_evaluation_instances().get(instance_id)
    _out(f"Evaluation completed. Instance ID: {instance_id}")
    if inst is not None and inst.evaluator_results:
        _out(inst.evaluator_results)
    return 0


def cmd_deploy(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.server.query_server import ServerConfig, serve_forever

    config = ServerConfig(
        engine_variant=args.engine_variant,
        ip=args.ip,
        port=args.port,
        feedback=args.feedback,
        event_server_ip=args.event_server_ip,
        event_server_port=args.event_server_port,
        access_key=args.access_key,
        server_access_key=args.server_access_key,
        ssl_cert=args.ssl_cert,
        ssl_key=args.ssl_key,
        log_url=args.log_url,
        log_prefix=args.log_prefix,
        query_timeout_sec=args.query_timeout_sec,
        algo_deadline_sec=args.algo_deadline_sec,
        algo_breaker_threshold=args.algo_breaker_threshold,
        algo_breaker_reset_sec=args.algo_breaker_reset_sec,
        smoke_queries=tuple(
            json.loads(q) for q in (args.smoke_query or ())),
        reload_probation_sec=args.reload_probation_sec,
        # unset flags keep the PIO_FLEET_SHARD_* env defaults
        **{k: v for k, v in (
            ("shard_id", args.shard_id),
            ("shard_count", args.shard_count),
            ("shard_state_dir", args.shard_state_dir),
        ) if v is not None},
        # unset flags keep the PIO_ADMISSION_* env defaults
        **{k: v for k, v in (
            ("admission_max_queue", args.admission_max_queue),
            ("admission_target_ms", args.admission_target_ms),
        ) if v is not None},
        **({"admission_adaptive": False}
           if args.no_adaptive_admission else {}),
    )
    # multi-tenant mode (docs/tenancy.md): a tenant table via --tenants
    # or PIO_TENANTS hosts N engines behind this one process; the classic
    # single-engine path below stays byte-identical without one
    tenants_src = args.tenants or os.environ.get("PIO_TENANTS", "").strip()
    if tenants_src:
        from incubator_predictionio_tpu.server.tenancy import (
            load_tenant_specs,
            serve_forever_tenants,
        )

        serve_forever_tenants(config, load_tenant_specs(tenants_src),
                              storage)
        return 0
    serve_forever(config, storage)
    return 0


def cmd_undeploy(args, storage: Storage) -> int:
    import urllib.request

    url = f"http://{args.ip}:{args.port}/stop"
    if args.server_access_key:
        url += f"?accessKey={args.server_access_key}"
    try:
        with urllib.request.urlopen(
            urllib.request.Request(url, method="POST"), timeout=10
        ) as resp:
            _out(resp.read().decode())
        return 0
    except Exception as e:  # noqa: BLE001
        _err(f"Undeploy failed: {e}")
        return 1


def cmd_batchpredict(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.core.workflow.batch_predict import (
        BatchPredictConfig,
        part_path,
        run_batch_predict,
    )
    from incubator_predictionio_tpu.parallel.mesh import MeshContext

    ctx = None
    if getattr(args, "distributed", False):
        # under `pio-tpu launch -n N batchpredict --distributed` each
        # process scores a slice and writes <output>.part-<pid>
        ctx = MeshContext.from_conf({"distributed": True})
    n = run_batch_predict(
        BatchPredictConfig(
            engine_variant=args.engine_variant,
            input_path=args.input,
            output_path=args.output,
            query_chunk=args.query_partitions or 1024,
        ),
        storage,
        ctx,
    )
    if ctx is not None and ctx.process_count > 1:
        _out(f"Batch predict completed: {n} predictions written to "
             f"{part_path(args.output, ctx.process_index)} "
             f"(slice {ctx.process_index + 1}/{ctx.process_count})")
    else:
        _out(f"Batch predict completed: {n} predictions written to {args.output}")
    return 0


def cmd_dashboard(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.tools.dashboard import DashboardConfig, serve_forever

    serve_forever(DashboardConfig(
        ip=args.ip, port=args.port,
        ssl_cert=args.ssl_cert, ssl_key=args.ssl_key,
        server_access_key=args.server_access_key), storage)
    return 0


def cmd_adminserver(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.tools.admin import AdminConfig, serve_forever

    serve_forever(AdminConfig(
        ip=args.ip, port=args.port,
        ssl_cert=args.ssl_cert, ssl_key=args.ssl_key,
        server_access_key=args.server_access_key), storage)
    return 0


def cmd_eventserver(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.server.event_server import (
        EventServerConfig,
        serve_forever,
    )

    kw = {}
    if args.wal_dir:  # unset keeps the PIO_EVENT_WAL_DIR env default
        kw["wal_dir"] = args.wal_dir
    if args.client_rate is not None:  # unset keeps the env default
        kw["client_rate"] = args.client_rate
    if args.client_burst is not None:
        kw["client_burst"] = args.client_burst
    serve_forever(EventServerConfig(ip=args.ip, port=args.port,
                                    stats=args.stats, ssl_cert=args.ssl_cert,
                                    ssl_key=args.ssl_key, **kw), storage)
    return 0


def cmd_storageserver(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.server.storage_server import (
        StorageServerConfig,
        serve_forever,
    )

    kw = {}
    if args.client_inflight is not None:  # unset keeps the env default
        kw["client_inflight"] = args.client_inflight
    if getattr(args, "repl_role", None):
        kw["repl_role"] = args.repl_role
    if getattr(args, "repl_peer", None):
        kw["repl_peers"] = tuple(args.repl_peer)
    if getattr(args, "repl_sync", None):
        kw["repl_sync"] = args.repl_sync
    serve_forever(StorageServerConfig(
        ip=args.ip, port=args.port,
        ssl_cert=args.ssl_cert, ssl_key=args.ssl_key,
        server_access_key=args.server_access_key, **kw), storage)
    return 0


def cmd_start_all(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.tools.ops import StartAllConfig, start_all

    _, unhealthy = start_all(StartAllConfig(
        ip=args.ip,
        event_server_port=args.event_server_port,
        with_dashboard=args.with_dashboard,
        dashboard_port=args.dashboard_port,
        with_adminserver=args.with_adminserver,
        adminserver_port=args.adminserver_port,
        with_storageserver=args.with_storageserver,
        storageserver_port=args.storageserver_port,
        storageserver_access_key=args.storageserver_access_key,
        stats=args.stats,
        wait_secs=args.wait_secs,
    ))
    return 1 if unhealthy else 0


def cmd_stop_all(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.tools.ops import stop_all

    stop_all()
    return 0


def cmd_redeploy(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.tools.ops import (
        RedeployConfig,
        redeploy,
        redeploy_via_jobs,
    )

    server_url = None if args.no_reload else f"http://{args.ip}:{args.port}"
    runner = redeploy if args.legacy else redeploy_via_jobs
    instance_id = runner(RedeployConfig(
        engine_variant=args.engine_variant,
        batch=args.batch,
        retries=args.retries,
        retry_wait_secs=args.retry_wait,
        server_url=server_url,
        server_access_key=args.server_access_key,
        interval_secs=args.interval,
        mesh_axes=json.loads(args.mesh_axes) if args.mesh_axes else None,
    ), storage)
    return 0 if instance_id else 1


def cmd_shell(args, storage: Storage) -> int:
    """Interactive console with the pypio-style bootstrap preloaded
    (bin/pio-shell + python/pypio/shell.py slot): ``storage``,
    ``l_event_store``, ``p_event_store``, and ``mesh(**axes)``."""
    import incubator_predictionio_tpu.shell as sh

    ns = {name: getattr(sh, name) for name in sh.__all__}
    if args.shell_code:
        exec(compile(args.shell_code, "<pio-tpu shell -c>", "exec"), ns)
        return 0
    import code

    banner = (
        f"incubator-predictionio-tpu shell (v{piotpu.__version__})\n"
        "preloaded: storage, l_event_store, p_event_store, mesh(**axes)"
    )
    code.interact(banner=banner, local=ns, exitmsg="")
    return 0


#: In-package template registry (commands/Template.scala:33-69 points at the
#: external gallery; templates ship in-package here, so list/get are real).
TEMPLATES = {
    "recommendation": {
        "factory": "incubator_predictionio_tpu.templates.recommendation."
                   "RecommendationEngine",
        "algorithms": [{"name": "als", "params": {
            "rank": 64, "numIterations": 20}}],
        "description": "two-tower MF over rate/buy events "
                       "(scala-parallel-recommendation slot)",
    },
    "classification": {
        "factory": "incubator_predictionio_tpu.templates.classification."
                   "ClassificationEngine",
        "algorithms": [{"name": "mlp", "params": {}}],
        "description": "MLP over $set attribute/label snapshots "
                       "(scala-parallel-classification slot)",
    },
    "similarproduct": {
        "factory": "incubator_predictionio_tpu.templates.similarproduct."
                   "SimilarProductEngine",
        "algorithms": [{"name": "als", "params": {}}],
        "description": "implicit MF + cooccurrence over view/like events "
                       "(scala-parallel-similarproduct slot)",
    },
    "recommendeduser": {
        "factory": "incubator_predictionio_tpu.templates.recommended_user."
                   "RecommendedUserEngine",
        "algorithms": [{"name": "als", "params": {}}],
        "description": "user-to-user implicit MF over follow events "
                       "(similarproduct/recommended-user slot)",
    },
    "ecommerce": {
        "factory": "incubator_predictionio_tpu.templates.ecommerce."
                   "ECommerceEngine",
        "algorithms": [{"name": "ecomm", "params": {}}],
        "algo_app_name": True,  # live serving-time event reads
        "description": "two-tower retrieval with live constraints "
                       "(scala-parallel-ecommercerecommendation slot)",
    },
    "sequential": {
        "factory": "incubator_predictionio_tpu.templates.sequential."
                   "SequentialEngine",
        "algorithms": [{"name": "transformer", "params": {}}],
        "algo_app_name": True,  # user-history reads at serving time
        "description": "session transformer next-item recommender "
                       "(long-context flagship; no reference counterpart)",
    },
}


def cmd_template_list(args, storage: Storage) -> int:
    for name, t in TEMPLATES.items():
        _out(f"{name:16s} {t['description']}")
        _out(f"{'':16s}   engineFactory: {t['factory']}")
    return 0


def cmd_template_get(args, storage: Storage) -> int:
    """Scaffold a ready-to-train engine.json for the named template."""
    t = TEMPLATES.get(args.name)
    if t is None:
        _err(f"Unknown template {args.name!r}; try: pio-tpu template list")
        return 1
    import copy
    import os

    os.makedirs(args.directory, exist_ok=True)
    path = os.path.join(args.directory, "engine.json")
    if os.path.exists(path) and not args.force:
        _err(f"{path} already exists (use --force to overwrite)")
        return 1
    app_name = args.app_name or args.name
    algorithms = copy.deepcopy(t["algorithms"])
    if t.get("algo_app_name"):
        # these algorithms read live events at SERVING time through their own
        # appName param (seen items, user history) — it must match the
        # datasource's app or those lookups silently return nothing
        for a in algorithms:
            a["params"]["appName"] = app_name
    variant = {
        "id": args.name,
        "version": "1",
        "engineFactory": t["factory"],
        "datasource": {"params": {"appName": app_name}},
        "algorithms": algorithms,
    }
    with open(path, "w") as f:
        json.dump(variant, f, indent=2)
        f.write("\n")
    _out(f"Wrote {path} — next: pio-tpu app new {args.app_name or args.name}; "
         f"pio-tpu train -v {path}")
    return 0


def cmd_export(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.tools.export_import import export_events

    channel_id = _resolve_channel(args, storage)
    n = export_events(args.appid, args.output, channel_id, storage)
    _out(f"Exported {n} events.")
    return 0


def cmd_import(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.tools.export_import import import_events

    channel_id = _resolve_channel(args, storage)
    n = import_events(args.appid, args.input, channel_id, storage)
    _out(f"Imported {n} events.")
    return 0


def _resolve_channel(args, storage: Storage) -> Optional[int]:
    if not getattr(args, "channel", None):
        return None
    channels = storage.get_meta_data_channels().get_by_app_id(args.appid)
    channel = next((c for c in channels if c.name == args.channel), None)
    if channel is None:
        raise SystemExit(f"Channel {args.channel} does not exist for app {args.appid}")
    return channel.id


def cmd_status(args, storage: Storage) -> int:
    """(commands/Management.scala:99-181 + Storage.verifyAllDataObjects)"""
    import jax

    from incubator_predictionio_tpu.parallel.mesh import honor_platform_env

    _out(f"incubator_predictionio_tpu {piotpu.__version__}")
    honor_platform_env()
    devices = jax.devices()
    _out(f"Devices: {len(devices)} × {devices[0].platform}"
         f" ({devices[0].device_kind})")
    from incubator_predictionio_tpu.utils.tracing import device_memory_report

    for row in device_memory_report():
        if row["bytes_in_use"] is not None:
            _out(f"  {row['device']}: {row['bytes_in_use'] / 2**20:.1f} MiB in use"
                 + (f" / {row['bytes_limit'] / 2**20:.0f} MiB"
                    if row["bytes_limit"] else ""))
    for repo, name, source, type_name in storage.describe():
        _out(f"  {repo}: name={name} source={source} type={type_name}")
    failures = storage.verify_all_data_objects()
    if failures:
        for f in failures:
            _err(f"  [FAILED] {f}")
        _err("Unable to connect to all storage backends successfully.")
        return 1
    _out("Storage: all repositories verified (METADATA/EVENTDATA/MODELDATA).")
    _print_jobs_status(storage)
    _print_jit_status()
    _out("Your system is all ready to go.")
    return 0


def _print_jit_status() -> None:
    """The compile-churn section of ``pio-tpu status``: cumulative
    first-dispatch (compile-dominated) wall time per executable name
    (utils/jitstats.py) — in-process truth, so it is populated when status
    runs after a train/serve in the same process (tests, shell, bench)."""
    from incubator_predictionio_tpu.utils import jitstats

    top = jitstats.top_compiles()
    if not top:
        return
    total = jitstats.compile_seconds_total()
    _out(f"JIT compiles: {total:.2f}s first-dispatch wall across "
         f"{jitstats.count()} cached key(s)")
    for name, sec, n in top:
        _out(f"  {name}: {sec:.3f}s over {n} compile(s)")


def _print_jobs_status(storage: Storage) -> None:
    """The continuous-training section of ``pio-tpu status``: per-kind
    queue counts, tightest remaining lease, last failure (docs/jobs.md).
    Tolerant of backends without a jobs DAO (third-party METADATA)."""
    try:
        from incubator_predictionio_tpu.jobs import Orchestrator

        summary = Orchestrator(storage.get_meta_data_jobs()).summarize()
    except NotImplementedError:
        _out("Jobs: METADATA backend has no jobs DAO (control plane off).")
        return
    except Exception as e:  # noqa: BLE001 — status must not crash on this
        _err(f"Jobs: unreadable ({e})")
        return
    kinds = summary["kinds"]
    if not kinds:
        _out("Jobs: none submitted (docs/jobs.md — `pio-tpu jobs submit`).")
        return
    _out("Jobs:")
    for kind in sorted(kinds):
        k = kinds[kind]
        line = (f"  {kind}: queued {k.get('queued', 0)}, running "
                f"{k.get('running', 0)}, completed {k.get('completed', 0)}, "
                f"failed {k.get('failed', 0)}, refused {k.get('refused', 0)}")
        margin = k.get("oldestLeaseAgeSec")
        if margin is not None:
            line += (f", lease margin {margin:+.0f}s"
                     + (" [EXPIRED — reclaim pending]" if margin < 0 else ""))
        _out(line)
    lf = summary["lastFailure"]
    if lf:
        _out(f"  last failure: {lf['kind']} {lf['id'][:12]} "
             f"[{lf['status']}] {lf['failure']}")


def cmd_version(args, storage) -> int:
    _out(piotpu.__version__)
    return 0


def cmd_wal(args, storage: Storage) -> int:
    """Inspect / verify / replay an event-server spill WAL directory
    (resilience/wal.py; docs/resilience.md "Durability & crash recovery").

    Plain invocation is strictly read-only (safe against a live server):
    per-segment frame counts, CRC/torn-frame defects, the commit cursor,
    pending and dead-letter tallies. ``--replay`` lands every pending
    record in the configured event store (idempotent — ids are
    pre-assigned) and advances the cursor; ``--dead-letter`` prints the
    dead-letter records so a store-rejected batch can be repaired by hand.
    """
    from incubator_predictionio_tpu.resilience.wal import SpillWal, inspect_dir

    info = inspect_dir(args.directory)
    if args.json:
        _out(json.dumps(info, indent=2))
    else:
        _out(f"WAL directory: {info['directory']}")
        _out(f"  committed seq: {info['committedSeq']}")
        for seg in info["segments"]:
            line = (f"  {os.path.basename(seg['path'])}: "
                    f"{seg['frames']} frame(s), {seg['bytes']} bytes")
            if seg["maxSeq"] is not None:
                line += f", max seq {seg['maxSeq']}"
            if seg["defect"]:
                line += (f"  [DEFECT: {seg['defect']} @ byte "
                         f"{seg['defectOffset']}]")
            _out(line)
        _out(f"  pending (uncommitted): {info['pending']}")
        if info.get("firstCorrupt"):
            fc = info["firstCorrupt"]
            _out(f"  first corrupt frame: "
                 f"{os.path.basename(fc['segment'])} @ byte "
                 f"{fc['offset']} ({fc['defect']})")
        _out(f"  dead letters: {len(info['deadLetters'])}"
             + (f"  [DEFECT: {info['deadLetterDefect']} @ byte "
                f"{info['deadLetterDefectOffset']}]"
                if info["deadLetterDefect"] else ""))
    if args.dead_letter and info["deadLetters"]:
        for rec in info["deadLetters"]:
            _out(json.dumps(rec))
    if not args.replay:
        return 0

    from incubator_predictionio_tpu.data.event import Event

    wal = SpillWal(args.directory)
    pending = wal.replay()
    if not pending:
        _out("Nothing to replay.")
        wal.close()
        return 0
    events_store = storage.get_events()
    replayed = 0
    try:
        i = 0
        while i < len(pending):
            # one insert_batch per (app, channel) run, ≤ 50 like the server
            app_id = pending[i]["app_id"]
            channel_id = pending[i].get("channel_id")
            batch = []
            while (i < len(pending) and len(batch) < 50
                   and pending[i]["app_id"] == app_id
                   and pending[i].get("channel_id") == channel_id):
                batch.append(pending[i])
                i += 1
            events_store.init(app_id, channel_id)
            events_store.insert_batch(
                [Event.from_json_dict(r["event"]) for r in batch],
                app_id, channel_id)
            wal.commit(max(r["seq"] for r in batch))
            replayed += len(batch)
    except Exception as e:  # noqa: BLE001 - partial progress is committed
        _err(f"Replay stopped after {replayed}/{len(pending)} event(s): {e}")
        wal.close()
        return 1
    finally:
        if replayed:
            _out(f"Replayed {replayed} event(s) into the configured "
                 "event store.")
    wal.close()
    return 0


def cmd_stream(args, storage: Storage) -> int:
    """Streaming incremental updates (docs/streaming.md): tail the
    eventlog change feed, fold events into embedding-row deltas, and ship
    them to the given replicas as versioned delta deploys — crash-safe and
    exactly-once (cursor + delta archive live in ``--state-dir``).

    ``--status`` prints the stream state (cursor, quarantine, dead
    letters) without folding; ``--dead-letter`` prints the dead-lettered
    poison events as JSON lines; ``--once`` runs a single
    poll→fold→ship→commit round and exits (the chaos tests drive this)."""
    from incubator_predictionio_tpu.streaming.feed import resolve_feed_path
    from incubator_predictionio_tpu.streaming.updater import (
        StreamUpdater,
        UpdaterConfig,
        inspect_state_dir,
        load_base_model,
    )

    if args.status:
        # strictly read-only: no model load, no cursor creation, no
        # instance-change state reset — safe beside a live updater
        info = inspect_state_dir(args.state_dir)
        _out(json.dumps(info, indent=2, default=str))
        return 1 if info["quarantine"] else 0
    if args.dead_letter:
        from incubator_predictionio_tpu.resilience.wal import tail_frames

        path = os.path.join(args.state_dir, "deadletter.log")
        if not os.path.exists(path):
            _out("No dead letters.")
            return 0
        records, _, status = tail_frames(path)
        for _, rec in records:
            _out(json.dumps(rec))
        if status == "corrupt":
            _err("dead-letter file has a corrupt frame past the listed "
                 "records")
            return 1
        return 0
    model, instance_id, event_names, defaults = load_base_model(
        args.engine_variant, storage)
    feed_path = args.feed_path or resolve_feed_path(
        storage, args.app, args.channel)
    cfg = UpdaterConfig(
        state_dir=args.state_dir,
        feed_path=feed_path,
        replicas=tuple(args.replica or ()),
        access_key=args.server_access_key,
        batch_events=args.batch_events,
        poll_interval=args.interval,
        from_start=args.from_start,
    )
    updater = StreamUpdater(cfg, model, instance_id,
                            event_names=event_names,
                            default_values=defaults)
    obs_handle = None
    if args.obs_port:
        # the updater has no HTTP surface of its own; this thread serves
        # the shared /metrics + /traces.json so pio_stream_* is scrapeable
        from incubator_predictionio_tpu.obs.http import start_obs_server

        obs_handle = start_obs_server("stream_updater", args.obs_port,
                                      ip=args.obs_ip)
    try:
        if args.once:
            out = updater.run_once()
            _out(json.dumps(out, default=str))
            return 1 if out["status"] == "quarantined" else 0
        updater.run_forever(max_batches=args.max_batches)
        return 1 if updater.quarantined else 0
    finally:
        if obs_handle is not None:
            obs_handle.close()


def _fetch_health(url: str, timeout: float = 5.0) -> dict:
    """GET <url>/health, parsed. Module-level so tests can stub it; the
    single implementation lives in fleet/health.py (the router's watcher
    probes with exactly the same fetch)."""
    from incubator_predictionio_tpu.fleet.health import fetch_health

    return fetch_health(url, timeout)


def _health_row(url: str, h: Optional[dict], err: Optional[str]) -> dict:
    """One table row from any of the three servers' /health shapes:
    red = unreachable, draining, or degraded; the detail column names the
    reason (open breakers, spill depth, brownout, shed/throttle tallies)."""
    if h is None:
        return {"url": url, "status": "unreachable", "red": True,
                "detail": err or ""}
    breakers: dict[str, dict] = {}
    for k, v in h.items():
        if k.endswith("Breakers") and isinstance(v, dict):
            breakers.update(v)
        elif k.endswith("Breaker") and isinstance(v, dict):
            breakers[k] = v
    parts = []
    open_names = sorted(n for n, s in breakers.items()
                        if isinstance(s, dict) and s.get("state") != "closed")
    if open_names:
        parts.append("breakers open: " + ", ".join(open_names[:4]))
    if h.get("spillQueueDepth"):
        parts.append(f"spill {h['spillQueueDepth']}/{h.get('spillQueueMax')}")
    if h.get("deadLettered"):
        parts.append(f"deadLettered {h['deadLettered']}")
    adm = h.get("admission") or {}
    if adm.get("brownoutActive"):
        parts.append("BROWNOUT")
    if adm.get("queueDepth"):
        parts.append(f"queue {adm['queueDepth']}/{adm.get('queueMax')}")
    if adm.get("rejected"):
        parts.append(f"rejected {adm['rejected']}")
    if adm.get("shedExpired"):
        parts.append(f"shed {adm['shedExpired']}")
    throttled = adm.get("throttled") or (adm.get("fairness") or {}).get(
        "throttled")
    if throttled:
        parts.append(f"throttled {throttled}")
    # streaming update lag (docs/streaming.md): chain position + freshness
    stream = (h.get("deployment") or {}).get("streaming") or {}
    if stream.get("lastDeltaSeq") is not None:
        lag = stream.get("stalenessSeconds")
        parts.append(
            f"deltaSeq {stream['lastDeltaSeq']}"
            + (f", staleness {lag:.0f}s" if lag is not None else ""))
    # storage replication (docs/replication.md): role/epoch/lag rows so a
    # lagging or fenced store turns the fleet probe red
    from incubator_predictionio_tpu.fleet.health import replication_flags

    repl = replication_flags(h)
    repl_red = False
    if repl is not None:
        parts.append(f"repl {repl['role']}@{repl['epoch']}")
        if repl["fenced"]:
            parts.append(f"FENCED ({repl.get('fencedWrites') or 0} writes "
                         "rejected)")
        if repl.get("lagBytes"):
            parts.append(f"lag {repl['lagBytes']}B"
                         + (" EXCEEDED" if repl["lagExceeded"] else ""))
        repl_red = repl["red"]
    # SLO burn-rate verdicts (obs/slo.py): a breaching objective turns the
    # row red even while the server itself answers "ok" — error budget is
    # burning NOW regardless of breaker state
    slo = h.get("slo") or {}
    slo_red = bool(slo.get("breaching"))
    if slo_red:
        bad = [o.get("name", "?") for o in slo.get("objectives", [])
               if o.get("breaching")]
        parts.append("SLO BREACH: " + ", ".join(bad[:4]))
    status = h.get("status", "unknown")
    return {"url": url, "status": status,
            "red": status != "ok" or repl_red or slo_red,
            "replication": repl,
            "slo": slo or None,
            "detail": "; ".join(parts)}


def cmd_health(args, storage) -> int:
    """Aggregate ``GET /health`` from every given server (event, query,
    storage — any mix) into one table: status, draining, breaker, spill,
    and admission/overload state. Exit non-zero when ANY server is red
    (unreachable, draining, or degraded) — the fleet smoke gate the
    overload chaos test uses (docs/resilience.md).

    Probes run CONCURRENTLY (fleet/health.py — the same fan-out the fleet
    router's health watcher uses): a fleet with slow or dead replicas
    answers in ~one probe timeout, not O(N × timeout)."""
    from incubator_predictionio_tpu.fleet.health import probe_health_urls

    # fetch resolved through the module global so tests can stub it
    probed = probe_health_urls(
        args.urls, args.timeout,
        fetch=lambda url, timeout: _fetch_health(url, timeout))
    rows = [_health_row(url, *probed[url]) for url in args.urls]
    rows.extend(_shard_coverage_rows(args.urls, probed))
    if getattr(args, "stream_state_dir", None):
        rows.append(_quarantine_row(args.stream_state_dir,
                                    args.quarantine_max_age))
    if getattr(args, "backup_dir", None):
        rows.append(_backup_row(args.backup_dir, args.backup_max_age))
    if getattr(args, "dist_state_dir", None):
        rows.append(_mesh_row(args.dist_state_dir))
    if not rows:
        _err("health: nothing to probe (give server URLs and/or "
             "--stream-state-dir / --backup-dir)")
        return 2
    if args.json:
        _out(json.dumps(rows, indent=2))
    else:
        w = max(len(r["url"]) for r in rows)
        for r in rows:
            mark = "!!" if r["red"] else "ok"
            line = f"{mark} {r['url']:<{w}}  {r['status']}"
            if r["detail"]:
                line += f"  [{r['detail']}]"
            _out(line)
    return 1 if any(r["red"] for r in rows) else 0


def _shard_coverage_rows(urls: list, probed: dict) -> list[dict]:
    """Synthetic fleet rows (the quarantine-row pattern) for multi-host
    shard ownership (docs/sharding.md "Multi-host shard owners"): one row
    per announced shard range, RED when the range has zero live owners —
    those catalog rows can no longer appear in any merged answer, which a
    per-replica table hides (every surviving replica still looks green).
    An owner announcing below the range's max epoch is a deposed process
    restarted with stale rows: counted fenced, never live (the router's
    epoch-fencing discipline, fleet/topology.py)."""
    ranges: dict[int, dict] = {}
    for url in urls:
        h, _err = probed[url]
        owner = ((h or {}).get("deployment") or {}).get("shardOwner")
        if not isinstance(owner, dict):
            continue
        rows, sid = owner.get("rows"), owner.get("shardId")
        if sid is None or not rows or len(rows) != 2:
            continue
        g = ranges.setdefault(int(sid), {
            "lo": int(rows[0]), "hi": int(rows[1]),
            "max_epoch": 0, "owners": []})
        g["lo"] = min(g["lo"], int(rows[0]))
        g["hi"] = max(g["hi"], int(rows[1]))
        epoch = int(owner.get("epoch") or 0)
        g["max_epoch"] = max(g["max_epoch"], epoch)
        g["owners"].append(
            (url, epoch,
             h.get("status") == "ok" and not h.get("draining")))
        g["count"] = max(g.get("count", 0),
                         int(owner.get("shardCount") or 0))
    # a shard id whose owners are ALL unreachable never announces at all
    # — the announced shardCount from the reachable owners reveals the
    # hole (without it the dead range would silently vanish from the
    # report, the exact failure this table exists to catch)
    if ranges:
        expect = max(g.get("count", 0) for g in ranges.values())
        for sid in range(expect):
            if sid not in ranges:
                ranges[sid] = {"lo": -1, "hi": -1, "max_epoch": 0,
                               "owners": []}
    out: list[dict] = []
    for sid in sorted(ranges, key=lambda s: (ranges[s]["lo"], s)):
        g = ranges[sid]
        live = [u for u, e, ok in g["owners"]
                if ok and e >= g["max_epoch"]]
        fenced = [u for u, e, _ok in g["owners"] if e < g["max_epoch"]]
        known = g["lo"] >= 0
        span = f"{g['lo']}-{g['hi']}" if known else "?"
        url = f"shard:{sid}:rows={span}"
        if live:
            detail = f"live owners: {', '.join(live)}"
            if fenced:
                detail += ("; FENCED stale-epoch: " + ", ".join(fenced)
                           + " (resync + POST /shard/promote to re-admit)")
            out.append({"url": url, "status": "ok", "red": False,
                        "detail": detail})
        else:
            rows_txt = (f"rows [{g['lo']},{g['hi']})" if known
                        else "its rows (range unannounced — every owner "
                             "unreachable)")
            out.append({
                "url": url, "status": "no-live-owner", "red": True,
                "detail": (f"{rows_txt} unservable — promote a standby "
                           f"(`pio-tpu deploy --shard-id {sid}` + POST "
                           "/shard/promote) or answers go partial/504 "
                           "(docs/sharding.md)")})
    return out


def _quarantine_row(state_dir: str, max_age: Optional[float]) -> dict:
    """The stuck-control-loop probe (docs/jobs.md): a stream quarantine
    marker older than the retrain trigger interval means the auto-retrain
    loop that should have cleared it is not running — red. A younger
    marker is the control loop mid-recovery — reported, not red."""
    from incubator_predictionio_tpu.jobs import quarantine_age_seconds

    age = quarantine_age_seconds(state_dir)
    url = f"stream:{state_dir}"
    if age is None:
        return {"url": url, "status": "ok", "red": False,
                "detail": "no quarantine marker"}
    if max_age is None:
        max_age = float(os.environ.get("PIO_JOBS_INTERVAL", "0")) or 300.0
    stuck = age > max_age
    detail = (f"QUARANTINED {age:.0f}s"
              + (f" > trigger interval {max_age:.0f}s — control loop "
                 "stuck (is `pio-tpu jobs triggers` + a worker running?)"
                 if stuck else f" (retrain due within {max_age:.0f}s)"))
    return {"url": url, "status": "quarantined", "red": stuck,
            "detail": detail}


def _mesh_row(state_dir: str) -> dict:
    """Synthetic health row for a distributed-training mesh (the
    quarantine-row pattern): red when live members are below quorum — a
    mesh that can no longer make training progress or commit a checkpoint
    (docs/sharding.md "Multi-host training")."""
    from incubator_predictionio_tpu.distributed.context import DistConfig
    from incubator_predictionio_tpu.distributed.meshdir import MeshDirectory

    conf = DistConfig.from_env()
    snap = MeshDirectory(state_dir).health_snapshot(
        conf.heartbeat_ms, quorum=conf.quorum or None)
    url = f"mesh:{state_dir}"
    commit = snap.get("lastCommit") or {}
    commit_txt = (f"last commit step {commit['step']} "
                  f"(gen {commit['generation']})" if commit else "no commit yet")
    detail = (f"generation {snap['generation']}, members "
              f"{snap['aliveMembers']}/{snap['expectedMembers']} alive "
              f"(quorum {snap['quorum']}); {commit_txt}")
    if snap["degraded"]:
        detail += (" — BELOW QUORUM: training cannot progress; restart the "
                   "lost members or their supervisor (docs/sharding.md)")
        return {"url": url, "status": "degraded", "red": True,
                "detail": detail}
    if snap["expectedMembers"] == 0:
        return {"url": url, "status": "no-mesh", "red": False,
                "detail": "no generation announced yet"}
    return {"url": url, "status": "ok", "red": False, "detail": detail}


def cmd_dist_status(args, storage) -> int:
    """``pio-tpu dist status`` — the operator view of a training mesh:
    generation, per-member heartbeat ages, last coordinated commit, and
    the quorum verdict. Exits non-zero when the mesh is degraded (the
    ``pio-tpu health`` convention)."""
    from incubator_predictionio_tpu.distributed.context import DistConfig
    from incubator_predictionio_tpu.distributed.meshdir import MeshDirectory

    conf = DistConfig.from_env()
    state_dir = getattr(args, "state_dir", None) or conf.state_dir
    if not state_dir:
        _err("dist status: no coordination dir (--state-dir or "
             "PIO_DIST_STATE_DIR)")
        return 2
    snap = MeshDirectory(state_dir).health_snapshot(
        conf.heartbeat_ms, quorum=conf.quorum or None)
    if getattr(args, "json", False):
        _out(json.dumps(snap, indent=2))
        return 1 if snap["degraded"] else 0
    _out(f"Mesh {state_dir}")
    _out(f"  generation: {snap['generation']}   members: "
         f"{snap['aliveMembers']}/{snap['expectedMembers']} alive   "
         f"quorum: {snap['quorum']}   "
         f"{'DEGRADED' if snap['degraded'] else 'ok'}")
    commit = snap.get("lastCommit")
    if commit:
        _out(f"  last commit: step {commit['step']} "
             f"(generation {commit['generation']})")
    else:
        _out("  last commit: none")
    for mrec in snap["members"]:
        state = "alive" if mrec["alive"] else (
            "fenced" if mrec["generation"] != snap["generation"] else "STALE")
        _out(f"  member {mrec['rank']}: pid {mrec['pid']} gen "
             f"{mrec['generation']} step {mrec['step']} "
             f"beat {mrec['ageMs']:.0f}ms ago [{state}]")
    return 1 if snap["degraded"] else 0


def format_index_stats(models) -> list[str]:
    """Human-readable two-stage retrieval state for a deployed engine's
    models — separated from cmd_index so tests drive it with hand-built
    models instead of a full storage round trip."""
    lines: list[str] = []
    for i, m in enumerate(models):
        info = m.serving_info() if hasattr(m, "serving_info") else {}
        name = type(m).__name__
        mode = info.get("retrieval_mode", "exact")
        lines.append(f"model {i} ({name}): path={info.get('path', '?')} "
                     f"catalog_rows={info.get('catalog_rows', '?')} "
                     f"retrieval={mode}")
        stats = info.get("index")
        if isinstance(stats, list):
            # sharded serving: one IVF per shard (docs/sharding.md)
            live = [s for s in stats if s]
            if live:
                parts = [s["n_partitions"] for s in live]
                lines.append(
                    f"  per-shard IVF over {len(stats)} shards: "
                    f"{sum(parts)} partitions total "
                    f"({min(parts)}–{max(parts)}/shard) covering "
                    f"{sum(s['n_items'] for s in live)} items; "
                    f"rerank {'int8' if live[0]['quantized'] else 'fp32'}, "
                    f"index bytes {sum(s['index_bytes'] for s in live)} "
                    "— `pio-tpu shards` prints the layout")
                saved = sum(s.get("bytes_saved", 0) for s in live)
                if saved:
                    lines.append(
                        f"  quantization: int8 member rows + "
                        f"{'int8' if live[0].get('quant_coarse') else 'fp32'}"
                        f" coarse — saves {saved} bytes vs fp32 rerank "
                        "storage across shards")
                continue
            stats = None
        if not stats:
            lines.append("  no partition index (exact full-catalog retrieval"
                         " — see PIO_RETRIEVAL_MODE in docs/serving.md)")
            continue
        lines.append(
            f"  partitions: {stats['n_partitions']} over "
            f"{stats['n_items']} items  "
            f"(size min/mean/max {stats['partition_size_min']}/"
            f"{stats['partition_size_mean']}/{stats['partition_size_max']}, "
            f"skew {stats['size_skew']}, "
            f"{stats['empty_partitions']} empty)")
        lines.append(
            f"  rerank storage: "
            f"{'int8 (quantize_rows)' if stats['quantized'] else 'fp32'}  "
            f"default nprobe: {stats['default_nprobe']}  "
            f"index bytes: {stats['index_bytes']}  "
            f"build: {stats['build_seconds']}s")
        if stats.get("quantized"):
            lines.append(
                f"  quantization: int8 member rows "
                f"({stats.get('rerank_bytes', '?')} bytes, saves "
                f"{stats.get('bytes_saved', 0)} vs fp32) + "
                f"{'int8' if stats.get('quant_coarse') else 'fp32'} coarse "
                "(PIO_RETRIEVAL_QUANT_COARSE)")
    return lines


def _fmt_bytes(n) -> str:
    if n is None:
        return "unbounded"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"  # pragma: no cover - loop always returns


def format_shard_stats(models) -> list[str]:
    """Human-readable shard layout for a deployed engine's models —
    separated from cmd_shards so tests drive it with hand-built models
    (the format_index_stats pattern)."""
    lines: list[str] = []
    for i, m in enumerate(models):
        name = type(m).__name__
        if not hasattr(m, "shard_info"):
            lines.append(f"model {i} ({name}): no shard layout "
                         "(not an embedding-table model)")
            continue
        info = m.shard_info()
        if not info.get("sharded"):
            lines.append(f"model {i} ({name}): UNSHARDED single-host layout")
            items = info.get("items") or {}
            lines.append(
                f"  items: {items.get('n_rows', '?')} rows × "
                f"{items.get('width', '?')} "
                f"({_fmt_bytes(items.get('table_bytes'))} f32; "
                f"train+adam {_fmt_bytes(items.get('train_bytes_per_shard'))}"
                "/chip)")
            budget = info.get("hbm_budget")
            lines.append(
                f"  hbm budget: {_fmt_bytes(budget)}"
                + ("  — EXCEEDS one chip: train/serve sharded "
                   "(PIO_SHARD_SERVE, docs/sharding.md)"
                   if info.get("requires_sharding") else ""))
            continue
        items, users = info["items"], info["users"]
        lines.append(
            f"model {i} ({name}): SHARDED ×{info['n_shards']} "
            f"({info['mode']} shards)")
        for label, t in (("items", items), ("users", users)):
            rows = t["shard_rows"]
            lines.append(
                f"  {label}: {t['n_rows']} rows → {t['rows_per_shard']}"
                f"/shard (real min/max {min(rows)}/{max(rows)}), "
                f"{_fmt_bytes(t['table_bytes'] // t['n_shards'])} f32/shard, "
                f"train+adam {_fmt_bytes(t['train_bytes_per_shard'])}/shard")
        # owned row ranges: which rows [lo, hi) each shard id serves —
        # the unit of ownership multi-host shard owners announce on
        # /health.deployment.shardOwner (docs/sharding.md)
        from incubator_predictionio_tpu.sharding.table import ShardSpec

        spec = ShardSpec(items["name"], items["n_rows"], items["width"],
                         items["n_shards"])
        lines.append("  item row ranges: " + "  ".join(
            f"{s}:[{lo},{hi})" for s, (lo, hi) in
            ((s, spec.shard_bounds(s)) for s in range(spec.n_shards))))
        lines.append(
            f"  merge fan-in: {info['merge_fanin']} candidates/query "
            f"({info['n_shards']} shards × per-shard top-k, "
            f"serve_k {info['serve_k']})")
        budget = info.get("hbm_budget")
        if budget is not None:
            lines.append(f"  hbm budget: {_fmt_bytes(budget)}")
        ivf = info.get("ivf")
        if ivf and any(ivf):
            parts = [s["n_partitions"] for s in ivf if s]
            lines.append(
                f"  per-shard IVF: {sum(parts)} partitions total "
                f"({min(parts)}–{max(parts)}/shard) — each shard prunes "
                "locally, the merge reranks")
            if info.get("quantized"):
                lines.append(
                    f"  quantization: int8 rerank/shard "
                    f"({_fmt_bytes(items.get('shard_serve_bytes_int8'))} "
                    f"int8 vs "
                    f"{_fmt_bytes(items.get('table_bytes', 0) // max(info.get('n_shards', 1), 1))}"
                    f" f32 HBM/shard; saves "
                    f"{_fmt_bytes(info.get('rerank_bytes_saved', 0))} total)")
    return lines


def cmd_shards(args, storage: Storage) -> int:
    """Inspect the shard layout of the latest COMPLETED instance's models:
    per-shard row counts, HBM-bytes estimates, merge fan-in
    (docs/sharding.md)."""
    from incubator_predictionio_tpu.server.query_server import (
        ServerConfig,
        load_deployed_engine,
    )

    # warmup=False: inspection only reads shard_info() — XLA bucket
    # compiles would be paid for nothing
    deployed = load_deployed_engine(
        ServerConfig(engine_variant=args.engine_variant, max_batch=1),
        storage, warmup=False)
    _out(f"engine instance {deployed.instance.id}")
    for line in format_shard_stats(deployed.models):
        _out(line)
    return 0


def cmd_index(args, storage: Storage) -> int:
    """Inspect (building if needed) the two-stage retrieval partition of the
    latest COMPLETED instance's models (docs/serving.md "Two-stage
    retrieval")."""
    if args.two_stage:
        # force the build so small/dev catalogs are inspectable too
        os.environ["PIO_RETRIEVAL_MODE"] = "two_stage"
    from incubator_predictionio_tpu.server.query_server import (
        ServerConfig,
        load_deployed_engine,
    )

    # warmup=False: inspection only reads serving_info() — XLA bucket
    # compiles and two-stage priming would be paid for nothing
    deployed = load_deployed_engine(
        ServerConfig(engine_variant=args.engine_variant, max_batch=1),
        storage, warmup=False)
    _out(f"engine instance {deployed.instance.id}")
    for line in format_index_stats(deployed.models):
        _out(line)
    return 0


def cmd_tenants(args, storage) -> int:
    """Per-tenant fleet rollup (docs/tenancy.md): one row per tenant
    aggregated across every given server's ``/health`` + ``/metrics`` —
    requests + qps, p99, quota fill, throttles, cold loads, evictions,
    and resident HBM bytes. Red rows (the `pio-tpu health` row pattern:
    ``!!`` mark + non-zero exit) on quota exhaustion or eviction
    thrash."""
    from incubator_predictionio_tpu.fleet.health import probe_health_urls
    from incubator_predictionio_tpu.obs.metrics import (
        bucket_quantiles,
        parse_prometheus_text,
    )

    probed = probe_health_urls(
        args.urls, args.timeout,
        fetch=lambda url, timeout: _fetch_health(url, timeout))
    agg: dict[str, dict] = {}

    def slot(t: str) -> dict:
        return agg.setdefault(t, {
            "tenant": t, "requests": 0, "throttled": 0, "evictions": 0,
            "coldLoads": 0, "residentBytes": 0, "replicas": 0,
            "resident": 0, "pinned": False, "quotaFill": None,
            "p99Ms": None, "qps": None})

    rows: list[dict] = []
    for url in args.urls:
        h, err = probed[url]
        if h is None:
            rows.append({"url": url, "status": "unreachable", "red": True,
                         "detail": err or ""})
            continue
        tenants = ((h.get("tenancy") or {}).get("tenants")) or {}
        for t, trow in tenants.items():
            a = slot(t)
            a["replicas"] += 1
            a["resident"] += 1 if trow.get("resident") else 0
            a["pinned"] = a["pinned"] or bool(trow.get("pinned"))
            a["requests"] += int(trow.get("requests") or 0)
            a["throttled"] += int(trow.get("throttled") or 0)
            a["evictions"] += int(trow.get("evictions") or 0)
            a["coldLoads"] += int(trow.get("coldLoads") or 0)
            a["residentBytes"] += int(trow.get("residentBytes") or 0)
            fill = (trow.get("quota") or {}).get("fill")
            if fill is not None:
                a["quotaFill"] = (fill if a["quotaFill"] is None
                                  else min(a["quotaFill"], fill))
    # /metrics fold: fleet-merged per-tenant histogram buckets give the
    # p99; a second scrape ``--interval`` later turns the cumulative
    # request counters into a live qps (0 disables the second scrape)
    scrapes: list[dict] = [{}, {}]
    n_scrapes = 2 if args.interval > 0 else 1
    for phase in range(n_scrapes):
        if phase == 1:
            import time as _time

            _time.sleep(args.interval)
        for url in args.urls:
            if probed[url][0] is None:
                continue
            try:
                text = _fetch_metrics_text(_metrics_url(url), args.timeout)
            except Exception:  # noqa: BLE001 - the rollup is best-effort
                continue
            scrapes[phase][url] = parse_prometheus_text(text)
    reqs: list[dict[str, float]] = [{}, {}]
    buckets: dict[str, dict[float, float]] = {}
    last = scrapes[n_scrapes - 1]
    for phase in range(n_scrapes):
        for fams in scrapes[phase].values():
            fam = fams.get("pio_tenant_requests_total") or {}
            for _s, labels, value in fam.get("samples", []):
                t = labels.get("tenant")
                if t:
                    reqs[phase][t] = reqs[phase].get(t, 0.0) + value
    for fams in last.values():
        fam = fams.get("pio_tenant_request_seconds") or {}
        for sname, labels, value in fam.get("samples", []):
            if not sname.endswith("_bucket"):
                continue
            t = labels.get("tenant")
            if not t:
                continue
            le = float({"+Inf": "inf"}.get(labels["le"], labels["le"]))
            b = buckets.setdefault(t, {})
            b[le] = b.get(le, 0.0) + value
    for t, b in buckets.items():
        q = bucket_quantiles(sorted(b.items())).get("p99")
        if q is not None:
            slot(t)["p99Ms"] = round(q * 1e3, 2)
    if n_scrapes == 2:
        for t in list(agg):
            d = reqs[1].get(t, 0.0) - reqs[0].get(t, 0.0)
            agg[t]["qps"] = round(max(0.0, d) / args.interval, 2)
    for t in sorted(agg):
        a = agg[t]
        reasons = []
        fill = a["quotaFill"]
        if a["throttled"] and fill is not None and fill <= args.fill_red:
            reasons.append(f"QUOTA EXHAUSTED (fill {fill:.2f}, "
                           f"{a['throttled']} throttled)")
        if a["evictions"] >= args.thrash_evictions:
            reasons.append(f"EVICTION THRASH ({a['evictions']} evictions "
                           f">= {args.thrash_evictions} — grow "
                           "PIO_TENANT_HBM_BUDGET or pin the tenant)")
        parts = [f"req {a['requests']}"]
        if a["qps"] is not None:
            parts.append(f"qps {a['qps']}")
        if a["p99Ms"] is not None:
            parts.append(f"p99 {a['p99Ms']}ms")
        if fill is not None:
            parts.append(f"quota fill {fill:.2f}")
        if a["throttled"]:
            parts.append(f"throttled {a['throttled']}")
        parts.append(f"resident {a['resident']}/{a['replicas']}"
                     + (" pinned" if a["pinned"] else ""))
        parts.append(f"hbm {a['residentBytes']}B")
        if a["coldLoads"]:
            parts.append(f"coldLoads {a['coldLoads']}")
        if a["evictions"]:
            parts.append(f"evictions {a['evictions']}")
        parts.extend(reasons)
        rows.append({"url": f"tenant:{t}", **a,
                     "status": ("over-quota" if reasons else "ok"),
                     "red": bool(reasons), "detail": "; ".join(parts)})
    if not rows:
        _err("tenants: nothing to report (are these multi-tenant "
             "query servers? docs/tenancy.md)")
        return 2
    if args.json:
        _out(json.dumps(rows, indent=2))
    else:
        w = max(len(r["url"]) for r in rows)
        for r in rows:
            mark = "!!" if r["red"] else "ok"
            line = f"{mark} {r['url']:<{w}}  {r['status']}"
            if r["detail"]:
                line += f"  [{r['detail']}]"
            _out(line)
    return 1 if any(r["red"] for r in rows) else 0


def _fetch_metrics_text(url: str, timeout: float = 10.0,
                        exemplars: bool = False) -> str:
    """GET one /metrics page. Module-level so tests can stub it. The
    pretty-printer asks for exemplars explicitly (``?exemplars=1``);
    ``--raw`` output must stay strict 0.0.4 — its consumers (promtool, a
    pasted scrape) never asked for exemplar suffixes."""
    import urllib.request

    if exemplars:
        url = f"{url}{'&' if '?' in url else '?'}exemplars=1"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def _metrics_url(url: str) -> str:
    url = url.rstrip("/")
    return url if url.endswith("/metrics") else url + "/metrics"


def _hist_by_labelset(samples) -> dict:
    """Histogram samples → {labelset_key: {"buckets": [(le, cum)],
    "sum": x, "count": n}}."""
    by_key: dict[tuple, dict] = {}
    for sname, labels, value in samples:
        key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        slot = by_key.setdefault(key, {"buckets": [], "sum": 0.0,
                                       "count": 0.0})
        if sname.endswith("_bucket"):
            slot["buckets"].append((float(labels["le"]), value))
        elif sname.endswith("_sum"):
            slot["sum"] = value
        elif sname.endswith("_count"):
            slot["count"] = value
    return by_key


def _label_str(key: tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in key) or "(no labels)"


def _render_metrics_single(families, args) -> None:
    import math

    from incubator_predictionio_tpu.obs.metrics import bucket_quantiles

    for name in sorted(families):
        fam = families[name]
        kind, samples = fam["type"] or "untyped", fam["samples"]
        if args.filter and args.filter not in name:
            continue
        _out(f"{name} ({kind})" + (f" — {fam['help']}" if fam["help"] else ""))
        if kind == "histogram":
            ex_by_key: dict[tuple, list] = {}
            for sname, labels, ex in fam.get("exemplars", []):
                k = tuple(sorted((lk, lv) for lk, lv in labels.items()
                                 if lk != "le"))
                ex_by_key.setdefault(k, []).append((labels.get("le", "?"),
                                                    ex))
            # per label-set: count, sum, mean, estimated quantiles
            for key, slot in sorted(_hist_by_labelset(samples).items()):
                count = slot.get("count", 0)
                mean = (slot.get("sum", 0.0) / count) if count else 0.0
                qs = bucket_quantiles(slot["buckets"])
                _out(f"  {_label_str(key)}: count={int(count)} "
                     f"mean={mean * 1e3:.3f}ms "
                     + " ".join(f"~{k}={v * 1e3:.3f}ms"
                                for k, v in qs.items()))
                for le, ex in ex_by_key.get(key, []):
                    # the bucket's exemplar links the latency straight to
                    # a showable trace (`pio-tpu trace show <id>`)
                    tid = ex.get("labels", {}).get("trace_id", "?")
                    _out(f"    exemplar le={le}: "
                         f"{ex['value'] * 1e3:.3f}ms trace={tid}")
        else:
            for sname, labels, value in sorted(
                    samples, key=lambda s: sorted(s[1].items())):
                label = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
                v = int(value) if float(value).is_integer() \
                    and not math.isinf(value) else value
                _out(f"  {label or '(no labels)'}: {v}")


def _render_metrics_fleet(pages: dict, args) -> None:
    """Merged multi-server table: one row per sample with a per-server
    column and an aggregate (sum for monotonic counters and histogram
    count/sum, max for gauges; histogram quantiles re-estimated from the
    bucket-merged fleet distribution)."""
    import math

    from incubator_predictionio_tpu.obs.metrics import bucket_quantiles

    urls = list(pages)
    aliases = {url: f"s{i + 1}" for i, url in enumerate(urls)}
    _out("servers:")
    for url in urls:
        _out(f"  {aliases[url]} = {url}")
    names = sorted({n for fams in pages.values() for n in fams})
    for name in names:
        if args.filter and args.filter not in name:
            continue
        kinds = [pages[u][name]["type"] for u in urls
                 if name in pages[u] and pages[u][name]["type"]]
        kind = kinds[0] if kinds else "untyped"
        helps = [pages[u][name]["help"] for u in urls
                 if name in pages[u] and pages[u][name]["help"]]
        _out(f"{name} ({kind})"
             + (f" — {helps[0]}" if helps else ""))
        if kind == "histogram":
            per_server = {u: _hist_by_labelset(pages[u][name]["samples"])
                          for u in urls if name in pages[u]}
            keys = sorted({k for slots in per_server.values()
                           for k in slots})
            for key in keys:
                cols = []
                merged_buckets: dict[float, float] = {}
                total_count = total_sum = 0.0
                for url in urls:
                    slot = per_server.get(url, {}).get(key)
                    if slot is None:
                        cols.append(f"{aliases[url]}=-")
                        continue
                    count = slot.get("count", 0)
                    p99 = bucket_quantiles(slot["buckets"],
                                           qs=(0.99,))["p99"]
                    cols.append(f"{aliases[url]} count={int(count)} "
                                f"~p99={p99 * 1e3:.3f}ms")
                    total_count += count
                    total_sum += slot.get("sum", 0.0)
                    for le, cum in slot["buckets"]:
                        merged_buckets[le] = merged_buckets.get(le, 0) + cum
                p99_all = bucket_quantiles(sorted(merged_buckets.items()),
                                           qs=(0.99,))["p99"]
                mean = (total_sum / total_count) if total_count else 0.0
                cols.append(f"all count={int(total_count)} "
                            f"mean={mean * 1e3:.3f}ms "
                            f"~p99={p99_all * 1e3:.3f}ms")
                _out(f"  {_label_str(key)}: " + " | ".join(cols))
        else:
            # counters sum across the fleet; gauges take the max (a depth
            # or limit summed across servers is not a meaningful number)
            agg = max if kind == "gauge" else sum
            keys = sorted({tuple(sorted(labels.items()))
                           for u in urls if name in pages[u]
                           for _, labels, _ in pages[u][name]["samples"]})
            for key in keys:
                cols, values = [], []
                for url in urls:
                    vals = [
                        v for _, labels, v
                        in pages.get(url, {}).get(name, {}).get("samples", [])
                        if tuple(sorted(labels.items())) == key]
                    if not vals:
                        cols.append(f"{aliases[url]}=-")
                        continue
                    v = vals[0]
                    values.append(v)
                    iv = int(v) if float(v).is_integer() \
                        and not math.isinf(v) else v
                    cols.append(f"{aliases[url]}={iv}")
                a = agg(values) if values else 0
                a = int(a) if float(a).is_integer() and not math.isinf(a) \
                    else a
                label = "max" if kind == "gauge" else "sum"
                cols.append(f"{label}={a}")
                _out(f"  {_label_str(key)}: " + " ".join(cols))


def cmd_metrics(args, storage) -> int:
    """Fetch and pretty-print one or more servers' ``/metrics`` pages
    (docs/observability.md). Multiple URLs (or ``--fleet``) render a merged
    table with per-server columns plus a summed/max aggregate — probes run
    concurrently (the fleet/health.py fan-out pattern), so one dead server
    costs one timeout, not O(N)."""
    from concurrent.futures import ThreadPoolExecutor

    from incubator_predictionio_tpu.obs.metrics import (
        MetricError,
        parse_prometheus_text,
    )

    urls = [_metrics_url(u) for u in args.urls]
    texts: dict[str, str] = {}
    failures: list[str] = []
    with ThreadPoolExecutor(max_workers=min(16, len(urls))) as pool:
        futures = {url: pool.submit(_fetch_metrics_text, url, args.timeout,
                                    not args.raw)
                   for url in urls}
        for url, fut in futures.items():
            try:
                texts[url] = fut.result()
            except Exception as e:  # noqa: BLE001 - a dead server is a row
                failures.append(f"{url}: {e}")
    for f in failures:
        _err(f"Unable to fetch {f}")
    if not texts:
        return 1
    if args.raw:
        for url, text in texts.items():
            if len(texts) > 1:
                _out(f"# ---- {url} ----")
            _out(text.rstrip())
        return 1 if failures else 0
    pages: dict[str, dict] = {}
    for url, text in texts.items():
        try:
            pages[url] = parse_prometheus_text(text)
        except MetricError as e:
            _err(f"{url} served malformed metrics: {e}")
            failures.append(url)
    if not pages:
        return 1
    if len(pages) == 1 and not args.fleet:
        _render_metrics_single(next(iter(pages.values())), args)
    else:
        _render_metrics_fleet(pages, args)
    return 1 if failures else 0


def _fetch_json(url: str, timeout: float = 10.0) -> dict:
    """GET one JSON document. Module-level so tests can stub it."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def cmd_profile(args, storage) -> int:
    """Fetch and render a server's ``GET /profile.json`` — the continuous
    profiler's live document (docs/observability.md "Profiling"): per-scope
    phase attribution (where the step time goes), the wall-stack sampler's
    top-N (when PIO_PROFILE_HZ > 0), training MFU, and device-memory
    watermarks."""
    url = args.url.rstrip("/") + "/profile.json"
    try:
        doc = _fetch_json(url, args.timeout)
    except Exception as e:  # noqa: BLE001 - a dead server is the answer
        _err(f"Unable to fetch {url}: {e}")
        return 1
    if args.json:
        _out(json.dumps(doc, indent=2))
        return 0
    _out(f"service: {doc.get('service', '?')}")
    phases = doc.get("phases") or {}
    if not phases:
        _out("phases: none recorded yet")
    for scope in sorted(phases):
        e = phases[scope]
        wall = e.get("wall_seconds", 0.0)
        _out(f"{scope}: wall {wall:.3f}s over {e.get('count', 0)} scope(s)")
        for p, ph in sorted((e.get("phases") or {}).items(),
                            key=lambda kv: -kv[1]["seconds"]):
            pct = 100.0 * ph["seconds"] / wall if wall else 0.0
            _out(f"  {p:<12} {ph['seconds']:9.3f}s  {pct:5.1f}%  "
                 f"({ph['count']} interval(s))")
    tr = doc.get("training") or {}
    if tr.get("mfu"):
        peak = tr.get("peak_flops")
        _out(f"training MFU: {tr['mfu'] * 100:.1f}%"
             + (f" of {peak:.3g} FLOP/s peak" if peak else ""))
    for dev, v in sorted((doc.get("deviceWatermark") or {}).items()):
        _out(f"device {dev}: peak {v / 2**20:.1f} MiB")
    sampler = doc.get("sampler")
    if sampler is None:
        _out("sampler: off (set PIO_PROFILE_HZ to enable the wall-stack "
             "profiler)")
        return 0
    _out(f"sampler: {sampler['hz']:g} Hz, {sampler['samples']} sample(s)")
    for i, row in enumerate(sampler.get("top") or [], 1):
        stack = row.get("stack") or ["?"]
        _out(f"  #{i:<3}{row['pct']:5.1f}%  ({row['samples']})  {stack[0]}")
        for frame in stack[1:]:
            _out(f"          {frame}")
    return 0


def _load_history_records(source: str, since, timeout: float) -> list:
    """History records from a PIO_HISTORY_DIR (durable segments) or a
    server base URL (the live in-memory ring via /history.json)."""
    from incubator_predictionio_tpu.obs import history as hist

    if source.startswith("http://") or source.startswith("https://"):
        url = source.rstrip("/") + "/history.json"
        if since is not None:
            url += f"?since={since:g}"
        return _fetch_json(url, timeout).get("records") or []
    return hist.read_history(source, since=since)


def cmd_history(args, storage) -> int:
    """Inspect the durable metrics history (docs/observability.md "Metrics
    history & SLOs"): a PIO_HISTORY_DIR's CRC-framed segments, or a live
    server's in-memory ring over ``GET /history.json``. Without --series,
    summarizes what is recorded; with --series (glob over family names),
    prints the matching time series (counters additionally as per-interval
    rates)."""
    from incubator_predictionio_tpu.obs import history as hist

    try:
        records = _load_history_records(args.source, args.since, args.timeout)
    except Exception as e:  # noqa: BLE001 - dead server / bad dir is the answer
        _err(f"history: unable to read {args.source}: {e}")
        return 1
    if not records:
        _out(f"history: no records in {args.source}")
        return 1
    if args.json and not args.series:
        _out(json.dumps(records, indent=2))
        return 0
    services = sorted({r.get("service", "?") for r in records})
    span = records[-1]["t"] - records[0]["t"]
    if not args.series:
        _out(f"{len(records)} snapshot(s) over {span:.0f}s from "
             f"{', '.join(services)}")
        types = hist.merged_types(records)
        for name in hist.list_series(records):
            count = sum(1 for r in records
                        if any(s[0] == name for s in r["samples"]))
            kind = types.get(name.split("_bucket")[0], "")
            _out(f"  {name:<48} {count:>6} point(s)"
                 + (f"  [{kind}]" if kind else ""))
        return 0
    types = hist.merged_types(records)
    matched = hist.list_series(records, pattern=args.series)
    if not matched:
        _err(f"history: no series match {args.series!r}")
        return 1
    out_doc = {}
    for name in matched:
        points = hist.series(records, name)
        kind = types.get(name, "")
        if args.json:
            out_doc[name] = points
            continue
        _out(f"{name}" + (f" ({kind})" if kind else ""))
        shown = (hist.rate_series(points)
                 if kind == "counter" and len(points) > 1 else points)
        for t, v in shown[-args.limit:]:
            vv = int(v) if float(v).is_integer() else round(v, 6)
            _out(f"  {t:.0f}  {vv}")
        if kind == "counter" and len(points) > 1:
            _out(f"  (per-second rates; cumulative "
                 f"{points[-1][1]:g} at t={points[-1][0]:.0f})")
    if args.json:
        _out(json.dumps(out_doc, indent=2))
    return 0


def _top_snapshot(url: str, timeout: float) -> dict:
    """One server's 'top' row source: the parsed /metrics families."""
    from incubator_predictionio_tpu.obs.metrics import parse_prometheus_text

    return parse_prometheus_text(
        _fetch_metrics_text(_metrics_url(url), timeout))


def _top_row(url: str, fams: dict, prev: Optional[tuple],
             now: float) -> tuple[str, tuple]:
    """Render one server's top line; returns (line, state-for-next-tick).
    qps derives from the pio_http_requests_total delta between refreshes."""
    from incubator_predictionio_tpu.obs.metrics import bucket_quantiles

    def total(family: str) -> Optional[float]:
        fam = fams.get(family)
        if fam is None:
            return None
        vals = [v for n, _l, v in fam["samples"] if n == family]
        return sum(vals) if vals else None

    reqs = total("pio_http_requests_total")
    qps = None
    if reqs is not None and prev is not None and now > prev[0]:
        qps = max(0.0, (reqs - prev[1])) / (now - prev[0])
    parts = []
    parts.append(f"qps={qps:.1f}" if qps is not None else "qps=-")
    lat = fams.get("pio_http_request_seconds")
    if lat is not None:
        merged: dict[float, float] = {}
        for n, labels, v in lat["samples"]:
            if n.endswith("_bucket"):
                le = float(labels["le"])
                merged[le] = merged.get(le, 0.0) + v
        if merged:
            p99 = bucket_quantiles(sorted(merged.items()), qs=(0.99,))["p99"]
            parts.append(f"p99={p99 * 1e3:.1f}ms")
    rss = total("pio_process_rss_bytes")
    if rss:
        parts.append(f"rss={rss / 2**20:.0f}MiB")
    fds = total("pio_process_open_fds")
    if fds:
        parts.append(f"fds={int(fds)}")
    lag_fam = fams.get("pio_process_loop_lag_seconds")
    if lag_fam is not None and lag_fam["samples"]:
        lag = max(v for _n, _l, v in lag_fam["samples"])
        parts.append(f"lag={lag * 1e3:.1f}ms")
    mfu = total("pio_training_mfu")
    if mfu:
        parts.append(f"mfu={mfu * 100:.1f}%")
    compiles = total("pio_jit_compile_seconds_total")
    if compiles:
        parts.append(f"jit={compiles:.1f}s")
    breaching = total("pio_slo_breaching")
    mark = "ok"
    if breaching:
        parts.append(f"SLO_BREACH={int(breaching)}")
        mark = "!!"
    return f"{mark} {url}  " + " ".join(parts), (now, reqs)


def cmd_top(args, storage) -> int:
    """Live-refreshing one-line-per-server view of the performance plane
    (docs/observability.md): qps (from the requests-counter delta between
    refreshes), fleet p99, RSS/FDs/loop-lag, training MFU, cumulative jit
    compile seconds, and SLO breach state. ``-n 1`` prints once (scripts);
    the default refreshes until interrupted."""
    import time as _time

    prev: dict[str, tuple] = {}
    iteration = 0
    while True:
        iteration += 1
        lines = []
        for url in args.urls:
            now = _time.time()
            try:
                fams = _top_snapshot(url, args.timeout)
            except Exception as e:  # noqa: BLE001 - a dead server is a row
                lines.append(f"!! {url}  unreachable: {e}")
                continue
            line, state = _top_row(url, fams, prev.get(url), now)
            prev[url] = state
            lines.append(line)
        if args.iterations != 1 and sys.stdout.isatty():
            sys.stdout.write("\x1b[2J\x1b[H")
        _out(_time.strftime("%H:%M:%S") + f"  refresh {iteration}")
        for line in lines:
            _out(line)
        if args.iterations and iteration >= args.iterations:
            return 0
        try:
            _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def cmd_slo(args, storage) -> int:
    """SLO config validation and offline burn-rate verdicts
    (docs/observability.md "Metrics history & SLOs").

    ``--check <config>`` validates the objectives file and exits non-zero
    with named-position errors on any defect — the CI gate for config
    drift. With a history source (PIO_HISTORY_DIR or server URL), loads
    the config (--config, else $PIO_SLO_CONFIG), evaluates every objective
    over the recorded windows, prints the verdict table, and exits
    non-zero when any objective is breaching."""
    from incubator_predictionio_tpu.obs import slo as slomod

    if args.check:
        try:
            objectives = slomod.load_config(args.check)
        except slomod.SloConfigError as e:
            _err(f"slo: {args.check} INVALID:")
            for err in e.errors:
                _err(f"  {err}")
            return 1
        _out(f"slo: {args.check} OK — {len(objectives)} objective(s)")
        for o in objectives:
            line = (f"  {o['name']}: {o['type']} on {o['service']} "
                    f"objective={o['objective']:g}")
            if o.get("threshold_ms") is not None:
                line += f" threshold={o['threshold_ms']:g}ms"
            _out(line)
        if not args.source:
            return 0
    if not args.source:
        _err("slo: give a history dir / server URL, or --check <config>")
        return 2
    cfg_path = args.config or (args.check if args.check else None) \
        or os.environ.get(slomod.ENV_CONFIG)
    if not cfg_path:
        _err("slo: no objectives config (--config, --check, or "
             "PIO_SLO_CONFIG)")
        return 2
    try:
        objectives = slomod.load_config(cfg_path)
    except slomod.SloConfigError as e:
        _err(f"slo: {cfg_path} INVALID:")
        for err in e.errors:
            _err(f"  {err}")
        return 1
    try:
        records = _load_history_records(args.source, args.since,
                                        args.timeout)
    except Exception as e:  # noqa: BLE001
        _err(f"slo: unable to read {args.source}: {e}")
        return 1
    if not records:
        _err(f"slo: no history records in {args.source}")
        return 1
    verdicts = slomod.evaluate(objectives, records)
    if args.json:
        _out(json.dumps(verdicts, indent=2))
        return 1 if any(v["breaching"] for v in verdicts) else 0
    for v in verdicts:
        mark = "!!" if v["breaching"] else ("??" if v["no_data"] else "ok")
        line = f"{mark} {v['name']} ({v['type']} on {v['service']})"
        if v["budget_remaining"] is not None:
            line += f"  budget {v['budget_remaining'] * 100:.2f}%"
        _out(line)
        for wname, w in sorted(v["windows"].items()):
            bs = "-" if w["burn_short"] is None else f"{w['burn_short']:.2f}"
            bl = "-" if w["burn_long"] is None else f"{w['burn_long']:.2f}"
            _out(f"    {wname}: burn {bs}x/{bl}x "
                 f"({w['short_sec']:g}s/{w['long_sec']:g}s windows, "
                 f"threshold {w['threshold']:g}x)"
                 + ("  BREACHING" if w["breaching"] else ""))
    return 1 if any(v["breaching"] for v in verdicts) else 0


def cmd_trace(args, storage) -> int:
    """Assemble cross-process traces from span spools and/or live servers
    (docs/observability.md "The trace plane"): ``list`` recent traces,
    ``show <id>`` one trace's terminal waterfall, ``slowest`` the worst
    offenders — the answer to "which hop made this p99 query slow?"."""
    from incubator_predictionio_tpu.obs import collect

    if not getattr(args, "trace_command", None):
        _err("trace: missing subcommand (list|show|slowest)")
        return 1
    spools = list(args.spool or ())
    urls = list(args.url or ())
    if not spools and not urls:
        default_dir = os.environ.get("PIO_TRACE_SPOOL_DIR")
        if default_dir:
            spools = [default_dir]
        else:
            _err("trace: give at least one --spool DIR or --url URL "
                 "(or set PIO_TRACE_SPOOL_DIR)")
            return 2
    spans, problems = collect.gather_spans(
        spools=spools, urls=urls, timeout=args.timeout)
    for p in problems:
        _err(f"trace: {p}")
    traces = collect.assemble(spans)
    if args.trace_command == "show":
        tree, matches = collect.find_trace(traces, args.trace_id)
        if tree is None:
            if matches:
                _err(f"trace prefix {args.trace_id!r} is ambiguous — "
                     f"{len(matches)} match: " + ", ".join(matches[:8]))
            else:
                _err(f"trace {args.trace_id!r} not found "
                     f"({len(traces)} trace(s) in the given sources)")
            return 1
        if args.json:
            _out(json.dumps(tree, indent=2, default=str))
        else:
            for line in collect.waterfall(tree):
                _out(line)
        return 0
    if args.trace_command == "slowest":
        picked = collect.slowest(traces, args.limit)
        if args.json:
            _out(json.dumps(
                {"slowest": collect.list_rows(picked),
                 "waterfall": (collect.waterfall(picked[0])
                               if picked else [])}, indent=2, default=str))
            return 0
        for row in collect.list_rows(picked):
            _out(f"{row['traceId']}  {row['durationMs']:>9.1f}ms  "
                 f"spans={row['spans']} errors={row['errors']} "
                 f"complete={str(row['complete']).lower()}  "
                 f"[{row['services']}]  {row['root']}")
        if picked:
            _out("")
            for line in collect.waterfall(picked[0]):
                _out(line)
        return 0
    # list (default)
    rows = collect.list_rows(traces[:args.limit])
    if args.json:
        _out(json.dumps({"traces": rows}, indent=2, default=str))
        return 0
    if not rows:
        _out("No traces in the given sources.")
        return 0
    for row in rows:
        _out(f"{row['traceId']}  {row['durationMs']:>9.1f}ms  "
             f"spans={row['spans']} errors={row['errors']} "
             f"complete={str(row['complete']).lower()}  "
             f"[{row['services']}]  {row['root']}")
    return 0


# ---------------------------------------------------------------------------
# fleet: router / rolling deploy / experiment (docs/serving.md
# "Fleet serving")
# ---------------------------------------------------------------------------

def cmd_fleet_route(args, storage) -> int:
    """Run the fleet router server over the given replicas."""
    from incubator_predictionio_tpu.fleet.experiments import Experiment
    from incubator_predictionio_tpu.fleet.router import (
        RouterConfig,
        serve_forever,
    )

    experiment = None
    if args.experiment_weight is not None:
        if not args.candidate:
            # refuse rather than silently run 100% control: the operator
            # believes an experiment is live (matches the runtime path,
            # where POST /experiment without candidates answers 409)
            _err("--experiment-weight needs at least one --candidate "
                 "replica to route the candidate arm to")
            return 2
        experiment = Experiment(
            name=args.experiment_name, mode=args.experiment_mode,
            weight=args.experiment_weight,
            hash_field=args.experiment_hash_field)
    kw = {}
    for flag, key in (("deadline", "deadline_sec"),
                      ("retries", "max_attempts"),
                      ("health_interval", "health_interval_sec"),
                      ("probe_timeout", "probe_timeout_sec"),
                      ("eject_threshold", "eject_threshold")):
        v = getattr(args, flag)
        if v is not None:  # unset flags keep the PIO_FLEET_* env defaults
            kw[key] = v
    serve_forever(RouterConfig(
        replicas=tuple(args.replica),
        candidates=tuple(args.candidate or ()),
        ip=args.ip, port=args.port,
        server_access_key=args.server_access_key,
        experiment=experiment, **kw))
    return 0


def cmd_fleet_rollout(args, storage) -> int:
    """Sequential fleet rolling deploy with halt-and-rollback
    (fleet/rollout.py). Exits non-zero on a halt, even when the rollback
    repaired every replica — a halted rollout is a failed deploy."""
    from incubator_predictionio_tpu.fleet.rollout import (
        RolloutConfig,
        run_rollout,
    )

    result = run_rollout(RolloutConfig(
        replicas=tuple(args.replicas),
        server_access_key=args.server_access_key,
        observe_sec=args.observe, poll_sec=args.poll,
        timeout_sec=args.timeout))
    if args.json:
        _out(json.dumps({
            "ok": result.ok, "updated": result.updated,
            "rolledBack": result.rolled_back,
            "haltedAt": result.halted_at, "reason": result.reason,
            "events": result.events}, indent=2))
    else:
        for line in result.events:
            _out(line)
        _out("ROLLOUT " + ("OK" if result.ok else
                           f"HALTED at {result.halted_at}: {result.reason}"))
    return 0 if result.ok else 1


def _arm_stats_from_metrics(families: dict) -> dict:
    """Per-arm request/error/latency stats from a router's /metrics page
    (pio_fleet_arm_* families; docs/observability.md)."""
    from incubator_predictionio_tpu.obs.metrics import bucket_quantiles

    arms: dict[str, dict] = {}

    def slot(arm: str) -> dict:
        return arms.setdefault(arm, {
            "requests": 0, "errors": 0, "buckets": [],
            "latency_sum": 0.0, "latency_count": 0})

    fam = families.get("pio_fleet_arm_requests_total")
    for _, labels, value in (fam["samples"] if fam else ()):
        s = slot(labels.get("arm", "?"))
        s["requests"] += int(value)
        if labels.get("status", "").startswith("5"):
            s["errors"] += int(value)
    fam = families.get("pio_fleet_arm_latency_seconds")
    for sname, labels, value in (fam["samples"] if fam else ()):
        s = slot(labels.get("arm", "?"))
        if sname.endswith("_bucket"):
            s["buckets"].append((float(labels["le"]), value))
        elif sname.endswith("_sum"):
            s["latency_sum"] += value
        elif sname.endswith("_count"):
            s["latency_count"] += int(value)
    out = {}
    for arm, s in arms.items():
        qs = bucket_quantiles(s["buckets"]) if s["buckets"] else {}
        out[arm] = {
            "requests": s["requests"],
            "errorRate": round(s["errors"] / s["requests"], 4)
            if s["requests"] else 0.0,
            "meanMs": round(1e3 * s["latency_sum"]
                            / max(1, s["latency_count"]), 2),
            "p95Ms": round(qs.get("p95", 0.0) * 1e3, 2),
        }
    return out


def _experiment_verdict(arms: dict) -> str:
    """Promote-or-abort reading of the live per-arm evidence. Advisory —
    the operator promotes by redeploying the control fleet, the CLI only
    names what the numbers say."""
    control, candidate = arms.get("control"), arms.get("candidate")
    if not control or not candidate:
        return "insufficient data (need traffic on both arms)"
    if candidate["requests"] < 20:
        return f"continue (candidate has {candidate['requests']} requests)"
    if candidate["errorRate"] > control["errorRate"] + 0.01:
        return (f"ABORT: candidate error rate {candidate['errorRate']:.2%} "
                f"vs control {control['errorRate']:.2%}")
    if control["p95Ms"] and candidate["p95Ms"] > 1.5 * control["p95Ms"]:
        return (f"ABORT: candidate p95 {candidate['p95Ms']}ms vs control "
                f"{control['p95Ms']}ms")
    return "PROMOTE-worthy: error rate and latency within control's band"


def cmd_fleet_experiment(args, storage) -> int:
    """Inspect (default), start (--start), or stop (--stop) the A/B /
    shadow experiment on a running router, with per-arm live evidence
    from the router's /metrics."""
    import urllib.request

    from incubator_predictionio_tpu.obs.metrics import parse_prometheus_text

    base = args.router_url.rstrip("/")
    auth = (f"?accessKey={args.server_access_key}"
            if args.server_access_key else "")
    if args.start or args.stop:
        body = (json.dumps({"stop": True}).encode() if args.stop
                else json.dumps({
                    "name": args.start, "mode": args.mode,
                    "weight": args.weight,
                    "hashField": args.hash_field}).encode())
        req = urllib.request.Request(
            f"{base}/experiment{auth}", data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                _out(json.loads(resp.read()).get("message", "ok"))
        except Exception as e:  # noqa: BLE001
            _err(f"experiment update failed: {e}")
            return 1
        return 0
    try:
        with urllib.request.urlopen(f"{base}/experiment.json",
                                    timeout=10) as resp:
            state = json.loads(resp.read())
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            arms = _arm_stats_from_metrics(
                parse_prometheus_text(resp.read().decode()))
    except Exception as e:  # noqa: BLE001
        _err(f"Unable to read {base}: {e}")
        return 1
    exp = state.get("experiment")
    payload = {"experiment": exp, "arms": arms,
               "verdict": _experiment_verdict(arms) if exp else None}
    if args.json:
        _out(json.dumps(payload, indent=2))
        return 0
    if exp is None:
        _out("no experiment running")
        return 0
    _out(f"experiment {exp['name']}: mode={exp['mode']} "
         f"weight={exp['weight']} hashField={exp['hashField']}")
    _out(f"  assigned: {exp['assigned']}")
    for arm in ("control", "candidate"):
        if arm in arms:
            a = arms[arm]
            _out(f"  {arm:<10} requests={a['requests']} "
                 f"errorRate={a['errorRate']:.2%} mean={a['meanMs']}ms "
                 f"p95={a['p95Ms']}ms")
    _out(f"  verdict: {payload['verdict']}")
    return 0


# ---------------------------------------------------------------------------
# jobs: continuous-training control plane (docs/jobs.md)
# ---------------------------------------------------------------------------

def _job_orchestrator(storage: Storage):
    from incubator_predictionio_tpu.jobs import Orchestrator

    return Orchestrator(storage.get_meta_data_jobs())


def _job_params_from_args(args) -> dict:
    params: dict = {"engine_variant": args.engine_variant}
    if getattr(args, "batch", None):
        params["batch"] = args.batch
    if getattr(args, "server_url", None):
        params["server_url"] = args.server_url
    if getattr(args, "replica", None):
        params["replicas"] = list(args.replica)
    if getattr(args, "server_access_key", None):
        params["server_access_key"] = args.server_access_key
    if getattr(args, "mesh_axes", None):
        params["mesh_axes"] = json.loads(args.mesh_axes)
    if getattr(args, "evaluation_class", None):
        params["evaluation_class"] = args.evaluation_class
    if getattr(args, "no_gate", False):
        params["gate"] = "off"
    if getattr(args, "dist", 0):
        if args.kind != "train":
            raise SystemExit("jobs submit: --dist applies to --kind train")
        params["dist"] = int(args.dist)
        if getattr(args, "dist_state_dir", None):
            params["dist_state_dir"] = args.dist_state_dir
    if getattr(args, "params", None):
        params.update(json.loads(args.params))
    return params


def cmd_jobs_submit(args, storage: Storage) -> int:
    orch = _job_orchestrator(storage)
    job = orch.submit(
        args.kind, params=_job_params_from_args(args), trigger="manual",
        dedupe_key=(f"train:{os.path.abspath(args.engine_variant)}"
                    if args.kind == "train" and not args.no_dedupe else ""),
        max_attempts=args.max_attempts)
    _out(f"Submitted {job.kind} job {job.id} (status {job.status}, "
         f"attempt {job.attempt}/{job.max_attempts}).")
    _out("Run `pio-tpu jobs worker` somewhere to execute it; "
         f"`pio-tpu jobs watch {job.id}` follows it.")
    return 0


def _job_row(j, now: float) -> dict:
    lease = None
    if j.status == "RUNNING" and j.lease_expires_at is not None:
        lease = round(j.lease_expires_at.timestamp() - now, 1)
    summary = ""
    if j.status == "COMPLETED":
        summary = j.result.get("instanceId") or ""
        gate = j.result.get("gate") or {}
        if gate.get("verdict"):
            summary += f" gate={gate['verdict']}"
    elif j.failure:
        summary = j.failure.splitlines()[-1][:80]
    return {"id": j.id, "kind": j.kind, "status": j.status,
            "trigger": j.trigger, "attempt": f"{j.attempt}/{j.max_attempts}",
            "fence": j.fence, "leaseSecLeft": lease,
            "owner": j.lease_owner or "",
            "submittedAt": j.submitted_at.isoformat()
            if j.submitted_at else None,
            "summary": summary}


def cmd_jobs_list(args, storage: Storage) -> int:
    import time as _time

    orch = _job_orchestrator(storage)
    jobs = sorted(orch.jobs.get_all(),
                  key=lambda j: (j.submitted_at.timestamp()
                                 if j.submitted_at else 0.0, j.id))
    if not args.all:
        # active + the most recent terminal few — the operator's default view
        terminal = [j for j in jobs if not j.active][-10:]
        jobs = [j for j in jobs if j.active] + terminal
        jobs.sort(key=lambda j: (j.submitted_at.timestamp()
                                 if j.submitted_at else 0.0, j.id))
    rows = [_job_row(j, _time.time()) for j in jobs]
    if args.json:
        _out(json.dumps(rows, indent=2))
        return 0
    if not rows:
        _out("No jobs.")
        return 0
    _out(f"{'ID':<12} {'KIND':<12} {'STATUS':<10} {'TRIGGER':<10} "
         f"{'ATT':<5} {'LEASE':<8} SUMMARY")
    for r in rows:
        lease = ("-" if r["leaseSecLeft"] is None
                 else f"{r['leaseSecLeft']:+.0f}s")
        _out(f"{r['id'][:12]:<12} {r['kind']:<12} {r['status']:<10} "
             f"{r['trigger']:<10} {r['attempt']:<5} {lease:<8} "
             f"{r['summary']}")
    return 0


def cmd_jobs_watch(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.jobs import wait_for_job

    orch = _job_orchestrator(storage)
    try:
        j = wait_for_job(orch, args.id, timeout=args.timeout,
                         poll=args.poll)
    except KeyError:
        _err(f"No job {args.id}.")
        return 1
    except TimeoutError as e:
        _err(str(e))
        return 1
    _out(json.dumps(_job_row(j, __import__("time").time()), indent=2))
    if j.status == "COMPLETED":
        return 0
    if j.failure:
        _err(j.failure.splitlines()[-1])
    return 1


def cmd_jobs_cancel(args, storage: Storage) -> int:
    j = _job_orchestrator(storage).cancel(args.id)
    if j is None:
        _err(f"Job {args.id} is not active (or does not exist).")
        return 1
    _out(f"Cancelled job {j.id} (a running worker is fenced off at its "
         "next heartbeat; no deploy can land).")
    return 0


def cmd_jobs_retry(args, storage: Storage) -> int:
    j = _job_orchestrator(storage).retry(args.id)
    if j is None:
        _err(f"Job {args.id} is not terminal (or does not exist).")
        return 1
    _out(f"Requeued job {j.id} with a fresh attempt budget.")
    return 0


def cmd_jobs_prune(args, storage: Storage) -> int:
    n = _job_orchestrator(storage).prune(
        keep_terminal=args.keep,
        max_age_sec=args.older_than)
    _out(f"Pruned {n} terminal job(s).")
    return 0


def cmd_jobs_worker(args, storage: Storage) -> int:
    from incubator_predictionio_tpu.jobs import JobWorker, WorkerConfig

    cfg = WorkerConfig.from_env()
    if args.lease is not None:
        cfg = dataclasses_replace(cfg, lease_sec=args.lease)
    if args.poll is not None:
        cfg = dataclasses_replace(cfg, poll_sec=args.poll)
    worker = JobWorker(_job_orchestrator(storage), storage, cfg)
    _out(f"jobs worker {worker.config.worker_id} polling "
         f"(lease {worker.config.lease_sec:.0f}s).")
    obs_handle = None
    if args.obs_port:
        # the worker has no HTTP surface of its own; this thread serves
        # the shared /metrics + /traces.json so pio_jobs_* is scrapeable
        from incubator_predictionio_tpu.obs.http import start_obs_server

        obs_handle = start_obs_server("jobs_worker", args.obs_port,
                                      ip=args.obs_ip)
    try:
        if args.once:
            out = worker.run_once()
            if out is None:
                _out("Queue idle.")
                return 0
            _out(json.dumps(out, default=str))
            return 0 if out.get("status") in ("COMPLETED",) else 1
        worker.run_forever(max_jobs=args.max_jobs)
        return 0
    finally:
        if obs_handle is not None:
            obs_handle.close()


def cmd_jobs_triggers(args, storage: Storage) -> int:  # noqa: C901
    from incubator_predictionio_tpu.jobs import TriggerConfig, TriggerLoop

    overrides: dict = {
        "engine_variant": args.engine_variant,
        "server_url": args.server_url,
        "replicas": tuple(args.replica or ()),
        "server_access_key": args.server_access_key,
        "poll_sec": args.poll,
    }
    if args.interval is not None:
        overrides["interval_sec"] = args.interval
    if args.drift_events is not None:
        overrides["drift_events"] = args.drift_events
    if args.state_dir:
        overrides["stream_state_dir"] = args.state_dir
    if args.app:
        overrides["app_name"] = args.app
    loop = TriggerLoop(_job_orchestrator(storage), storage,
                       TriggerConfig.from_env(**overrides))
    if args.once:
        jobs = loop.run_once()
        _out(json.dumps([{"id": j.id, "trigger": j.trigger,
                          "status": j.status} for j in jobs]))
        return 0
    _out("jobs trigger loop running "
         f"(interval={loop.config.interval_sec or 'off'} "
         f"drift={loop.config.drift_events or 'off'} "
         f"quarantine={'on' if loop.config.stream_state_dir else 'off'}).")
    loop.run_forever()
    return 0


# ---------------------------------------------------------------------------
# store: replicated-storage admin (docs/replication.md)
# ---------------------------------------------------------------------------

def _store_rpc(url: str, verb: str, payload: dict, key=None, timeout=10.0):
    from incubator_predictionio_tpu.replication.manager import default_rpc

    return default_rpc(url, verb, payload, key=key, timeout=timeout)


def cmd_store_status(args, storage) -> int:
    """Per-replica replication state from each storage server's /health:
    role, epoch, fenced-write tally, per-peer lag. Exits non-zero when
    any replica is unreachable, fenced, or beyond the lag bound."""
    from incubator_predictionio_tpu.fleet.health import (
        probe_health_urls,
        replication_flags,
    )

    probed = probe_health_urls(args.urls, args.timeout,
                               fetch=lambda u, t: _fetch_health(u, t))
    red = False
    rows = []
    for url in args.urls:
        h, err = probed[url]
        repl = replication_flags(h)
        if h is None:
            rows.append({"url": url, "error": err})
            red = True
            continue
        row = {"url": url, "status": h.get("status"),
               "replication": h.get("replication")}
        rows.append(row)
        if repl is None:
            red = True  # a storage replica without a replication section
        else:
            red = red or repl["red"]
    if args.json:
        _out(json.dumps(rows, indent=2))
        return 1 if red else 0
    w = max(len(r["url"]) for r in rows)
    for r in rows:
        if "error" in r:
            _out(f"!! {r['url']:<{w}}  unreachable  [{r['error']}]")
            continue
        repl = r.get("replication")
        if repl is None:
            # reachable but replication is OFF — red (the operator asked
            # about a replica set; an unreplicated member is the finding)
            _out(f"!! {r['url']:<{w}}  replication not configured "
                 "(--repl-peer / PIO_REPL_PEERS)")
            continue
        line = (f"{'!!' if (repl.get('fenced') or repl.get('lagExceeded')) else 'ok'} "
                f"{r['url']:<{w}}  {repl.get('role', '?')}@"
                f"{repl.get('epoch', '?')}")
        if repl.get("fenced"):
            line += f"  FENCED (writes rejected: {repl.get('fencedWrites', 0)})"
        if repl.get("role") == "primary":
            for peer, st in (repl.get("peers") or {}).items():
                line += (f"\n     -> {peer}: lag {st.get('lagBytes', '?')}B"
                         f"{'' if st.get('reachable') else ' UNREACHABLE'}"
                         f"{' DIVERGED' if st.get('diverged') else ''}")
        elif repl.get("contactAgeSeconds") is not None:
            line += f"  last primary contact {repl['contactAgeSeconds']}s ago"
        _out(line)
    return 1 if red else 0


def cmd_store_promote(args, storage) -> int:
    """Promote a follower storage server to primary (the failover step):
    bumps its persisted epoch, re-opens its logs writable, and (via
    --peer) reconfigures its replica set — on failover the dead primary
    is removed until `store scrub` repairs and rejoins it. The old
    primary, wherever it resurfaces, is epoch-fenced from then on."""
    payload: dict = {}
    if args.peer is not None:
        payload["peers"] = list(args.peer)
    try:
        status, body = _store_rpc(args.url, "promote", payload,
                                  key=args.server_access_key)
    except OSError as e:
        _err(f"promote failed: {args.url} unreachable: {e}")
        return 1
    if status != 200:
        _err(f"promote failed: {status} {body.get('message', body)}")
        return 1
    _out(f"{args.url} promoted: role={body['role']} epoch={body['epoch']}")
    return 0


def cmd_store_scrub(args, storage) -> int:
    """Anti-entropy: exchange per-segment CRC digests between the primary
    and each follower, repair divergence/bitrot by re-fetching the
    authoritative range, and verify the copies come back bit-identical
    (docs/replication.md scrub playbook). --check-only detects without
    repairing. Exits non-zero when any follower could not be verified."""
    from incubator_predictionio_tpu.replication.scrub import (
        ScrubError,
        scrub_follower,
    )

    rpc = lambda url, verb, payload: _store_rpc(  # noqa: E731
        url, verb, payload, key=args.server_access_key)
    ok = True
    out = {}
    for follower in args.followers:
        try:
            report = scrub_follower(args.primary, follower, rpc,
                                    segment_bytes=args.segment_bytes,
                                    repair=not args.check_only)
        except ScrubError as e:
            _err(f"scrub {follower}: {e}")
            ok = False
            continue
        out[follower] = report
        ok = ok and report["clean"]
        if not args.json:
            state = ("clean" if report["divergentSegments"] == 0 else
                     ("REPAIRED" if report["clean"] else "DIVERGENT"))
            _out(f"{follower}: {state} — "
                 f"{report['divergentSegments']} divergent segment(s), "
                 f"{report['repairedBytes']} byte(s) repaired")
            for name, row in sorted(report["logs"].items()):
                if row["divergent"] or not row["verified"]:
                    _out(f"  {name}: divergent at offsets {row['divergent']}"
                         f" (primary {row['sizePrimary']}B / follower "
                         f"{row['sizeFollower']}B) verified="
                         f"{row['verified']}")
    if args.json:
        _out(json.dumps(out, indent=2))
    return 0 if ok else 1


def _backup_source(args, storage):
    from incubator_predictionio_tpu.backup import source_from_storage

    src = source_from_storage(
        storage,
        eventlog_dir=args.eventlog_dir,
        wal_dir=args.wal_dir,
        stream_state_dir=args.stream_state_dir,
        device_models_dir=args.device_models_dir,
        checkpoint_dirs=tuple(args.checkpoint_dir or ()),
    )
    if args.no_meta:
        src = dataclasses_replace(src, storage=None)
    return src


def cmd_backup_create(args, storage: Storage) -> int:
    """Take one consistent point-in-time backup (docs/dr.md): eventlog
    segments up to a cut, the spill WAL, streaming state, model sidecars,
    and a metadata dump via the DAO dump/load contract. Incremental by
    default (append-only segments ⇒ only new extents copied); the entry
    self-verifies before this verb reports success."""
    from incubator_predictionio_tpu.backup import BackupError, create_backup

    src = _backup_source(args, storage)
    if not src.components() and src.storage is None:
        _err("backup create: nothing to back up (no --eventlog-dir / "
             "--wal-dir / --stream-state-dir / ... resolved, and --no-meta "
             "set)")
        return 2
    try:
        report = create_backup(args.backup_dir, src,
                               incremental=not args.full,
                               include_meta=not args.no_meta)
    except BackupError as e:
        _err(f"backup create failed: {e}")
        return 1
    if args.json:
        _out(json.dumps(report, indent=2))
    else:
        v = report.get("verify") or {}
        _out(f"backup {report['backupId']} (seq {report['seq']}"
             + (f", incremental on {report['parent']}" if report["parent"]
                else ", full") + ")")
        _out(f"  files: {report['files']}  stored: {report['bytesStored']}B"
             f"  logical: {report['bytesLogical']}B")
        for path, cut in sorted(report["cuts"].items()):
            _out(f"  cut {path} @ {cut}")
        _out(f"  verify: {'clean' if v.get('clean') else 'FAILED'}")
        for err in (v.get("errors") or [])[:8]:
            _err(f"    {err}")
    return 0 if (report.get("verify") or {}).get("clean") else 1


def cmd_backup_verify(args, storage) -> int:
    """Re-verify a backup entry end to end: chain integrity, per-window
    CRC digests of every logical file, and cut/record-boundary
    consistency. The verdict lands in the entry's verify.json, which the
    `pio-tpu health --backup-dir` row reads."""
    from incubator_predictionio_tpu.backup import BackupError, verify_backup

    try:
        report = verify_backup(args.backup_dir, args.id)
    except BackupError as e:
        _err(f"backup verify failed: {e}")
        return 1
    if args.json:
        _out(json.dumps(report, indent=2))
    else:
        _out(f"backup {report['backupId']}: "
             f"{'clean' if report['clean'] else 'FAILED'} "
             f"({report['filesChecked']} file(s), "
             f"{report['bytesChecked']}B in {report['seconds']}s)")
        for err in report["errors"][:16]:
            _err(f"  {err}")
    return 0 if report["clean"] else 1


def cmd_backup_restore(args, storage: Storage) -> int:
    """Rehydrate a fresh data dir from a backup entry, verified while it
    writes: files land bit-identical to the cut, the metadata dump loads
    into the CONFIGURED backend, the streaming cursor is clamped to the
    cut, the replication epoch is bumped so stale peers fence, and
    --replay-wal finishes the RPO story by replaying the acked-but-
    unstored WAL tail into the restored store."""
    from incubator_predictionio_tpu.backup import (
        BackupError,
        RestoreTargets,
        restore_backup,
    )

    targets = RestoreTargets(
        eventlog_dir=args.eventlog_dir,
        wal_dir=args.wal_dir,
        stream_state_dir=args.stream_state_dir,
        device_models_dir=args.device_models_dir,
        checkpoint_dirs=tuple(args.checkpoint_dir or ()),
    )
    try:
        report = restore_backup(
            args.backup_dir, targets, backup_id=args.id,
            storage=None if args.no_meta else storage,
            epoch_bump=not args.no_epoch_bump,
            replay_wal=args.replay_wal, force=args.force)
    except BackupError as e:
        _err(f"backup restore failed: {e}")
        return 1
    if args.json:
        _out(json.dumps(report, indent=2))
    else:
        _out(f"restored backup {report['backupId']}: "
             f"{report['filesRestored']} file(s), "
             f"{report['bytesRestored']}B in {report['seconds']}s")
        if report.get("meta"):
            loaded = ", ".join(f"{k}={v}" for k, v in
                               sorted(report["meta"]["loaded"].items()))
            _out(f"  metadata: {loaded}; models: "
                 f"{report['meta']['models']}")
        if report.get("cursorClamped"):
            _out("  streaming cursor clamped to the eventlog cut")
        if report.get("epoch"):
            ep = report["epoch"]
            _out(f"  replication epoch {ep['epochBefore']} -> "
                 f"{ep['epochAfter']}"
                 + ("" if ep["bumped"] else " (bump disabled)"))
        if report.get("walReplayed") is not None:
            _out(f"  WAL tail replayed: {report['walReplayed']} event(s)")
        if report.get("skippedComponents"):
            _out("  skipped (no target dir given): "
                 + ", ".join(report["skippedComponents"]))
    return 0


def cmd_backup_list(args, storage) -> int:
    """List committed backup entries: seq, age, chain parent, stored vs
    logical bytes, and the last verification verdict."""
    from incubator_predictionio_tpu.backup import BackupSet, entry_summary

    bset = BackupSet(args.backup_dir)
    try:
        rows = [entry_summary(bset, e) for e in bset.entries()]
    except Exception as e:  # noqa: BLE001 - a damaged entry is the finding
        _err(f"backup list failed: {e}")
        return 1
    if args.json:
        _out(json.dumps(rows, indent=2))
        return 0
    if not rows:
        _out(f"no backups in {args.backup_dir}")
        return 0
    for r in rows:
        mark = "ok" if r["verified"] else "!!"
        _out(f"{mark} {r['backupId']}  seq {r['seq']:>4}  "
             f"{r['createdAt']}  "
             f"{'incr on ' + r['parent'] if r['parent'] else 'full'}  "
             f"{r['files']} file(s) {r['storedBytes']}B stored "
             f"({r['logicalBytes']}B logical)  "
             f"{'verified' if r['verified'] else 'NOT VERIFIED'}")
    return 0


def cmd_backup_prune(args, storage) -> int:
    """Delete old entries, keeping the newest --keep entries plus every
    chain ancestor they reference (an incremental child never loses the
    full copy under it); crashed .tmp- stubs are cleared too."""
    from incubator_predictionio_tpu.backup import BackupError
    from incubator_predictionio_tpu.backup.manifest import prune

    try:
        removed = prune(args.backup_dir, args.keep)
    except BackupError as e:
        _err(f"backup prune failed: {e}")
        return 1
    _out(f"pruned {len(removed)} entr(ies): "
         + (", ".join(removed) if removed else "nothing to remove"))
    return 0


def cmd_lint(args, storage) -> int:
    """Run the project invariant linter (docs/analysis.md): R1
    async-blocking, R2 clock-discipline, R3 durability-ordering, R4
    knob-registry, R5 lock/await-hygiene, plus the S1/S2/B1 audits of
    the suppression surface itself. Exit 0 = clean, 1 = findings,
    2 = usage error (unknown rule id)."""
    from incubator_predictionio_tpu.analysis.engine import (
        render_json,
        render_text,
        run_lint,
    )

    try:
        result = run_lint(
            root=args.root,
            rules=args.rule or None,
            baseline_path=args.baseline,
            update_baseline=args.update_baseline,
        )
    except ValueError as e:
        _err(f"lint: {e}")
        return 2
    if args.update_baseline:
        # stderr under --json: stdout must stay one valid JSON document
        note = (f"baseline updated: {len(result.baselined)} entr(ies) "
                f"({args.baseline or 'conf/lint_baseline.txt'})")
        (_err if args.json else _out)(note)
    _out(render_json(result) if args.json else render_text(result))
    return 0 if result.clean else 1


def _backup_row(backup_dir: str, max_age: Optional[float],
                now: Optional[float] = None) -> dict:
    """The backup-staleness probe for ``pio-tpu health --backup-dir``
    (same alarm pattern as the quarantine row): red when there is no
    verified backup, the newest entry's last verify FAILED, or the newest
    verified entry is older than PIO_BACKUP_MAX_AGE (default 24h). An
    unverified-but-fresh backup is red too — an unverified backup is a
    hope, not a recovery plan (docs/dr.md)."""
    import time

    from incubator_predictionio_tpu.backup import BackupSet, read_verify
    from incubator_predictionio_tpu.backup.manifest import parse_iso

    url = f"backup:{backup_dir}"
    if max_age is None:
        max_age = float(os.environ.get("PIO_BACKUP_MAX_AGE", "86400"))
    try:
        entries = BackupSet(backup_dir).entries()
    except Exception as e:  # noqa: BLE001 - unreadable dir is red
        return {"url": url, "status": "unreadable", "red": True,
                "detail": str(e)}
    if not entries:
        return {"url": url, "status": "missing", "red": True,
                "detail": "no backups — run `pio-tpu backup create`"}
    tip = entries[-1]
    v = read_verify(tip.path)
    if v is not None and not v.get("clean"):
        return {"url": url, "status": "verify-failed", "red": True,
                "detail": f"backup {tip.backup_id} failed verification at "
                          f"{v.get('at')} — the newest backup is not "
                          "restorable"}
    newest_verified = None
    for e in reversed(entries):
        ve = read_verify(e.path)
        if ve is not None and ve.get("clean"):
            newest_verified = e
            break
    if newest_verified is None:
        return {"url": url, "status": "unverified", "red": True,
                "detail": f"{len(entries)} backup(s), none verified — run "
                          "`pio-tpu backup verify`"}
    created = parse_iso(newest_verified.manifest.get("createdAt"))
    now_s = now if now is not None else time.time()
    age = (now_s - created.timestamp()) if created is not None else None
    if age is None or age > max_age:
        return {"url": url, "status": "stale", "red": True,
                "detail": f"newest verified backup "
                          f"{newest_verified.backup_id} is "
                          + (f"{age:.0f}s old > PIO_BACKUP_MAX_AGE "
                             f"{max_age:.0f}s" if age is not None
                             else "undated")
                          + " — backups are not keeping up"}
    return {"url": url, "status": "ok", "red": False,
            "detail": f"backup {newest_verified.backup_id} verified, "
                      f"{age:.0f}s old (max {max_age:.0f}s)"}


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pio-tpu",
        description="TPU-native PredictionIO-capability ML server framework",
    )
    sub = parser.add_subparsers(dest="command")

    sub.add_parser("version")
    sub.add_parser("status")

    # app
    app = sub.add_parser("app").add_subparsers(dest="app_command")
    p = app.add_parser("new")
    p.add_argument("name")
    p.add_argument("--id", type=int, default=0)
    p.add_argument("--description")
    p.add_argument("--access-key", default="")
    app.add_parser("list")
    p = app.add_parser("show")
    p.add_argument("name")
    p = app.add_parser("delete")
    p.add_argument("name")
    p.add_argument("-f", "--force", action="store_true")
    p = app.add_parser("data-delete")
    p.add_argument("name")
    p.add_argument("--channel")
    p.add_argument("-f", "--force", action="store_true")
    p = app.add_parser("channel-new")
    p.add_argument("app_name")
    p.add_argument("channel")
    p = app.add_parser("channel-delete")
    p.add_argument("app_name")
    p.add_argument("channel")
    p.add_argument("-f", "--force", action="store_true")

    # accesskey
    ak = sub.add_parser("accesskey").add_subparsers(dest="accesskey_command")
    p = ak.add_parser("new")
    p.add_argument("app_name")
    p.add_argument("--access-key", default="")
    p.add_argument("--event", action="append")
    p = ak.add_parser("list")
    p.add_argument("app_name", nargs="?")
    p = ak.add_parser("delete")
    p.add_argument("key")

    # template (commands/Template.scala; in-package registry here)
    tp = sub.add_parser("template").add_subparsers(dest="template_command")
    tp.add_parser("list")
    p = tp.add_parser("get")
    p.add_argument("name")
    p.add_argument("directory", nargs="?", default=".")
    p.add_argument("--app-name")
    p.add_argument("--force", action="store_true")

    # train
    p = sub.add_parser("train")
    p.add_argument("-v", "--engine-variant", default="engine.json")
    p.add_argument("--batch", default="")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--skip-sanity-check", action="store_true")
    p.add_argument("--stop-after-read", action="store_true")
    p.add_argument("--stop-after-prepare", action="store_true")
    p.add_argument("--mesh-axes", help='JSON, e.g. \'{"data": 4, "model": 2}\'')
    p.add_argument("--distributed", action="store_true",
                   help="join a jax.distributed job (see the launch verb / "
                        "PIO_DIST_* env)")
    p.add_argument("--profile-dir",
                   help="capture a jax.profiler trace of the run into this dir")

    # launch (Runner.runOnSpark counterpart: N coordinated local processes)
    p = sub.add_parser("launch")
    p.add_argument("-n", "--num-processes", type=int, required=True)
    p.add_argument("--coordinator-port", type=int)
    p.add_argument("--cpu-devices-per-process", type=int,
                   help="force a CPU mesh with this many virtual devices per "
                        "process (testing without accelerators)")
    p.add_argument("--timeout", type=float, default=None,
                   help="kill the whole job after this many seconds (a wedged "
                        "peer otherwise hangs the launcher indefinitely)")
    p.add_argument("verb_args", nargs=argparse.REMAINDER,
                   help="the pio-tpu verb (and flags) each process runs")

    # eval
    p = sub.add_parser("eval")
    p.add_argument("evaluation_class")
    p.add_argument("engine_params_generator_class", nargs="?")
    p.add_argument("-v", "--engine-variant", default="engine.json")
    p.add_argument("--batch", default="")
    p.add_argument("--mesh-axes", help='JSON, e.g. \'{"data": 4}\'')
    p.add_argument("--distributed", action="store_true",
                   help="join a jax.distributed job (see the launch verb)")
    p.add_argument("--no-fast-eval", action="store_true",
                   help="disable prefix memoization across variants "
                        "(FastEvalEngine is the default)")

    # deploy / undeploy
    p = sub.add_parser("deploy")
    p.add_argument("-v", "--engine-variant", default="engine.json")
    p.add_argument("--ip", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--feedback", action="store_true")
    p.add_argument("--event-server-ip", default="127.0.0.1")
    p.add_argument("--event-server-port", type=int, default=7070)
    p.add_argument("--accesskey", dest="access_key")
    p.add_argument("--server-access-key")
    p.add_argument("--ssl-cert")
    p.add_argument("--ssl-key")
    p.add_argument("--log-url",
                   help="ship serving errors to this URL "
                        "(reference CreateServer.scala:423-436)")
    p.add_argument("--log-prefix", default="",
                   help="prefix for shipped log messages")
    p.add_argument("--query-timeout", type=float, dest="query_timeout_sec",
                   help="total per-query budget in seconds; blown budgets "
                        "answer degraded-200 from the last-good cache "
                        "instead of 500 (docs/resilience.md)")
    p.add_argument("--algo-deadline", type=float, dest="algo_deadline_sec",
                   help="per-algorithm deadline in seconds; slower answers "
                        "count as circuit-breaker failures")
    p.add_argument("--algo-breaker-threshold", type=int, default=3,
                   help="consecutive failures before an algorithm's "
                        "breaker opens (default 3)")
    p.add_argument("--algo-breaker-reset", type=float, default=10.0,
                   dest="algo_breaker_reset_sec",
                   help="seconds an open algorithm breaker waits before a "
                        "half-open probe (default 10)")
    p.add_argument("--smoke-query", action="append",
                   help="JSON query payload the /reload health gate runs "
                        "against a NEW instance before it may serve "
                        "(repeatable; any failure keeps the live instance "
                        "— docs/resilience.md)")
    p.add_argument("--reload-probation", type=float, default=30.0,
                   dest="reload_probation_sec",
                   help="seconds after a /reload swap during which a "
                        "serving-breaker trip auto-rolls back to the "
                        "previous instance (default 30; 0 disables)")
    p.add_argument("--admission-max-queue", type=int,
                   help="bounded admission queue depth; waiting queries "
                        "beyond it answer 429 + Retry-After "
                        "(PIO_ADMISSION_MAX_QUEUE env, default 256 — "
                        "docs/resilience.md)")
    p.add_argument("--admission-target-ms", type=float,
                   help="explicit latency target (ms) for the adaptive "
                        "concurrency limiter; unset = gradient mode "
                        "(PIO_ADMISSION_TARGET_MS env)")
    p.add_argument("--no-adaptive-admission", action="store_true",
                   help="disable the AIMD concurrency limiter "
                        "(PIO_ADMISSION_ADAPTIVE=0 env)")
    p.add_argument("--shard-id", type=int, default=None,
                   help="this process owns item-catalog shard N of "
                        "--shard-count; announced on /health and served "
                        "via /shard/queries.json (PIO_FLEET_SHARD_ID env "
                        "— docs/sharding.md \"Multi-host shard owners\")")
    p.add_argument("--shard-count", type=int, default=None,
                   help="total shard-owner count the catalog's rows are "
                        "split across (PIO_FLEET_SHARD_COUNT env)")
    p.add_argument("--shard-state-dir", default=None,
                   help="directory persisting this owner's fencing epoch "
                        "across restarts; a corrupt token refuses startup "
                        "rather than guess (PIO_FLEET_SHARD_STATE_DIR env)")
    p.add_argument("--tenants", default=None,
                   help="multi-tenant mode: tenant table as a JSON file "
                        "path or inline JSON array — this process hosts "
                        "every listed engine behind /engines/{id}/... "
                        "(PIO_TENANTS env — docs/tenancy.md)")
    p = sub.add_parser("undeploy")
    p.add_argument("--ip", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--server-access-key")

    # batchpredict
    p = sub.add_parser("batchpredict")
    p.add_argument("--input", default="batchpredict-input.json")
    p.add_argument("--output", default="batchpredict-output.json")
    p.add_argument("-v", "--engine-variant", default="engine.json")
    p.add_argument("--query-partitions", type=int)
    p.add_argument("--distributed", action="store_true",
                   help="score a per-process slice under `launch -n N`; "
                        "writes <output>.part-<pid> files (the reference's "
                        "saveAsTextFile layout)")

    # eventserver
    p = sub.add_parser("eventserver")
    p.add_argument("--ip", default="0.0.0.0")
    p.add_argument("--port", type=int, default=7070)
    p.add_argument("--stats", action="store_true")
    p.add_argument("--ssl-cert")
    p.add_argument("--ssl-key")
    p.add_argument("--wal-dir",
                   help="write-ahead log directory for the spill queue: "
                        "spilled events are fsynced before their 201 and "
                        "replayed after a crash (PIO_EVENT_WAL_DIR env; "
                        "docs/resilience.md)")
    p.add_argument("--client-rate", type=float,
                   help="per-access-key ingest rate limit, events/sec; a "
                        "client over it answers 429 alone "
                        "(PIO_EVENTSERVER_CLIENT_RATE env; 0 disables)")
    p.add_argument("--client-burst", type=float,
                   help="per-access-key token-bucket burst capacity "
                        "(PIO_EVENTSERVER_CLIENT_BURST env; default 2× "
                        "the rate)")

    # storageserver — serve this process's storage config to remote clients
    p = sub.add_parser(
        "storageserver",
        help="serve the local storage backends over HTTP (the shared "
             "networked store of a multi-host job; clients use TYPE=remote)")
    p.add_argument("--ip", default="0.0.0.0")
    p.add_argument("--port", type=int, default=7072)
    p.add_argument("--ssl-cert")
    p.add_argument("--ssl-key")
    p.add_argument("--server-access-key",
                   help="shared secret required from every client")
    p.add_argument("--client-inflight", type=int,
                   help="concurrent in-flight RPCs allowed per client "
                        "address before 429 (PIO_STORAGE_CLIENT_INFLIGHT "
                        "env, default 64; 0 disables)")
    p.add_argument("--repl-role", choices=("primary", "follower"),
                   help="eventlog replication role (PIO_REPL_ROLE env; "
                        "docs/replication.md)")
    p.add_argument("--repl-peer", action="append",
                   help="base URL of another replica (repeatable; "
                        "PIO_REPL_PEERS env, comma-separated)")
    p.add_argument("--repl-sync", choices=("async", "quorum"),
                   help="replication ack mode: async (bounded lag, "
                        "default) or quorum (a write acks only once a "
                        "majority of the replica set holds it; "
                        "PIO_REPL_SYNC env)")

    # jobs — continuous-training control plane (docs/jobs.md)
    jobs = sub.add_parser(
        "jobs",
        help="continuous-training control plane: submit/list/watch/cancel/"
             "retry durable jobs, run the lease-fenced worker, run the "
             "auto-retrain trigger loop (docs/jobs.md)")
    jb = jobs.add_subparsers(dest="jobs_command")
    p = jb.add_parser("submit")
    p.add_argument("--kind", default="train",
                   choices=("train", "eval", "batchpredict", "rollout"))
    p.add_argument("-v", "--engine-variant", default="engine.json")
    p.add_argument("--batch", default="")
    p.add_argument("--server-url",
                   help="query server whose /reload promotes a passing "
                        "candidate (single-server deploy)")
    p.add_argument("--replica", action="append",
                   help="fleet replica base URL (repeatable; 2+ drive the "
                        "halt-and-rollback rollout orchestrator)")
    p.add_argument("--server-access-key")
    p.add_argument("--mesh-axes", help='JSON, e.g. \'{"data": 4}\'')
    p.add_argument("--evaluation-class",
                   help="for --kind eval: the Evaluation to run")
    p.add_argument("--no-gate", action="store_true",
                   help="skip the eval gate for this job "
                        "(PIO_JOBS_GATE=0 equivalent)")
    p.add_argument("--no-dedupe", action="store_true",
                   help="queue even if an identical train job is active")
    p.add_argument("--max-attempts", type=int, default=3)
    p.add_argument("--dist", type=int, default=0, metavar="N",
                   help="for --kind train: run the train as N supervised "
                        "member processes with mesh-generation fencing and "
                        "coordinated slice checkpoints (docs/sharding.md "
                        "\"Multi-host training\")")
    p.add_argument("--dist-state-dir",
                   help="coordination dir for --dist (default: "
                        "PIO_DIST_STATE_DIR, else a per-job dir under "
                        "PIO_FS_BASEDIR)")
    p.add_argument("--params", help="extra params JSON merged into the job")
    p = jb.add_parser("list")
    p.add_argument("--all", action="store_true",
                   help="include every terminal job (default: active + "
                        "the 10 most recent terminal)")
    p.add_argument("--json", action="store_true")
    p = jb.add_parser("watch")
    p.add_argument("id")
    p.add_argument("--timeout", type=float, default=3600.0)
    p.add_argument("--poll", type=float, default=0.5)
    p = jb.add_parser("cancel")
    p.add_argument("id")
    p = jb.add_parser("retry")
    p.add_argument("id")
    p = jb.add_parser("prune")
    p.add_argument("--keep", type=int, default=200,
                   help="terminal jobs to keep (newest first; active jobs "
                        "are never pruned)")
    p.add_argument("--older-than", type=float,
                   help="also drop terminal jobs older than this many "
                        "seconds")
    p = jb.add_parser("worker")
    p.add_argument("--once", action="store_true",
                   help="claim and execute at most one job, then exit")
    p.add_argument("--max-jobs", type=int,
                   help="exit after executing this many jobs")
    p.add_argument("--lease", type=float,
                   help="lease seconds (PIO_JOBS_LEASE_SEC env, default 60);"
                        " a worker dead this long has its job reclaimed")
    p.add_argument("--poll", type=float,
                   help="idle poll seconds (PIO_JOBS_POLL_SEC env)")
    p.add_argument("--obs-port", type=int, default=0,
                   help="serve GET /metrics + /traces.json on this port so "
                        "pio_jobs_* gauges are scrapeable (0 = disabled, "
                        "the default; docs/observability.md)")
    p.add_argument("--obs-ip", default="127.0.0.1",
                   help="bind address for --obs-port (default loopback)")
    p = jb.add_parser("triggers")
    p.add_argument("-v", "--engine-variant", default="engine.json")
    p.add_argument("--interval", type=float,
                   help="seconds between interval-trigger retrains "
                        "(PIO_JOBS_INTERVAL env; 0 disables)")
    p.add_argument("--drift-events", type=int,
                   help="retrain once this many events land after the last "
                        "trained instance (PIO_JOBS_DRIFT_EVENTS env; "
                        "0 disables)")
    p.add_argument("--state-dir",
                   help="streaming state dir to watch for the quarantine "
                        "marker (a trip auto-submits a full retrain)")
    p.add_argument("--app", help="app whose events feed the drift counter "
                                 "(default: the variant's datasource app)")
    p.add_argument("--server-url",
                   help="forwarded onto submitted train jobs as the deploy "
                        "target")
    p.add_argument("--replica", action="append")
    p.add_argument("--server-access-key")
    p.add_argument("--poll", type=float, default=5.0,
                   help="seconds between trigger evaluations")
    p.add_argument("--once", action="store_true",
                   help="evaluate every trigger once and exit")

    # store — replicated-storage admin (docs/replication.md)
    store = sub.add_parser(
        "store",
        help="replicated storage admin: status (role/epoch/lag per "
             "replica), promote (epoch-fenced failover), scrub "
             "(anti-entropy divergence detection + repair)")
    st = store.add_subparsers(dest="store_command")
    p = st.add_parser("status")
    p.add_argument("urls", nargs="+",
                   help="storage-server base URLs (the whole replica set)")
    p.add_argument("--timeout", type=float, default=5.0)
    p.add_argument("--json", action="store_true")
    p = st.add_parser("promote")
    p.add_argument("url", help="the follower to promote")
    p.add_argument("--peer", action="append",
                   help="replica set AFTER the promotion (repeatable; "
                        "omit to keep the follower's configured peers — "
                        "typically you exclude the dead primary here)")
    p.add_argument("--server-access-key")
    p = st.add_parser("scrub")
    p.add_argument("primary", help="authoritative replica base URL")
    p.add_argument("followers", nargs="+",
                   help="follower base URLs to verify/repair against it")
    p.add_argument("--segment-bytes", type=int, default=1 << 20,
                   help="digest window size (default 1 MiB)")
    p.add_argument("--check-only", action="store_true",
                   help="detect divergence without repairing")
    p.add_argument("--server-access-key")
    p.add_argument("--json", action="store_true")

    # backup — disaster recovery (docs/dr.md)
    backup = sub.add_parser(
        "backup",
        help="disaster recovery: consistent point-in-time backup and "
             "verified restore of the whole state surface — eventlog, "
             "metadata (dump/load), models + sidecars, spill WAL, "
             "streaming state, replication fencing state (docs/dr.md)")
    bk = backup.add_subparsers(dest="backup_command")

    def _backup_component_args(p, restoring: bool) -> None:
        verb = "restore into" if restoring else "back up"
        p.add_argument("--eventlog-dir",
                       help=f"eventlog directory to {verb} (.piolog logs "
                            "+ repl-state.json; default on create: "
                            "resolved from the configured eventlog "
                            "EVENTDATA backend)")
        p.add_argument("--wal-dir",
                       help=f"event-server spill WAL directory to {verb}")
        p.add_argument("--stream-state-dir",
                       help=f"streaming state directory to {verb} "
                            "(cursor, trainer state, delta archive, "
                            "quarantine marker)")
        p.add_argument("--device-models-dir",
                       help=f"device-model sidecar tree to {verb} "
                            "(default on create: $PIO_FS_BASEDIR/"
                            "device_models when present)")
        p.add_argument("--checkpoint-dir", action="append",
                       help=f"TrainCheckpointer directory to {verb} "
                            "(repeatable; mid-epoch training state)")
        p.add_argument("--no-meta", action="store_true",
                       help="skip the metadata dump/load and model blobs")
        p.add_argument("--json", action="store_true")

    p = bk.add_parser("create")
    p.add_argument("--backup-dir", required=True,
                   help="backup set directory (entries chain inside it)")
    _backup_component_args(p, restoring=False)
    p.add_argument("--full", action="store_true",
                   help="force a full copy instead of an incremental "
                        "extent on the previous entry")
    p = bk.add_parser("verify")
    p.add_argument("--backup-dir", required=True)
    p.add_argument("--id", help="backup id (default: the newest entry)")
    p.add_argument("--json", action="store_true")
    p = bk.add_parser("restore")
    p.add_argument("--backup-dir", required=True)
    p.add_argument("--id", help="backup id (default: the newest entry)")
    _backup_component_args(p, restoring=True)
    p.add_argument("--replay-wal", action="store_true",
                   help="after restoring, replay the WAL tail into the "
                        "configured event store (idempotent; otherwise "
                        "the event server replays it at startup)")
    p.add_argument("--no-epoch-bump", action="store_true",
                   help="keep the backed-up replication epoch instead of "
                        "bumping it (bump fences stale peers — only skip "
                        "when restoring an isolated dev copy)")
    p.add_argument("--force", action="store_true",
                   help="restore into a non-empty target directory")
    p = bk.add_parser("list")
    p.add_argument("--backup-dir", required=True)
    p.add_argument("--json", action="store_true")
    p = bk.add_parser("prune")
    p.add_argument("--backup-dir", required=True)
    p.add_argument("--keep", type=int, default=7,
                   help="newest entries to keep (their chain ancestors "
                        "are kept too; default 7)")

    # dashboard / adminserver
    p = sub.add_parser("dashboard")
    p.add_argument("--ip", default="127.0.0.1")
    p.add_argument("--port", type=int, default=9000)
    p.add_argument("--ssl-cert")
    p.add_argument("--ssl-key")
    p.add_argument("--server-access-key")
    p = sub.add_parser("adminserver")
    p.add_argument("--ip", default="127.0.0.1")
    p.add_argument("--port", type=int, default=7071)
    p.add_argument("--ssl-cert")
    p.add_argument("--ssl-key")
    p.add_argument("--server-access-key")

    # start-all / stop-all / redeploy
    p = sub.add_parser("start-all")
    p.add_argument("--ip", default="0.0.0.0")
    p.add_argument("--event-server-port", type=int, default=7070)
    p.add_argument("--with-dashboard", action="store_true")
    p.add_argument("--dashboard-port", type=int, default=9000)
    p.add_argument("--with-adminserver", action="store_true")
    p.add_argument("--adminserver-port", type=int, default=7071)
    p.add_argument("--with-storageserver", action="store_true")
    p.add_argument("--storageserver-port", type=int, default=7072)
    p.add_argument("--storageserver-access-key",
                   help="shared secret required from remote storage clients")
    p.add_argument("--stats", action="store_true")
    p.add_argument("--wait-secs", type=float, default=60.0)
    sub.add_parser("stop-all")
    p = sub.add_parser("redeploy")
    p.add_argument("-v", "--engine-variant", default="engine.json")
    p.add_argument("--batch", default="")
    p.add_argument("--retries", type=int, default=3)
    p.add_argument("--retry-wait", type=float, default=30.0)
    p.add_argument("--ip", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--server-access-key")
    p.add_argument("--no-reload", action="store_true")
    p.add_argument("--interval", type=float,
                   help="seconds between passes; omit to run once")
    p.add_argument("--mesh-axes", help='JSON, e.g. \'{"data": 4, "model": 2}\'')
    p.add_argument("--legacy", action="store_true",
                   help="run the old in-process train+reload loop instead "
                        "of submitting through the durable job "
                        "orchestrator (docs/jobs.md)")

    # shell (bin/pio-shell counterpart)
    p = sub.add_parser(
        "shell",
        help="interactive Python with the storage/event-store/mesh "
             "bootstrap preloaded (bin/pio-shell --with-pyspark slot)")
    p.add_argument("-c", "--code", dest="shell_code",
                   help="run this statement instead of going interactive")

    # metrics — scrape + pretty-print any server's /metrics
    p = sub.add_parser(
        "metrics",
        help="fetch and pretty-print one or more servers' Prometheus "
             "/metrics pages (multiple URLs merge into a per-server table "
             "with a summed/max aggregate column; docs/observability.md)")
    p.add_argument("urls", nargs="+",
                   help="server base URL(s), e.g. http://127.0.0.1:8000 "
                        "http://127.0.0.1:8001 — probed concurrently")
    p.add_argument("--fleet", action="store_true",
                   help="force the merged per-server table layout even for "
                        "a single URL (stable format for scripts)")
    p.add_argument("--raw", action="store_true",
                   help="print the raw exposition text instead")
    p.add_argument("--filter", help="only families whose name contains this")
    p.add_argument("--timeout", type=float, default=10.0,
                   help="per-server fetch timeout in seconds (default 10)")

    # profile — the continuous profiler's live document
    p = sub.add_parser(
        "profile",
        help="fetch and render a server's /profile.json: per-scope phase "
             "attribution, wall-stack sampler top-N (PIO_PROFILE_HZ), "
             "training MFU, device-memory watermarks "
             "(docs/observability.md \"Profiling\")")
    p.add_argument("url", help="server base URL, e.g. http://127.0.0.1:8000")
    p.add_argument("--timeout", type=float, default=10.0)
    p.add_argument("--json", action="store_true")

    # history — durable metrics history (docs/observability.md)
    p = sub.add_parser(
        "history",
        help="inspect the self-scraped metrics history: a PIO_HISTORY_DIR's "
             "durable segments or a live server's ring via /history.json; "
             "--series prints matching time series "
             "(docs/observability.md \"Metrics history & SLOs\")")
    p.add_argument("source",
                   help="history directory (PIO_HISTORY_DIR) or server base "
                        "URL")
    p.add_argument("--series", metavar="GLOB",
                   help="print series whose family name matches this glob "
                        "(e.g. 'pio_http_*'); counters also render "
                        "per-interval deltas")
    p.add_argument("--since", type=float,
                   help="only records with unix timestamp >= this")
    p.add_argument("--limit", type=int, default=20,
                   help="points shown per series, newest last (default 20)")
    p.add_argument("--timeout", type=float, default=10.0)
    p.add_argument("--json", action="store_true")

    # top — live-refreshing performance-plane summary
    p = sub.add_parser(
        "top",
        help="live one-line-per-server view from /metrics: qps, p99, "
             "RSS/FDs/loop-lag, MFU, jit compile seconds, SLO breaches; "
             "refreshes until interrupted (-n 1 prints once)")
    p.add_argument("urls", nargs="+",
                   help="server base URL(s), e.g. http://127.0.0.1:8000")
    p.add_argument("-i", "--interval", type=float, default=2.0,
                   help="seconds between refreshes (default 2)")
    p.add_argument("-n", "--iterations", type=int, default=0,
                   help="stop after N refreshes (default 0 = forever)")
    p.add_argument("--timeout", type=float, default=5.0)

    # slo — objectives validation + offline burn-rate verdicts
    p = sub.add_parser(
        "slo",
        help="validate an SLO objectives config (--check, the CI gate) "
             "and/or evaluate burn-rate verdicts over recorded history, "
             "exiting non-zero on invalid config or a breaching objective "
             "(docs/observability.md \"Metrics history & SLOs\")")
    p.add_argument("source", nargs="?",
                   help="history directory (PIO_HISTORY_DIR) or server base "
                        "URL to evaluate over (omit with --check to only "
                        "validate)")
    p.add_argument("--check", metavar="CONFIG",
                   help="validate this objectives JSON; exit 1 with "
                        "named-position errors on any defect")
    p.add_argument("--config", metavar="CONFIG",
                   help="objectives JSON for evaluation (default: --check "
                        "value, else $PIO_SLO_CONFIG)")
    p.add_argument("--since", type=float,
                   help="only records with unix timestamp >= this")
    p.add_argument("--timeout", type=float, default=10.0)
    p.add_argument("--json", action="store_true")

    # trace — cross-process trace assembly (docs/observability.md)
    tr = sub.add_parser(
        "trace",
        help="assemble cross-process traces from span spools and/or live "
             "servers: list recent traces, show one as a terminal "
             "waterfall, or rank the slowest (docs/observability.md)")
    trs = tr.add_subparsers(dest="trace_command")

    def _trace_source_args(p) -> None:
        p.add_argument("--spool", action="append", metavar="DIR",
                       help="span spool directory (PIO_TRACE_SPOOL_DIR of "
                            "any fleet process; repeatable; default: "
                            "$PIO_TRACE_SPOOL_DIR when set)")
        p.add_argument("--url", action="append", metavar="URL",
                       help="server base URL whose live /traces.json ring "
                            "to include (repeatable)")
        p.add_argument("--timeout", type=float, default=5.0)
        p.add_argument("--json", action="store_true")

    p = trs.add_parser("list")
    _trace_source_args(p)
    p.add_argument("--limit", type=int, default=20,
                   help="traces to list, newest first (default 20)")
    p = trs.add_parser("show")
    p.add_argument("trace_id",
                   help="trace id (or unique prefix) — e.g. from a "
                        "response's X-PIO-Trace header or a /metrics "
                        "exemplar")
    _trace_source_args(p)
    p = trs.add_parser("slowest")
    _trace_source_args(p)
    p.add_argument("-n", "--limit", type=int, default=10,
                   help="slowest traces to rank (default 10); the worst "
                        "one renders as a waterfall")

    # index — two-stage retrieval partition inspection
    p = sub.add_parser(
        "index",
        help="inspect the two-stage retrieval partition (IVF) of the "
             "latest trained model: partition count, size skew, "
             "quantization mode (docs/serving.md)")
    p.add_argument("-v", "--engine-variant", default="engine.json")
    p.add_argument("--two-stage", action="store_true",
                   help="force PIO_RETRIEVAL_MODE=two_stage so an index is "
                        "built (and shown) even below the auto catalog-size "
                        "threshold")

    # shards — sharded embedding layout inspection (docs/sharding.md)
    p = sub.add_parser(
        "shards",
        help="inspect the sharded embedding layout of the latest trained "
             "model: per-shard row counts, HBM-bytes estimates, merge "
             "fan-in (docs/sharding.md)")
    p.add_argument("-v", "--engine-variant", default="engine.json")

    # health — one-probe fleet state across all three servers
    p = sub.add_parser(
        "health",
        help="aggregate GET /health from the given servers into one "
             "table (draining/breaker/spill/admission state); exits "
             "non-zero when any is unreachable, draining, or degraded")
    p.add_argument("urls", nargs="*",
                   help="server base URLs, e.g. http://127.0.0.1:7070 "
                        "http://127.0.0.1:8000 http://127.0.0.1:7072 "
                        "(may be empty when only --stream-state-dir / "
                        "--backup-dir rows are wanted)")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="per-probe timeout in seconds (default 5)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable row output")
    p.add_argument("--stream-state-dir",
                   help="also probe this streaming state dir's quarantine "
                        "marker: red when older than --quarantine-max-age "
                        "(stuck control loop — docs/jobs.md)")
    p.add_argument("--quarantine-max-age", type=float,
                   help="seconds a quarantine marker may age before the "
                        "row turns red (default: PIO_JOBS_INTERVAL, "
                        "else 300)")
    p.add_argument("--backup-dir",
                   help="also probe this backup directory: red when the "
                        "newest verified backup is older than "
                        "--backup-max-age or the last verify failed "
                        "(docs/dr.md)")
    p.add_argument("--backup-max-age", type=float,
                   help="seconds the newest verified backup may age "
                        "before the row turns red (default: "
                        "PIO_BACKUP_MAX_AGE, else 86400)")
    p.add_argument("--dist-state-dir",
                   help="also probe this distributed-training coordination "
                        "dir: red when live members fall below quorum "
                        "(docs/sharding.md \"Multi-host training\")")

    # tenants — per-tenant fleet rollup (docs/tenancy.md)
    p = sub.add_parser(
        "tenants",
        help="per-tenant rollup across the given multi-tenant query "
             "servers: requests/qps/p99/quota/evictions/HBM bytes from "
             "/health + /metrics; red rows on quota exhaustion or "
             "eviction thrash, non-zero exit when any row is red")
    p.add_argument("urls", nargs="+",
                   help="query-server base URLs, e.g. "
                        "http://127.0.0.1:8000 http://127.0.0.1:8001")
    p.add_argument("--timeout", type=float, default=5.0,
                   help="per-probe timeout in seconds (default 5)")
    p.add_argument("--interval", type=float, default=1.0,
                   help="seconds between the two /metrics scrapes the "
                        "qps column derives from (0 = single scrape, "
                        "no qps; default 1)")
    p.add_argument("--fill-red", type=float, default=0.05,
                   help="quota-fill fraction at or below which a tenant "
                        "with throttles paints red (default 0.05)")
    p.add_argument("--thrash-evictions", type=int, default=8,
                   help="total evictions at which a tenant paints red "
                        "for eviction thrash (default 8)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable row output")

    # dist — distributed-training mesh inspection (docs/sharding.md)
    dist = sub.add_parser(
        "dist",
        help="distributed training tier: status (mesh generation, member "
             "heartbeats, last coordinated checkpoint commit, quorum "
             "verdict)")
    ds = dist.add_subparsers(dest="dist_command")
    p = ds.add_parser("status")
    p.add_argument("--state-dir",
                   help="coordination directory (default: "
                        "PIO_DIST_STATE_DIR)")
    p.add_argument("--json", action="store_true")

    # fleet — router / rolling deploy / experiment (docs/serving.md)
    fleet = sub.add_parser(
        "fleet",
        help="fleet serving tier: route (health-aware query router), "
             "rollout (sequential rolling deploy with halt-and-rollback), "
             "experiment (A/B / shadow inspection and control)")
    fl = fleet.add_subparsers(dest="fleet_command")
    p = fl.add_parser("route")
    p.add_argument("--ip", default="0.0.0.0")
    p.add_argument("--port", type=int, default=8200)
    p.add_argument("--replica", action="append", required=True,
                   help="query-server replica base URL (repeatable)")
    p.add_argument("--candidate", action="append",
                   help="candidate-arm replica base URL for A/B / shadow "
                        "routing (repeatable; a different engine version "
                        "deployed beside the control fleet)")
    p.add_argument("--deadline", type=float,
                   help="total per-query budget in seconds across every "
                        "forwarding attempt (PIO_FLEET_DEADLINE env, "
                        "default 3)")
    p.add_argument("--retries", type=int,
                   help="forwarding attempts per query, each on a "
                        "different replica (PIO_FLEET_MAX_ATTEMPTS env, "
                        "default 2)")
    p.add_argument("--health-interval", type=float,
                   help="seconds between concurrent /health probe rounds "
                        "(PIO_FLEET_HEALTH_INTERVAL env, default 2)")
    p.add_argument("--probe-timeout", type=float,
                   help="per-replica /health probe timeout "
                        "(PIO_FLEET_PROBE_TIMEOUT env, default 2)")
    p.add_argument("--eject-threshold", type=int,
                   help="consecutive transport errors before a replica is "
                        "ejected until a probe succeeds "
                        "(PIO_FLEET_EJECT_THRESHOLD env, default 3)")
    p.add_argument("--experiment-name", default="candidate")
    p.add_argument("--experiment-mode", choices=("ab", "shadow"),
                   default="ab")
    p.add_argument("--experiment-weight", type=float,
                   help="fraction of traffic on the candidate arm; "
                        "requires --candidate (omit to start without an "
                        "experiment — POST /experiment starts one live)")
    p.add_argument("--experiment-hash-field",
                   help="query field whose value hashes to a sticky arm "
                        "(e.g. user); omitted = weighted rotation")
    p.add_argument("--server-access-key",
                   help="guards POST /experiment")
    p = fl.add_parser("rollout")
    p.add_argument("replicas", nargs="+",
                   help="query-server replica base URLs, deploy order")
    p.add_argument("--server-access-key")
    p.add_argument("--observe", type=float, default=5.0,
                   help="seconds to watch each replica's /health for a "
                        "probation auto-rollback after its swap (keep "
                        "well under the replicas' --reload-probation; "
                        "default 5)")
    p.add_argument("--poll", type=float, default=0.5,
                   help="seconds between /health polls while observing")
    p.add_argument("--timeout", type=float, default=120.0,
                   help="per-replica /reload timeout (load+warm+smoke)")
    p.add_argument("--json", action="store_true")
    p = fl.add_parser("experiment")
    p.add_argument("router_url",
                   help="fleet router base URL, e.g. http://127.0.0.1:8200")
    p.add_argument("--start", metavar="NAME",
                   help="start an experiment with this name")
    p.add_argument("--stop", action="store_true",
                   help="stop the running experiment")
    p.add_argument("--mode", choices=("ab", "shadow"), default="ab")
    p.add_argument("--weight", type=float, default=0.1)
    p.add_argument("--hash-field")
    p.add_argument("--server-access-key")
    p.add_argument("--json", action="store_true")

    # stream — incremental model updates from the live event feed
    p = sub.add_parser(
        "stream",
        help="streaming incremental updates: tail the eventlog change "
             "feed, fold events into embedding-row deltas, ship them to "
             "replicas as exactly-once delta deploys (docs/streaming.md)")
    p.add_argument("-v", "--engine-variant", default="engine.json")
    p.add_argument("--app", default="recommendation",
                   help="app whose eventlog to tail")
    p.add_argument("--channel", help="channel name (default: none)")
    p.add_argument("--state-dir", required=True,
                   help="cursor + trainer state + delta archive + dead "
                        "letters (crash-safe; single-writer)")
    p.add_argument("--feed-path",
                   help="explicit .piolog path (default: resolved from "
                        "the configured eventlog backend and --app)")
    p.add_argument("--replica", action="append",
                   help="query-server base URL to ship deltas to "
                        "(repeatable)")
    p.add_argument("--server-access-key",
                   help="the replicas' --server-access-key (guards "
                        "POST /delta)")
    p.add_argument("--batch-events", type=int, default=512,
                   help="max events folded per delta (PIO_STREAM_BATCH)")
    p.add_argument("--interval", type=float, default=1.0,
                   help="seconds between idle polls")
    p.add_argument("--once", action="store_true",
                   help="one poll→fold→ship→commit round, then exit")
    p.add_argument("--max-batches", type=int,
                   help="exit after this many applied deltas")
    p.add_argument("--from-start", action="store_true",
                   help="start a fresh cursor at the BEGINNING of the log "
                        "instead of its current end (fold history too)")
    p.add_argument("--status", action="store_true",
                   help="print stream state (cursor, quarantine, dead "
                        "letters) and exit; non-zero when quarantined")
    p.add_argument("--dead-letter", action="store_true",
                   help="print dead-lettered poison events as JSON lines")
    p.add_argument("--obs-port", type=int, default=0,
                   help="serve GET /metrics + /traces.json on this port so "
                        "pio_stream_* gauges are scrapeable (0 = disabled, "
                        "the default; docs/observability.md)")
    p.add_argument("--obs-ip", default="127.0.0.1",
                   help="bind address for --obs-port (default loopback)")

    # wal — inspect/verify/replay an event-server spill WAL
    p = sub.add_parser(
        "wal",
        help="inspect, verify, or manually replay an event-server spill "
             "WAL directory (docs/resilience.md)")
    p.add_argument("directory", help="the PIO_EVENT_WAL_DIR to inspect")
    p.add_argument("--dead-letter", action="store_true",
                   help="print the dead-letter records (store-rejected, "
                        "201-acked events) as JSON lines")
    p.add_argument("--replay", action="store_true",
                   help="insert every pending record into the configured "
                        "event store (idempotent) and advance the cursor")
    p.add_argument("--json", action="store_true",
                   help="machine-readable inspection output")

    # lint — project invariant linter (docs/analysis.md)
    p = sub.add_parser(
        "lint",
        help="run the AST-based project invariant linter: R1 async-"
             "blocking, R2 clock-discipline, R3 durability-ordering, "
             "R4 knob-registry (PIO_* knobs + pio_* metrics ↔ docs), "
             "R5 lock/await-hygiene; suppressions and the baseline are "
             "audited too (docs/analysis.md)")
    p.add_argument("--rule", action="append", metavar="R<n>",
                   help="run only this rule id (repeatable, e.g. "
                        "--rule R2 --rule R4; default: all)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings (schema in "
                        "docs/analysis.md)")
    p.add_argument("--update-baseline", action="store_true",
                   help="accept every current finding into the baseline "
                        "file — deterministic output (sorted, "
                        "path-relative) so the diff is reviewable")
    p.add_argument("--baseline", metavar="PATH",
                   help="baseline file, repo-relative "
                        "(default conf/lint_baseline.txt)")
    p.add_argument("--root",
                   help="repo root to lint (default: the tree this "
                        "package is installed from)")

    # export / import
    p = sub.add_parser("export")
    p.add_argument("--appid", type=int, required=True)
    p.add_argument("--output", required=True)
    p.add_argument("--channel")
    p = sub.add_parser("import")
    p.add_argument("--appid", type=int, required=True)
    p.add_argument("--input", required=True)
    p.add_argument("--channel")

    return parser


def cmd_launch(args, storage: Storage) -> int:
    """Spawn N coordinated processes of another verb (Runner.scala:185's
    spark-submit construction, minus the JVM)."""
    from incubator_predictionio_tpu.parallel.launcher import launch_local

    verb_args = list(args.verb_args)
    if verb_args and verb_args[0] == "--":
        verb_args = verb_args[1:]
    if not verb_args:
        _out("launch: no verb given (e.g. pio-tpu launch -n 2 train -v engine.json)")
        return 2
    if verb_args[0] not in ("train", "eval", "batchpredict"):
        # without --distributed gating, N processes of any other verb would
        # just run N independent copies against shared storage
        _out(f"launch: only the train/eval/batchpredict verbs join a "
             f"distributed job (got {verb_args[0]!r})")
        return 2
    if "--distributed" not in verb_args:
        verb_args.append("--distributed")
    result = launch_local(
        verb_args,
        num_processes=args.num_processes,
        coordinator_port=args.coordinator_port,
        cpu_devices_per_process=args.cpu_devices_per_process,
        timeout=args.timeout,
    )
    if result.timed_out:
        _out(f"launch: timed out after {args.timeout}s; job killed "
             "(per-process logs below show which peer wedged)")
    for pid, (rc, out) in enumerate(zip(result.returncodes, result.outputs)):
        _out(f"--- process {pid} (exit {rc}) ---")
        if out:
            _out(out.rstrip())
    return 0 if result.ok else 1


_COMMANDS = {
    "version": cmd_version,
    "status": cmd_status,
    "train": cmd_train,
    "launch": cmd_launch,
    "eval": cmd_eval,
    "deploy": cmd_deploy,
    "undeploy": cmd_undeploy,
    "batchpredict": cmd_batchpredict,
    "eventserver": cmd_eventserver,
    "storageserver": cmd_storageserver,
    "dashboard": cmd_dashboard,
    "adminserver": cmd_adminserver,
    "export": cmd_export,
    "import": cmd_import,
    "metrics": cmd_metrics,
    "trace": cmd_trace,
    "health": cmd_health,
    "tenants": cmd_tenants,
    "profile": cmd_profile,
    "history": cmd_history,
    "top": cmd_top,
    "slo": cmd_slo,
    "index": cmd_index,
    "shards": cmd_shards,
    "wal": cmd_wal,
    "lint": cmd_lint,
    "stream": cmd_stream,
    "start-all": cmd_start_all,
    "stop-all": cmd_stop_all,
    "redeploy": cmd_redeploy,
    "shell": cmd_shell,
}

_APP_COMMANDS = {
    "new": cmd_app_new,
    "list": cmd_app_list,
    "show": cmd_app_show,
    "delete": cmd_app_delete,
    "data-delete": cmd_app_data_delete,
    "channel-new": cmd_channel_new,
    "channel-delete": cmd_channel_delete,
}

_TEMPLATE_COMMANDS = {
    "list": cmd_template_list,
    "get": cmd_template_get,
}

_ACCESSKEY_COMMANDS = {
    "new": cmd_accesskey_new,
    "list": cmd_accesskey_list,
    "delete": cmd_accesskey_delete,
}

_FLEET_COMMANDS = {
    "route": cmd_fleet_route,
    "rollout": cmd_fleet_rollout,
    "experiment": cmd_fleet_experiment,
}

_STORE_COMMANDS = {
    "status": cmd_store_status,
    "promote": cmd_store_promote,
    "scrub": cmd_store_scrub,
}

_BACKUP_COMMANDS = {
    "create": cmd_backup_create,
    "verify": cmd_backup_verify,
    "restore": cmd_backup_restore,
    "list": cmd_backup_list,
    "prune": cmd_backup_prune,
}

_JOBS_COMMANDS = {
    "submit": cmd_jobs_submit,
    "list": cmd_jobs_list,
    "watch": cmd_jobs_watch,
    "cancel": cmd_jobs_cancel,
    "retry": cmd_jobs_retry,
    "prune": cmd_jobs_prune,
    "worker": cmd_jobs_worker,
    "triggers": cmd_jobs_triggers,
}

_DIST_COMMANDS = {
    "status": cmd_dist_status,
}


def main(argv: Optional[list[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not args.command:
        parser.print_help()
        return 1
    # The engine directory is the import path: a variant's ``engineFactory``
    # names a module in the user's engine dir, and `pio train` in that dir
    # must resolve it — the counterpart of the reference putting `pio build`'s
    # jar on the classpath (console/Console.scala). `python -m` adds cwd
    # already; the installed `pio-tpu` script does not.
    if os.getcwd() not in sys.path and "" not in sys.path:
        sys.path.insert(0, os.getcwd())
    # INFO-level console logging, like the reference console's log4j default
    # (WorkflowUtils.modifyLogging); framework INFO lines (mesh layout,
    # sharded reads, checkpoints) are part of the operator surface
    logging.basicConfig(
        level=logging.DEBUG if getattr(args, "verbose", False) else logging.INFO,
        format="[%(levelname)s] [%(name)s] %(message)s",
    )
    storage = get_storage()
    if args.command == "app":
        if not args.app_command:
            parser.parse_args(["app", "--help"])
            return 1
        return _APP_COMMANDS[args.app_command](args, storage)
    if args.command == "accesskey":
        if not args.accesskey_command:
            parser.parse_args(["accesskey", "--help"])
            return 1
        return _ACCESSKEY_COMMANDS[args.accesskey_command](args, storage)
    if args.command == "fleet":
        if not args.fleet_command:
            _err("fleet: missing subcommand (route|rollout|experiment)")
            return 1
        return _FLEET_COMMANDS[args.fleet_command](args, storage)
    if args.command == "store":
        if not args.store_command:
            _err("store: missing subcommand (status|promote|scrub)")
            return 1
        return _STORE_COMMANDS[args.store_command](args, storage)
    if args.command == "backup":
        if not args.backup_command:
            _err("backup: missing subcommand (create|verify|restore|"
                 "list|prune)")
            return 1
        return _BACKUP_COMMANDS[args.backup_command](args, storage)
    if args.command == "jobs":
        if not args.jobs_command:
            _err("jobs: missing subcommand (submit|list|watch|cancel|"
                 "retry|prune|worker|triggers)")
            return 1
        return _JOBS_COMMANDS[args.jobs_command](args, storage)
    if args.command == "dist":
        if not args.dist_command:
            _err("dist: missing subcommand (status)")
            return 1
        return _DIST_COMMANDS[args.dist_command](args, storage)
    if args.command == "template":
        if not args.template_command:
            # parse_args(["template", "--help"]) would SystemExit(0); a
            # missing subcommand must FAIL for scripted callers
            _err("template: missing subcommand (list|get)")
            return 1
        return _TEMPLATE_COMMANDS[args.template_command](args, storage)
    return _COMMANDS[args.command](args, storage)


if __name__ == "__main__":
    try:
        code = main()
        sys.stdout.flush()
    except BrokenPipeError:
        # downstream pager/head closed the pipe — conventional silent exit
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        code = 0
    sys.exit(code)
