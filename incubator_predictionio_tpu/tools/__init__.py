"""Operator tools: CLI console, export/import, dashboard, admin API."""
