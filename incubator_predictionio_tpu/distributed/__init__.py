"""Fault-tolerant multi-host TRAINING tier (the serve half landed with the
shard-owner scatter/gather work; this package is its training twin).

N worker processes form a ``jax.distributed`` mesh and train the row-sharded
tables with each owner holding only its ``[lo, hi)`` slice. Robustness rides
three pieces, all in the repo's established idioms:

- :mod:`.meshdir` — a durable coordination directory (heartbeat leases + a
  monotonic mesh **generation**, the epoch-fencing pattern of the shard
  owners) shared by the members and their supervisor;
- :mod:`.checkpoint` — coordinated slice checkpointing: every member saves
  its OWN rows, a commit marker lands only after all slices are durable, so
  a kill between slices can never compose two histories;
- :mod:`.context` / :mod:`.supervisor` — the in-process guard (collective
  timeout detection, generation fencing, self-abort on lost peers) and the
  process-level supervisor that detects member loss, bumps the generation,
  re-forms the mesh, and resumes from the last committed checkpoint.

docs/sharding.md ("Multi-host training") is the operator walkthrough.
"""

from incubator_predictionio_tpu.distributed.checkpoint import DistSliceCheckpointer
from incubator_predictionio_tpu.distributed.context import (
    DistConfig,
    DistContext,
    FencedGenerationError,
    MemberLostError,
    maybe_wrap_distributed,
)
from incubator_predictionio_tpu.distributed.meshdir import MeshDirectory
from incubator_predictionio_tpu.distributed.supervisor import Supervisor, SupervisorResult

__all__ = [
    "DistConfig",
    "DistContext",
    "DistSliceCheckpointer",
    "FencedGenerationError",
    "MemberLostError",
    "MeshDirectory",
    "Supervisor",
    "SupervisorResult",
    "maybe_wrap_distributed",
]
