"""Supervisor — forms, watches, and re-forms a distributed training mesh.

``launch_local`` runs a coordinated job and *waits*; the supervisor is its
fault-tolerant sibling: it spawns the N member processes, polls them, and
treats any member exit before the group finishes as a mesh loss:

1. record the detection time, SIGKILL the survivors (their in-step gloo
   collectives can never complete once a peer is gone);
2. bump the mesh **generation** in the coordination directory — durable
   BEFORE any relaunch, so a zombie that somehow survived the kill is
   fenced out of commits and collectives;
3. relaunch all N members on a FRESH coordinator port (a zombie holding
   the old port cannot answer a new-generation collective) with
   ``PIO_DIST_GENERATION`` advanced; members resume from the last
   committed slice checkpoint.

Recovery is bounded by ``PIO_DIST_MAX_RECOVERIES``; each recovery's MTTR
(detect → new mesh spawned) is recorded for the chaos test and the
``distributed_training`` bench lane. Member output goes to per-member,
per-generation log files under ``<state_dir>/logs/`` — the evidence the
chaos test greps for the pinned "resuming from epoch" line.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
from dataclasses import dataclass
from typing import Optional, Sequence

from incubator_predictionio_tpu.distributed import dist_metrics
from incubator_predictionio_tpu.distributed.meshdir import MeshDirectory
from incubator_predictionio_tpu.parallel.launcher import CLI_MODULE, free_port
from incubator_predictionio_tpu.resilience.clock import Clock, SYSTEM_CLOCK

logger = logging.getLogger(__name__)

#: supervision poll cadence — member exits are detected within this
_POLL_S = 0.1


@dataclass
class SupervisorResult:
    """What a supervised run proved."""

    ok: bool
    returncodes: list[int]          # final generation's exit codes
    recoveries: int                 # mesh re-formations performed
    mttr_s: list[float]             # detect → respawn, one per recovery
    generation: int                 # generation that finished (or gave up)
    log_paths: list[str]            # every member log, all generations
    timed_out: bool = False
    detail: str = ""

    def logs_text(self, rank: Optional[int] = None) -> str:
        """Concatenated member logs (optionally one rank's only), newest
        generation last — what log-pinned assertions read."""
        out = []
        for p in self.log_paths:
            if rank is not None and f"member-{rank}." not in os.path.basename(p):
                continue
            try:
                with open(p, "r", errors="replace") as f:
                    out.append(f.read())
            except OSError:
                continue
        return "\n".join(out)


class Supervisor:
    """Drive one distributed train job to completion through member losses."""

    def __init__(
        self,
        cli_args: Sequence[str],
        num_processes: int,
        state_dir: str,
        heartbeat_ms: int = 2000,
        max_recoveries: int = 2,
        cpu_devices_per_process: Optional[int] = None,
        env: Optional[dict[str, str]] = None,
        timeout: Optional[float] = None,
        clock: Clock = SYSTEM_CLOCK,
        command: Optional[Sequence[str]] = None,
        should_abort=None,
    ):
        if num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        self.cli_args = list(cli_args)
        self.num_processes = num_processes
        self.meshdir = MeshDirectory(state_dir)
        self.heartbeat_ms = heartbeat_ms
        self.max_recoveries = max_recoveries
        self.cpu_devices_per_process = cpu_devices_per_process
        self.env = dict(env or {})
        self.timeout = timeout
        self._clock = clock
        self.command = list(command) if command is not None else None
        #: jobs-worker seam: checked each poll; True aborts the whole run
        #: (the worker lost its lease — a fenced attempt must not keep
        #: training in the background)
        self.should_abort = should_abort
        self.log_dir = os.path.join(self.meshdir.state_dir, "logs")
        os.makedirs(self.log_dir, exist_ok=True)
        self._procs: list[subprocess.Popen] = []
        self._log_files: list = []
        self._log_paths: list[str] = []

    # -- lifecycle ---------------------------------------------------------
    def run(self) -> SupervisorResult:
        recoveries = 0
        mttrs: list[float] = []
        deadline = (None if self.timeout is None
                    else self._clock.monotonic() + self.timeout)
        generation = self.meshdir.bump_generation(self.num_processes)
        self._spawn(generation)
        try:
            while True:
                rcs = [p.poll() for p in self._procs]
                if all(rc == 0 for rc in rcs):
                    return self._result(True, recoveries, mttrs, generation)
                if self.should_abort is not None and self.should_abort():
                    self._kill_all()
                    return self._result(
                        False, recoveries, mttrs, generation,
                        detail="aborted by owner (lease/fence lost)")
                if deadline is not None and self._clock.monotonic() >= deadline:
                    self._kill_all()
                    return self._result(False, recoveries, mttrs, generation,
                                        timed_out=True, detail="timeout")
                dead = [(r, rc) for r, rc in enumerate(rcs)
                        if rc is not None and rc != 0]
                if dead:
                    t_detect = self._clock.monotonic()
                    dist_metrics.DIST_STEP_ABORTS.inc()
                    logger.warning(
                        "dist supervisor: member loss in generation %d: %s",
                        generation,
                        ", ".join(f"rank {r} rc={rc}" for r, rc in dead))
                    if recoveries >= self.max_recoveries:
                        self._kill_all()
                        return self._result(
                            False, recoveries, mttrs, generation,
                            detail=f"member loss after {recoveries} "
                                   "recoveries (budget exhausted)")
                    self._kill_all()
                    # fence first, spawn second: a zombie must read the new
                    # generation before any new-mesh member can commit
                    generation = self.meshdir.bump_generation(
                        self.num_processes)
                    self.meshdir.clear_members()
                    recoveries += 1
                    self._spawn(generation)
                    mttrs.append(self._clock.monotonic() - t_detect)
                    logger.warning(
                        "dist supervisor: mesh re-formed as generation %d "
                        "(recovery %d, MTTR %.2fs)",
                        generation, recoveries, mttrs[-1])
                self._clock.sleep(_POLL_S)
        finally:
            self._kill_all()
            self._close_logs()

    def alive_pids(self) -> dict[int, int]:
        """rank → pid of currently-running members (chaos tests aim their
        SIGKILL with this)."""
        return {r: p.pid for r, p in enumerate(self._procs)
                if p.poll() is None}

    # -- internals ---------------------------------------------------------
    def _spawn(self, generation: int) -> None:
        port = free_port()
        dist_metrics.DIST_GENERATION.set(generation)
        dist_metrics.DIST_MEMBERS.set(self.num_processes)
        self._procs = []
        self._log_files = []
        for rank in range(self.num_processes):
            penv = dict(os.environ)
            penv.update(self.env)
            penv["PIO_DIST_COORDINATOR"] = f"127.0.0.1:{port}"
            penv["PIO_DIST_NUM_PROCESSES"] = str(self.num_processes)
            penv["PIO_DIST_PROCESS_ID"] = str(rank)
            penv["PIO_DIST_STATE_DIR"] = self.meshdir.state_dir
            penv["PIO_DIST_GENERATION"] = str(generation)
            penv["PIO_DIST_HEARTBEAT_MS"] = str(self.heartbeat_ms)
            if self.cpu_devices_per_process:
                penv["JAX_PLATFORMS"] = "cpu"
                flags = penv.get("XLA_FLAGS", "")
                flags = " ".join(
                    f for f in flags.split()
                    if "xla_force_host_platform_device_count" not in f)
                penv["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count="
                    f"{self.cpu_devices_per_process}").strip()
            path = os.path.join(self.log_dir,
                                f"member-{rank}.gen-{generation}.log")
            # append mode: file objects double as the capture sink (pipes
            # deadlock coordinated peers, see launcher.py)
            f = open(path, "a")
            self._log_files.append(f)
            self._log_paths.append(path)
            self._procs.append(subprocess.Popen(
                self.command if self.command is not None
                else [sys.executable, "-m", CLI_MODULE, *self.cli_args],
                env=penv, stdout=f, stderr=subprocess.STDOUT, text=True,
            ))

    def _kill_all(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                p.kill()
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass

    def _close_logs(self) -> None:
        for f in self._log_files:
            try:
                f.close()
            except OSError:  # pragma: no cover
                pass

    def _result(self, ok: bool, recoveries: int, mttrs: list[float],
                generation: int, timed_out: bool = False,
                detail: str = "") -> SupervisorResult:
        dist_metrics.DIST_MEMBERS.set(
            sum(1 for p in self._procs if p.poll() is None))
        return SupervisorResult(
            ok=ok,
            returncodes=[(-1 if p.poll() is None else p.returncode)
                         for p in self._procs],
            recoveries=recoveries,
            mttr_s=mttrs,
            generation=generation,
            log_paths=list(self._log_paths),
            timed_out=timed_out,
            detail=detail,
        )
