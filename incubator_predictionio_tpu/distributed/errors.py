"""Failure vocabulary of the distributed training tier.

Both errors are *verdicts*, not bugs: they name the two ways a member can
fall out of a mesh, and callers (the epoch driver, the supervisor, the jobs
worker) branch on them for recovery accounting.
"""

from __future__ import annotations


class MemberLostError(RuntimeError):
    """A peer stopped answering within its heartbeat lease (or a collective
    failed outright). The step is lost; the supervisor bumps the generation
    and re-forms the mesh — training resumes from the last commit."""


class FencedGenerationError(RuntimeError):
    """This process's mesh generation is older than the directory's — it is
    a zombie from a torn-down mesh. It must neither commit a checkpoint nor
    answer a collective; the only correct move is to stop."""
