"""DistSliceCheckpointer — coordinated slice checkpoints for multi-host fit.

Drop-in for :class:`~incubator_predictionio_tpu.utils.checkpoint.TrainCheckpointer`
(same ``save/latest_step/all_steps/restore(like=)/delete_all/close`` surface,
injected through ``maybe_resume(factory=...)``), but each mesh member writes
only the rows it OWNS — the ``replica_id == 0`` addressable shards of every
sharded leaf, straight off the device, no host gather of the full table —
and a step is restorable only once member 0 has written the commit marker,
which it does strictly after observing every member's slice durable on the
shared filesystem.

Two-phase discipline (filesystem protocol in ``utils/checkpoint.py``):

1. every member: atomic npz (data) then atomic manifest (= done marker),
   both carrying the member's mesh **generation**;
2. member 0: poll for all ``members`` manifests of its own generation,
   re-check the fencing token, then atomically write ``commit-<step>.json``.

A kill anywhere in phase 1 or 2 leaves the step uncommitted → restore uses
the previous commit; a zombie from an older generation fails the fence
re-check and cannot commit (``pio_dist_fenced_total``); a slice written by
an older generation never satisfies the phase-2 poll. Composing two
histories is therefore structurally impossible, which is the whole point.
"""

from __future__ import annotations

import logging
import shutil
from typing import Any, Callable, Optional

import numpy as np

from incubator_predictionio_tpu.distributed import dist_metrics
from incubator_predictionio_tpu.distributed.errors import (
    FencedGenerationError,
    MemberLostError,
)
from incubator_predictionio_tpu.distributed.meshdir import MeshDirectory
from incubator_predictionio_tpu.resilience.clock import Clock, SYSTEM_CLOCK
from incubator_predictionio_tpu.utils import checkpoint as ckpt_fs

logger = logging.getLogger(__name__)

#: commit-poll cadence — cheap manifest stats on a local/shared fs
_POLL_S = 0.025


class DistSliceCheckpointer:
    """Slice-aware checkpointer for one mesh member.

    ``slice_fn(leaf_idx, leaf, member, members)`` (tests / fake members)
    overrides shard discovery: return ``[(block, index_or_None), ...]`` for
    the blocks this member owns (``[]`` when none). Without it, ownership
    comes from the leaf's addressable ``replica_id == 0`` shards, so the
    real multi-process path and the simulated one share every line below
    the slicing seam.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        members: int = 1,
        member: int = 0,
        generation: int = 0,
        meshdir: Optional[MeshDirectory] = None,
        slice_fn: Optional[Callable] = None,
        clock: Clock = SYSTEM_CLOCK,
        commit_timeout_ms: int = 60_000,
    ):
        import os

        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.members = int(members)
        self.member = int(member)
        self.generation = int(generation)
        self.meshdir = meshdir
        self._slice_fn = slice_fn
        self._clock = clock
        self.commit_timeout_ms = commit_timeout_ms

    # -- TrainCheckpointer surface ----------------------------------------
    def save(self, step: int, state: Any) -> None:
        """Write this member's slice; on member 0, also drive the commit.
        Returning means: my slice is durable, and (member 0 only) the step
        is committed. Raises :class:`FencedGenerationError` before touching
        disk when the mesh has moved on — a zombie cannot even dirty the
        slice files of the generation that replaced it."""
        import jax

        self._check_fence()
        leaves = jax.tree_util.tree_leaves(state)
        entries, arrays = [], {}
        for i, leaf in enumerate(leaves):
            for j, (block, index) in enumerate(self._local_blocks(i, leaf)):
                key = f"l{i}b{j}"
                entries.append({
                    "key": key, "leaf": i,
                    "globalShape": [int(s) for s in np.shape(leaf)],
                    "index": index,
                })
                arrays[key] = block
        ckpt_fs.save_member_slice(self.directory, step, self.member,
                                  self.generation, entries, arrays)
        if self.member == 0:
            self._commit(step)

    def latest_step(self) -> Optional[int]:
        steps = ckpt_fs.committed_steps(self.directory)
        return steps[-1] if steps else None

    def all_steps(self) -> list[int]:
        return ckpt_fs.committed_steps(self.directory)

    def delete_all(self) -> None:
        import os

        shutil.rmtree(os.path.join(self.directory, ckpt_fs.SLICES_DIR),
                      ignore_errors=True)

    def restore(self, step: Optional[int] = None, like: Any = None) -> Any:
        """Reassemble the full host-side state of a COMMITTED step (every
        member restores the whole tree; placement back onto the mesh is
        ``restore_placed``'s job, exactly as with the orbax checkpointer)."""
        import jax

        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no committed steps under {self.directory}")
        leaves = ckpt_fs.assemble_committed_step(self.directory, step)
        if like is None:
            return leaves
        treedef = jax.tree_util.tree_structure(like)
        if treedef.num_leaves != len(leaves):
            raise ValueError(
                f"committed step {step} has {len(leaves)} leaves, template "
                f"has {treedef.num_leaves}")
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def close(self) -> None:
        """No manager handle to release (parity with TrainCheckpointer)."""

    def __enter__(self) -> "DistSliceCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- slicing -----------------------------------------------------------
    def _local_blocks(self, leaf_idx: int, leaf: Any) -> list:
        """Blocks of ``leaf`` this member owns: ``[(host_array, index), ...]``
        where ``index`` is ``[[lo, hi], None, ...]`` for a row block or
        ``None`` for the whole (replicated / host) leaf."""
        if self._slice_fn is not None:
            return list(self._slice_fn(leaf_idx, leaf, self.member, self.members))
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            # plain host leaf (the epoch counter): member 0 carries it
            return [(np.asarray(leaf), None)] if self.member == 0 else []
        ndim = getattr(leaf, "ndim", 0)
        rows = int(leaf.shape[0]) if ndim else 1
        blocks = []
        for s in shards:
            if s.replica_id != 0:
                continue  # another shard holds the canonical copy
            idx = tuple(s.index)
            for d, sl in enumerate(idx[1:], start=1):
                lo_d, hi_d, _ = sl.indices(int(leaf.shape[d]))
                if (lo_d, hi_d) != (0, int(leaf.shape[d])):
                    raise ValueError(
                        "slice checkpointing supports row-sharded leaves "
                        f"only; leaf {leaf_idx} is split on dim {d}")
            lo, hi, _ = idx[0].indices(rows) if idx else (0, rows, 1)
            if (lo, hi) == (0, rows):
                blocks.append((np.asarray(s.data), None))
            else:
                blocks.append((np.asarray(s.data),
                               [[int(lo), int(hi)]] + [None] * (ndim - 1)))
        return blocks

    # -- commit ------------------------------------------------------------
    def _check_fence(self) -> None:
        if self.meshdir is None:
            return
        current, _ = self.meshdir.read_generation()
        if current > self.generation:
            dist_metrics.DIST_FENCED.inc()
            raise FencedGenerationError(
                f"mesh generation is {current}, this member holds "
                f"{self.generation}: fenced, refusing to touch checkpoints")

    def _commit(self, step: int) -> None:
        deadline = self._clock.monotonic() + self.commit_timeout_ms / 1000.0
        while True:
            done = ckpt_fs.members_done(self.directory, step, self.members,
                                        self.generation)
            if len(done) == self.members:
                break
            self._check_fence()
            if self._clock.monotonic() >= deadline:
                dist_metrics.DIST_STEP_ABORTS.inc()
                missing = sorted(set(range(self.members)) - set(done))
                raise MemberLostError(
                    f"checkpoint step {step}: members {missing} did not "
                    f"write their slice within {self.commit_timeout_ms}ms")
            self._clock.sleep(_POLL_S)
        # the token may have moved while we polled — a commit from a fenced
        # generation is exactly the composed-history bug, so re-check LAST
        self._check_fence()
        ckpt_fs.write_commit_marker(self.directory, step, self.generation,
                                    self.members)
        dist_metrics.DIST_COMMITS.inc()
        if self.meshdir is not None:
            self.meshdir.record_commit(step, self.generation)
        ckpt_fs.gc_slice_steps(self.directory, self.max_to_keep)
        logger.info("dist checkpoint: committed step %d (generation %d, "
                    "%d members)", step, self.generation, self.members)
