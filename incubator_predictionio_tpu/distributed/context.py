"""DistContext — the fault-aware wrapper a distributed member trains under.

Duck-types :class:`~incubator_predictionio_tpu.parallel.mesh.MeshContext`
(every attribute it does not define delegates to the wrapped context, so
engine/stage code is unchanged) and adds the three member-side behaviours
of the fault-tolerant tier:

- **heartbeat lease** — a daemon thread renews ``member-<rank>.json`` in
  the :class:`~incubator_predictionio_tpu.distributed.meshdir.MeshDirectory`
  every third of ``PIO_DIST_HEARTBEAT_MS``;
- **collective guard** — host-level collectives (``allgather_obj``, which
  the sharded input path rides for vocab/row-count exchange) run under a
  watchdog: a peer whose lease expires, a generation bump, or an outright
  collective failure aborts the step with :class:`MemberLostError` /
  :class:`FencedGenerationError` instead of hanging in gloo forever;
- **self-abort** — the in-step XLA collectives of a jitted train chunk
  cannot be cancelled from Python, so in real multi-process mode a
  watchdog thread ``os._exit``\\ s the process when peers are lost or the
  member is fenced; the supervisor observes the exit and re-forms the
  mesh. One step lost, never a hang.

The degenerate single-process mesh gets the same wrapper minus the
threads — every fencing/checkpoint contract stays tier-1-testable on a
FakeClock with zero wall sleeps.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Any, Optional

from incubator_predictionio_tpu.distributed import dist_metrics
from incubator_predictionio_tpu.distributed.checkpoint import DistSliceCheckpointer
from incubator_predictionio_tpu.distributed.errors import (
    FencedGenerationError,
    MemberLostError,
)
from incubator_predictionio_tpu.distributed.meshdir import MeshDirectory
from incubator_predictionio_tpu.resilience.clock import Clock, SYSTEM_CLOCK

logger = logging.getLogger(__name__)

#: exit codes a self-aborting member hands the supervisor — recognizable in
#: logs/bench archives, distinct from a python crash's 1
ABORT_RC = 86    # lost a peer mid-step
FENCED_RC = 87   # fenced by a newer generation

#: collective-guard poll cadence (wall under SystemClock, virtual under Fake)
_POLL_S = 0.05


@dataclasses.dataclass(frozen=True)
class DistConfig:
    """The PIO_DIST_* knob surface (docs/configuration.md)."""

    state_dir: str = ""
    heartbeat_ms: int = 2000
    quorum: int = 0            # 0 = majority of expected members
    commit_timeout_ms: int = 60_000
    generation: int = 0
    max_recoveries: int = 2

    @staticmethod
    def from_env() -> "DistConfig":
        return DistConfig(
            state_dir=os.environ.get("PIO_DIST_STATE_DIR", ""),
            heartbeat_ms=int(os.environ.get("PIO_DIST_HEARTBEAT_MS", "2000")),
            quorum=int(os.environ.get("PIO_DIST_QUORUM", "0")),
            commit_timeout_ms=int(
                os.environ.get("PIO_DIST_COMMIT_TIMEOUT_MS", "60000")),
            generation=int(os.environ.get("PIO_DIST_GENERATION", "0")),
            max_recoveries=int(os.environ.get("PIO_DIST_MAX_RECOVERIES", "2")),
        )


def maybe_wrap_distributed(ctx, clock: Clock = SYSTEM_CLOCK):
    """The workflow seam: wrap ``ctx`` when ``PIO_DIST_STATE_DIR`` names a
    coordination directory (the supervisor always sets it for members),
    return it untouched otherwise — zero cost on the plain path."""
    conf = DistConfig.from_env()
    if not conf.state_dir:
        return ctx
    return DistContext(ctx, conf, clock=clock)


class DistContext:
    """One member's fault-aware view of the mesh."""

    def __init__(
        self,
        inner,
        conf: DistConfig,
        meshdir: Optional[MeshDirectory] = None,
        clock: Clock = SYSTEM_CLOCK,
        start_threads: Optional[bool] = None,
    ):
        self._inner = inner
        self.conf = conf
        self._clock = clock
        self.generation = conf.generation
        self.meshdir = meshdir or (
            MeshDirectory(conf.state_dir) if conf.state_dir else None)
        self._step = 0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        if self.meshdir is not None:
            self.meshdir.announce_generation(self.generation,
                                             inner.process_count)
            self.meshdir.heartbeat(inner.process_index, self.generation,
                                   step=0)
        dist_metrics.DIST_GENERATION.set(self.generation)
        real = (inner.process_count > 1 and self.meshdir is not None
                if start_threads is None else start_threads)
        if real:
            self._start_threads()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    # -- the fit() seam ----------------------------------------------------
    @property
    def dist_hooks(self) -> "DistContext":
        """What trainers pick up via ``getattr(ctx, "dist_hooks", None)``."""
        return self

    def checkpointer_factory(self, directory: str,
                             max_to_keep: int = 3) -> DistSliceCheckpointer:
        """``maybe_resume(factory=...)`` — slice checkpoints instead of the
        whole-tree orbax manager."""
        return DistSliceCheckpointer(
            directory,
            max_to_keep=max_to_keep,
            members=self._inner.process_count,
            member=self._inner.process_index,
            generation=self.generation,
            meshdir=self.meshdir,
            clock=self._clock,
            commit_timeout_ms=self.conf.commit_timeout_ms,
        )

    def on_chunk(self, epoch: int) -> None:
        """Chunk-boundary hook from ``checkpointed_epochs``: renew the
        lease with training progress, then verify the mesh is still ours —
        aborting HERE costs one chunk; hanging in the next collective
        costs the whole heartbeat timeout."""
        self._step = int(epoch)
        if self.meshdir is not None:
            self.meshdir.heartbeat(self._inner.process_index, self.generation,
                                   step=self._step)
        self.check_peers()

    # -- fault detection ---------------------------------------------------
    def check_peers(self) -> None:
        """Raise the verdict for the current mesh state: fenced when the
        generation moved past ours, member-lost when a peer's lease
        expired; otherwise update the liveness gauge and return."""
        if self.meshdir is None:
            return
        current, _ = self.meshdir.read_generation()
        if current > self.generation:
            dist_metrics.DIST_FENCED.inc()
            raise FencedGenerationError(
                f"mesh generation is {current}, this member holds "
                f"{self.generation}")
        stale = self.meshdir.stale_members(self.conf.heartbeat_ms,
                                           self.generation)
        if stale:
            dist_metrics.DIST_STEP_ABORTS.inc()
            raise MemberLostError(
                "peer heartbeat expired: "
                + ", ".join(f"rank {m.rank} (pid {m.pid})" for m in stale))
        dist_metrics.DIST_MEMBERS.set(
            len(self.meshdir.alive_members(self.conf.heartbeat_ms,
                                           self.generation)))

    def allgather_obj(self, obj: Any) -> list[Any]:
        """The guarded host-metadata collective (vocab union, row counts).
        Without a coordination directory this is a straight delegate."""
        if self.meshdir is None:
            return self._inner.allgather_obj(obj)
        return self._guarded("allgather_obj",
                             lambda: self._inner.allgather_obj(obj))

    def _guarded(self, what: str, fn):
        """Run a blocking collective in a side thread and poll for loss:
        gloo gives no cancellable handle, so the guard's job is to turn
        'peer died, call will never return' into a prompt MemberLostError
        (the stuck daemon thread is abandoned — the process is about to
        either abort the step or exit)."""
        box: dict[str, Any] = {}
        done = threading.Event()

        def run():
            try:
                box["value"] = fn()
            except BaseException as e:  # noqa: BLE001 — relayed as verdict
                box["error"] = e
            finally:
                done.set()

        threading.Thread(target=run, daemon=True,
                         name=f"dist-{what}").start()
        hb_s = self.conf.heartbeat_ms / 1000.0
        deadline = self._clock.monotonic() + max(
            10.0 * hb_s, self.conf.commit_timeout_ms / 1000.0)
        while not done.is_set():
            self.check_peers()
            if self._clock.monotonic() >= deadline:
                dist_metrics.DIST_STEP_ABORTS.inc()
                raise MemberLostError(
                    f"collective {what} stalled past the loss deadline")
            self._clock.sleep(min(hb_s / 4.0, _POLL_S))
            # scheduling yield: under FakeClock the sleep above is virtual,
            # so give the collective thread a real slot to finish in
            done.wait(0.001)
        if "error" in box:
            dist_metrics.DIST_STEP_ABORTS.inc()
            raise MemberLostError(
                f"collective {what} failed: {box['error']}") from box["error"]
        return box["value"]

    # -- member threads (real multi-process mode) --------------------------
    def _start_threads(self) -> None:
        hb = threading.Thread(target=self._heartbeat_loop, daemon=True,
                              name="dist-heartbeat")
        wd = threading.Thread(target=self._watchdog_loop, daemon=True,
                              name="dist-watchdog")
        self._threads = [hb, wd]
        hb.start()
        wd.start()

    def _heartbeat_loop(self) -> None:
        period = self.conf.heartbeat_ms / 3000.0
        while not self._stop.is_set():
            try:
                self.meshdir.heartbeat(self._inner.process_index,
                                       self.generation, step=self._step)
            except OSError:  # pragma: no cover - transient fs trouble
                pass
            self._clock.sleep(period)

    def _watchdog_loop(self) -> None:  # pragma: no cover - exercised by
        # the real-subprocess chaos test, not in-process tier-1
        period = self.conf.heartbeat_ms / 3000.0
        while not self._stop.is_set():
            try:
                self.check_peers()
            except FencedGenerationError as e:
                logger.error("dist watchdog: %s — exiting fenced", e)
                logging.shutdown()
                os._exit(FENCED_RC)
            except MemberLostError as e:
                # a jitted chunk's XLA collectives cannot be cancelled:
                # exiting is the only way to unstick this member so the
                # supervisor can re-form the mesh
                logger.error("dist watchdog: %s — aborting step, exiting "
                             "for mesh re-formation", e)
                logging.shutdown()
                os._exit(ABORT_RC)
            except OSError:
                pass  # transient fs trouble: retry next tick
            self._clock.sleep(period)

    def stop(self) -> None:
        self._stop.set()
        self._inner.stop()

    def __repr__(self) -> str:  # pragma: no cover
        return (f"DistContext(gen={self.generation}, "
                f"inner={self._inner!r})")
