"""``pio_dist_*`` metrics for the fault-tolerant multi-host training tier
(docs/observability.md)."""

from __future__ import annotations

from incubator_predictionio_tpu.obs.metrics import REGISTRY

DIST_MEMBERS = REGISTRY.gauge(
    "pio_dist_members",
    "Live members of the current training mesh generation (supervisor / "
    "heartbeat view; drops below the expected count while a loss is being "
    "recovered)")
DIST_GENERATION = REGISTRY.gauge(
    "pio_dist_generation",
    "Current mesh generation — the monotonic fencing token; every bump is "
    "one mesh re-formation after a member loss")
DIST_STEP_ABORTS = REGISTRY.counter(
    "pio_dist_step_aborts_total",
    "Training steps aborted because a member was lost mid-collective "
    "(heartbeat lease expired or the collective itself failed)")
DIST_FENCED = REGISTRY.counter(
    "pio_dist_fenced_total",
    "Actions refused because the actor's generation was stale — a zombie "
    "from a torn-down mesh tried to commit a checkpoint or join a collective")
DIST_COMMITS = REGISTRY.counter(
    "pio_dist_checkpoint_commits_total",
    "Coordinated checkpoint commits (marker written only after every "
    "member's slice is durable)")
