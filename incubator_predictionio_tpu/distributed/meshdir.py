"""MeshDirectory — the durable coordination directory of a distributed run.

The mesh members and their supervisor share no sockets beyond the gloo
collectives themselves (which cannot carry control decisions: a hung
all-gather is exactly the failure being detected). Coordination instead
rides a directory of small atomically-written JSON records — the same
``utils/fs.atomic_write_bytes`` discipline the WAL cursor and shard-owner
epoch files use — so every decision survives kill -9 and is inspectable
with ``cat`` (and ``pio-tpu dist status``):

- ``generation.json`` — the monotonic mesh **generation** (the PR 9/11/16
  epoch-fencing pattern applied to training): bumped by the supervisor
  every time the mesh re-forms. A member that reads a generation newer than its
  own is a zombie from a torn-down mesh — it must neither commit a
  checkpoint nor answer a collective.
- ``member-<rank>.json`` — per-member heartbeat lease: pid, generation,
  last beat (wall clock — monotonic clocks are not comparable across
  processes) and the member's last reported step.
- ``last-commit.json`` — the newest coordinated checkpoint commit, for
  ``/health`` and ``dist status`` (the authoritative commit markers live
  in the checkpoint directory; this is the observability mirror).

Timestamps are wall-clock by necessity (cross-process comparison) and the
time source is injectable (``now_fn``) so staleness decisions are testable
on a virtual clock with zero wall sleeps.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from dataclasses import dataclass
from typing import Callable, Optional

from incubator_predictionio_tpu.utils.fs import atomic_write_bytes

GENERATION_FILE = "generation.json"
LAST_COMMIT_FILE = "last-commit.json"
LOCK_FILE = ".lock"


@dataclass(frozen=True)
class MemberRecord:
    """One member's heartbeat lease as last written."""

    rank: int
    pid: int
    generation: int
    beat_at: float
    step: int

    def age_s(self, now: float) -> float:
        return max(0.0, now - self.beat_at)


def default_quorum(members: int) -> int:
    """Majority — the smallest count that cannot split-brain."""
    return members // 2 + 1


class MeshDirectory:
    """Read/write the coordination records under ``state_dir``."""

    def __init__(self, state_dir: str, now_fn: Callable[[], float] = time.time):
        self.state_dir = os.path.abspath(state_dir)
        os.makedirs(self.state_dir, exist_ok=True)
        self._now = now_fn

    # -- generation (the fencing token) -----------------------------------
    def read_generation(self) -> tuple[int, int]:
        """``(generation, members)`` — ``(0, 0)`` before the first announce."""
        rec = self._read_json(GENERATION_FILE)
        if not rec:
            return 0, 0
        return int(rec.get("generation", 0)), int(rec.get("members", 0))

    def announce_generation(self, generation: int, members: int) -> None:
        """Persist a generation the caller already owns (member bootstrap
        from ``PIO_DIST_GENERATION``: idempotent, never moves backwards)."""
        with self._locked():
            current, _ = self.read_generation()
            if generation < current:
                return
            self._write_json(GENERATION_FILE, {
                "generation": int(generation), "members": int(members),
                "updatedAt": self._now(),
            })

    def bump_generation(self, members: int) -> int:
        """Advance the fencing token (supervisor, before re-forming the
        mesh). Durable before return — a zombie that reads the directory
        after this sees itself fenced."""
        with self._locked():
            current, _ = self.read_generation()
            nxt = current + 1
            self._write_json(GENERATION_FILE, {
                "generation": nxt, "members": int(members),
                "updatedAt": self._now(),
            })
            return nxt

    # -- heartbeats --------------------------------------------------------
    def heartbeat(self, rank: int, generation: int, pid: Optional[int] = None,
                  step: int = 0) -> None:
        """Renew member ``rank``'s lease. Non-durable write (``durable=False``):
        a lost heartbeat is indistinguishable from a late one and the next
        beat overwrites it — fsync per beat would put a disk flush on the
        training hot path for no correctness gain."""
        self._write_json(f"member-{int(rank)}.json", {
            "rank": int(rank),
            "pid": int(os.getpid() if pid is None else pid),
            "generation": int(generation),
            "beatAt": self._now(),
            "step": int(step),
        }, durable=False)

    def members(self) -> list[MemberRecord]:
        out = []
        for name in sorted(os.listdir(self.state_dir)):
            if not (name.startswith("member-") and name.endswith(".json")):
                continue
            rec = self._read_json(name)
            if not rec:
                continue
            out.append(MemberRecord(
                rank=int(rec.get("rank", -1)),
                pid=int(rec.get("pid", 0)),
                generation=int(rec.get("generation", 0)),
                beat_at=float(rec.get("beatAt", 0.0)),
                step=int(rec.get("step", 0)),
            ))
        return out

    def stale_members(self, heartbeat_ms: int,
                      generation: Optional[int] = None) -> list[MemberRecord]:
        """Members of ``generation`` (default: current) whose lease expired.
        Records from older generations are not stale — they are *fenced*,
        a different verdict (the member is not lost, its mesh is gone)."""
        gen = self.read_generation()[0] if generation is None else generation
        now = self._now()
        return [m for m in self.members()
                if m.generation == gen and m.age_s(now) * 1000.0 > heartbeat_ms]

    def alive_members(self, heartbeat_ms: int,
                      generation: Optional[int] = None) -> list[MemberRecord]:
        gen = self.read_generation()[0] if generation is None else generation
        now = self._now()
        return [m for m in self.members()
                if m.generation == gen and m.age_s(now) * 1000.0 <= heartbeat_ms]

    def clear_members(self) -> None:
        """Drop every heartbeat record (supervisor, between generations —
        a dead member's last beat must not read as alive in the new one)."""
        for name in os.listdir(self.state_dir):
            if name.startswith("member-") and name.endswith(".json"):
                with contextlib.suppress(OSError):
                    os.unlink(os.path.join(self.state_dir, name))

    # -- commit mirror -----------------------------------------------------
    def record_commit(self, step: int, generation: int) -> None:
        self._write_json(LAST_COMMIT_FILE, {
            "step": int(step), "generation": int(generation),
            "committedAt": self._now(),
        })

    def last_commit(self) -> Optional[dict]:
        return self._read_json(LAST_COMMIT_FILE) or None

    # -- health ------------------------------------------------------------
    def health_snapshot(self, heartbeat_ms: int,
                        quorum: Optional[int] = None) -> dict:
        """The ``/health`` mesh block (and the ``dist status`` payload):
        generation, expected vs alive members, last commit, quorum verdict."""
        generation, expected = self.read_generation()
        now = self._now()
        members = [{
            "rank": m.rank, "pid": m.pid, "generation": m.generation,
            "ageMs": round(m.age_s(now) * 1000.0, 1), "step": m.step,
            "alive": m.generation == generation
                     and m.age_s(now) * 1000.0 <= heartbeat_ms,
        } for m in self.members()]
        alive = sum(1 for m in members if m["alive"])
        need = default_quorum(expected) if quorum is None else quorum
        return {
            "stateDir": self.state_dir,
            "generation": generation,
            "expectedMembers": expected,
            "aliveMembers": alive,
            "quorum": need,
            "degraded": expected > 0 and alive < need,
            "members": members,
            "lastCommit": self.last_commit(),
        }

    # -- plumbing ----------------------------------------------------------
    def _path(self, name: str) -> str:
        return os.path.join(self.state_dir, name)

    def _read_json(self, name: str) -> dict:
        try:
            with open(self._path(name), "rb") as f:
                return json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError):
            # atomic_write_bytes means a present file is never torn; missing
            # (no beat yet) or unparsable (foreign junk) both read as absent
            return {}

    def _write_json(self, name: str, payload: dict, durable: bool = True) -> None:
        atomic_write_bytes(self._path(name),
                           json.dumps(payload, sort_keys=True).encode("utf-8"),
                           durable=durable)

    @contextlib.contextmanager
    def _locked(self):
        """flock-guarded read-modify-write for the generation record —
        the supervisor and a bootstrapping member may race an announce."""
        import fcntl

        fd = os.open(self._path(LOCK_FILE), os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)
