"""Eventlog replication: primary→follower shipping with epoch fencing.

One :class:`ReplicationManager` runs inside each storage server process
(server/storage_server.py wires it up when ``--repl-peer``/``PIO_REPL_*``
configure a replica set). The manager owns everything below the RPC
surface; the server's ``/repl/{verb}`` routes are a thin HTTP shim over
:meth:`ReplicationManager.handle`, so the whole protocol is unit-testable
in-process by wiring two managers' ``handle`` methods together — no
sockets, no sleeps.

Protocol (all verbs carry the sender's epoch; stale epochs are fenced):

- ``state``      — follower's per-log byte sizes (the replication cursor:
                   byte offsets ARE sequence numbers).
- ``append``     — one CRC32-verified chunk of complete eventlog records
                   at an exact byte offset. The follower applies it only
                   when the offset equals its current size — a mismatch
                   returns the follower's size so the primary resyncs
                   (the ``wal.tail_frames`` ok/waiting discipline, per
                   replica instead of per reader).
- ``heartbeat``  — epoch exchange; how a restarted stale primary learns
                   it was deposed *before* it can accept a write.
- ``promote``    — bump the persisted epoch, become primary, optionally
                   reconfigure the peer set (failover removes the dead
                   primary until it is scrubbed back in).
- ``digest`` / ``fetch`` / ``patch`` — anti-entropy surface (scrub.py).

Fencing invariant: an epoch is persisted (atomic-write discipline) before
it is ever announced, every replicated append and admin RPC carries it,
and any node that observes a higher epoch than its own immediately stops
accepting writes (``pio_repl_fenced_writes_total`` counts the rejects).
Split-brain therefore cannot corrupt the log: at most one epoch's primary
can get its appends accepted by any follower.
"""

from __future__ import annotations

import base64
import dataclasses
import http.client
import json
import logging
import os
import threading
import urllib.parse
import zlib
from typing import Any, Callable, Optional

from incubator_predictionio_tpu.native import format as fmt
from incubator_predictionio_tpu.obs.metrics import REGISTRY
from incubator_predictionio_tpu.resilience.clock import SYSTEM_CLOCK, Clock
from incubator_predictionio_tpu.utils.fs import atomic_write_bytes

logger = logging.getLogger(__name__)

STATE_FILE = "repl-state.json"
ROLE_PRIMARY = "primary"
ROLE_FOLLOWER = "follower"

_SHIPPED = REGISTRY.counter(
    "pio_repl_shipped_bytes_total",
    "Eventlog bytes this primary shipped to followers (acked appends)")
_APPLIED = REGISTRY.counter(
    "pio_repl_applied_bytes_total",
    "Eventlog bytes this follower applied from replicated appends")
FENCED_WRITES = REGISTRY.counter(
    "pio_repl_fenced_writes_total",
    "Client writes rejected because this storage server is not the "
    "current-epoch primary (demoted, stale, or follower)")
_FENCED_APPENDS = REGISTRY.counter(
    "pio_repl_fenced_appends_total",
    "Replicated appends/heartbeats rejected for carrying a stale epoch "
    "(the split-brain write path that fencing exists to close)")
_CRC_FAILURES = REGISTRY.counter(
    "pio_repl_crc_failures_total",
    "Replicated chunks rejected because the CRC32 did not match on apply")
_DIVERGED = REGISTRY.counter(
    "pio_repl_divergence_detected_total",
    "Ship rounds that found a follower ahead of / disjoint from the "
    "primary (needs `pio-tpu store scrub`)")
_LAG_GAUGE = REGISTRY.gauge(
    "pio_repl_lag_bytes",
    "Replication lag in bytes (primary: bytes not yet acked by the "
    "best-caught-up follower)")
_EPOCH_GAUGE = REGISTRY.gauge(
    "pio_repl_epoch", "This replica's current fencing epoch")
_QUORUM_FAILURES = REGISTRY.counter(
    "pio_repl_quorum_failures_total",
    "Writes that could not reach quorum within the timeout (the storage "
    "server answers 503; the event server spills to its WAL)")


class FencedError(Exception):
    """The peer holds a higher epoch — the caller has been deposed."""

    def __init__(self, remote_epoch: int):
        super().__init__(f"fenced by epoch {remote_epoch}")
        self.remote_epoch = remote_epoch


class ReplicationUnavailable(Exception):
    """Quorum (or the async lag bound) cannot be satisfied right now —
    transient cluster-wise: the storage server answers 503 so clients
    spill/retry rather than treating an unreplicated write as durable."""


# ---------------------------------------------------------------------------
# record-boundary math (PIOLOG01 framing: magic, then [u32 len][payload]*)
# ---------------------------------------------------------------------------

def complete_extent(buf: bytes, file_offset: int) -> int:
    """Bytes of ``buf`` (read from ``file_offset``, which is 0 or a record
    boundary) forming complete PIOLOG records. A partial record at the end
    — the live-writer race — is excluded; ``plen == 0`` (a zeroed torn
    tail the writer will truncate at recovery) also stops the walk, so a
    defect is never shipped as if it were data. The walk itself is
    ``fmt.record_run_end`` — the same one ``valid_extent`` uses."""
    if file_offset == 0:
        if len(buf) < len(fmt.MAGIC) or buf[:len(fmt.MAGIC)] != fmt.MAGIC:
            return 0
        return fmt.record_run_end(buf, len(fmt.MAGIC))
    return fmt.record_run_end(buf, 0)


def tail_extent(path: str, from_offset: int,
                max_bytes: int = 1 << 20) -> tuple[bytes, int, str]:
    """Tail-follow read of complete records past ``from_offset`` — the
    ``wal.tail_frames`` contract transplanted onto the eventlog framing.

    Returns ``(data, next_offset, status)``: ``data`` is the raw byte
    range ``[from_offset, next_offset)`` holding only complete records;
    ``status`` is ``"ok"`` (clean end within the read), ``"waiting"``
    (the file ends mid-record — a live writer's normal artifact, re-poll
    from the same offset) or ``"bounded"`` (the read bound cut a record;
    more data exists on disk right now)."""
    try:
        size = os.path.getsize(path)
    except FileNotFoundError:
        return b"", from_offset, "ok"
    if size <= from_offset:
        return b"", from_offset, "ok"
    with open(path, "rb") as f:
        f.seek(from_offset)
        chunk = f.read(max_bytes)
    usable = complete_extent(chunk, from_offset)
    data = chunk[:usable]
    next_offset = from_offset + usable
    if usable == len(chunk) and next_offset >= size:
        return data, next_offset, "ok"
    if from_offset + len(chunk) < size:
        return data, next_offset, "bounded"
    return data, next_offset, "waiting"


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _safe_log_name(name: str) -> str:
    """Log names cross the RPC boundary — refuse anything that is not a
    plain ``*.piolog`` basename (no traversal, no absolute paths)."""
    if (name != os.path.basename(name) or os.sep in name
            or not name.endswith(".piolog") or name.startswith(".")):
        raise ValueError(f"invalid log name {name!r}")
    return name


def list_logs(directory: str) -> dict[str, int]:
    """``{basename: size}`` for every eventlog file in ``directory``."""
    out: dict[str, int] = {}
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return out
    for name in names:
        if name.endswith(".piolog"):
            try:
                out[name] = os.path.getsize(os.path.join(directory, name))
            except OSError:  # pragma: no cover - raced a remove
                pass
    return out


# ---------------------------------------------------------------------------
# peer RPC (client half; the server half is storage_server's /repl routes)
# ---------------------------------------------------------------------------

def rpc_connection(url: str, timeout: float) -> http.client.HTTPConnection:
    """Connection for a peer URL, honoring the scheme: ``https`` peers get
    TLS (unverified context — like the remote client's unpinned mode, the
    shared ``X-PIO-Storage-Key`` is the authentication and TLS provides
    transport privacy) and the scheme's default port."""
    p = urllib.parse.urlsplit(url)
    host = p.hostname or "127.0.0.1"
    if p.scheme == "https":
        import ssl as _ssl

        ctx = _ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = _ssl.CERT_NONE
        return http.client.HTTPSConnection(
            host, p.port or 443, timeout=timeout, context=ctx)
    return http.client.HTTPConnection(host, p.port or 7072,
                                      timeout=timeout)


def default_rpc(url: str, verb: str, payload: dict,
                key: Optional[str] = None,
                timeout: float = 5.0) -> tuple[int, dict]:
    """POST ``<url>/repl/<verb>`` with a JSON body; returns
    ``(status, parsed_body)``. Connection-level failures raise ``OSError``
    — the caller decides whether that peer counts as unreachable."""
    conn = rpc_connection(url, timeout)
    try:
        headers = {"Content-Type": "application/json"}
        if key:
            headers["X-PIO-Storage-Key"] = key
        conn.request("POST", f"/repl/{verb}",
                     json.dumps(payload).encode(), headers)
        resp = conn.getresponse()
        data = resp.read()
        try:
            body = json.loads(data) if data else {}
        except ValueError:
            body = {"message": data[:256].decode(errors="replace")}
        return resp.status, body
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ReplicationConfig:
    log_dir: str                       # the eventlog directory replicated
    role: str = ROLE_PRIMARY
    peers: tuple[str, ...] = ()        # the OTHER replicas' base URLs
    sync: str = dataclasses.field(     # "async" (bounded lag) | "quorum"
        default_factory=lambda: os.environ.get("PIO_REPL_SYNC", "async"))
    key: Optional[str] = None          # shared X-PIO-Storage-Key
    chunk_bytes: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_REPL_CHUNK_BYTES", str(1 << 20))))
    # async mode's lag bound: when the best-caught-up follower is more
    # than this many bytes behind, new writes 503 (the event server
    # spills) instead of growing the sole-copy window without bound.
    # 0 disables enforcement (lag is still reported and probed red).
    max_lag_bytes: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_REPL_MAX_LAG_BYTES", str(64 << 20))))
    poll_interval: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_REPL_INTERVAL", "0.05")))
    quorum_timeout: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_REPL_QUORUM_TIMEOUT", "5.0")))
    # follower apply durability: fsync each applied chunk (the replicated
    # copy should survive ITS host's power cut too; PIO_REPL_FSYNC=0 for
    # bench/battery-backed hosts)
    fsync: bool = dataclasses.field(
        default_factory=lambda: os.environ.get("PIO_REPL_FSYNC", "1") != "0")
    rpc_timeout: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_REPL_RPC_TIMEOUT", "5.0")))


class _PeerState:
    """Primary-side view of one follower."""

    def __init__(self, url: str):
        self.url = url
        self.offsets: dict[str, int] = {}   # acked byte size per log
        self.patches = 0                    # follower's repair counter
        self.reachable = False
        self.last_error: Optional[str] = None
        self.diverged = False
        # a peer's existing content must be CRC-verified as a prefix of
        # ours ONCE before the first append (a rejoined deposed replica
        # can hold a same-length-or-shorter divergent history that size
        # comparison alone cannot detect); appends preserve the invariant
        # afterwards
        self.verified = False
        # offsets signature at the last failed verification: the
        # (expensive) prefix-CRC check only re-runs when the peer's
        # state actually changed (a scrub repaired it)
        self.diverged_sig: Optional[tuple] = None


class ReplicationManager:
    """State machine + transfer engine for one replica.

    Thread-safety: role/epoch mutations and follower file writes happen
    under ``self._lock``; each peer's ship path is serialized by a
    per-peer lock so the background loop and a quorum-acking write RPC
    never interleave chunks to the same follower.
    """

    def __init__(self, config: ReplicationConfig,
                 clock: Clock = SYSTEM_CLOCK,
                 rpc: Optional[Callable[..., tuple[int, dict]]] = None,
                 on_writable: Optional[Callable[[], None]] = None,
                 on_read_only: Optional[Callable[[], None]] = None):
        self.config = config
        self.clock = clock
        self._rpc = rpc or (lambda url, verb, payload: default_rpc(
            url, verb, payload, key=config.key,
            timeout=config.rpc_timeout))
        self._on_writable = on_writable or (lambda: None)
        self._on_read_only = on_read_only or (lambda: None)
        self._lock = threading.RLock()
        os.makedirs(config.log_dir, exist_ok=True)
        self.role = config.role
        self.epoch = 1
        self.fenced = False
        self.fenced_writes = 0      # health-surface twin of the counter
        self._load_state()
        self.peers: dict[str, _PeerState] = {
            url: _PeerState(url) for url in config.peers}
        self._peer_locks: dict[str, threading.Lock] = {
            url: threading.Lock() for url in config.peers}
        # follower side: append handles (flock-held, so the co-resident
        # events store serves reads through lock-free read-only views)
        self._writers: dict[str, Any] = {}
        # bumped by every repair (patch/remove_log) and reported in
        # /repl/state: an in-place scrub repair leaves file SIZES
        # unchanged, so the primary's prefix-verification cache keys on
        # this too or it would never re-check a repaired peer
        self.patch_count = 0
        self._last_contact: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _EPOCH_GAUGE.set(self.epoch)

    # -- persisted state (atomic-write discipline) ------------------------
    def _state_path(self) -> str:
        return os.path.join(self.config.log_dir, STATE_FILE)

    def _load_state(self) -> None:
        try:
            with open(self._state_path()) as f:
                st = json.load(f)
        except FileNotFoundError:
            self._save_state()  # fresh replica: initialize
            return
        except ValueError as e:
            # NEVER guess an epoch from a corrupt fencing token: a deposed
            # primary re-initialized to epoch 1 could accept writes during
            # a partition that fencing will later discard
            raise RuntimeError(
                f"corrupt replication state {self._state_path()}: {e} — "
                "refusing to start with a guessed epoch; restore the file "
                "or wipe the replica and scrub it back in "
                "(docs/replication.md)") from e
        self.epoch = int(st.get("epoch", self.epoch))
        self.role = st.get("role", self.role)
        self.fenced = bool(st.get("fenced", False))

    def _save_state(self) -> None:
        atomic_write_bytes(
            self._state_path(),
            json.dumps({"epoch": self.epoch, "role": self.role,
                        "fenced": self.fenced},
                       sort_keys=True).encode(),
            durable=True)
        _EPOCH_GAUGE.set(self.epoch)

    # -- role surface ------------------------------------------------------
    @property
    def is_primary(self) -> bool:
        return self.role == ROLE_PRIMARY and not self.fenced

    def can_accept_writes(self) -> bool:
        return self.is_primary

    def record_fenced_write(self) -> None:
        self.fenced_writes += 1
        FENCED_WRITES.inc()

    def _fence(self, remote_epoch: int) -> None:
        """A higher epoch exists: whatever we believed, we are not the
        primary of the current configuration. Persist the demotion BEFORE
        acknowledging anything else."""
        with self._lock:
            if remote_epoch <= self.epoch and self.role != ROLE_PRIMARY:
                return
            logger.warning(
                "replication: fenced by epoch %d (own epoch %d, role %s) — "
                "demoting to read-only follower", remote_epoch, self.epoch,
                self.role)
            was_primary = self.role == ROLE_PRIMARY
            self.epoch = max(self.epoch, remote_epoch)
            self.role = ROLE_FOLLOWER
            self.fenced = True
            self._save_state()
        if was_primary:
            self._on_read_only()

    def promote(self, peers: Optional[list[str]] = None) -> dict:
        """Bump the epoch and become the primary (the failover step).
        ``peers`` reconfigures the replica set — on failover the dead
        primary is removed until it is repaired (`pio-tpu store scrub`)
        and rejoined.

        Ordering matters: the events store is flipped WRITABLE (and the
        replication append handles released) BEFORE the role flip admits
        the first write. The reverse order has a window where a write
        passes the fence gate but lands on a still-read-only store — a
        500 the event server's drain would misread as a semantic
        rejection and dead-letter acked events on (found by the failover
        bench: exactly one lost ack per unlucky promote)."""
        with self._lock:
            self._close_writers()
            self._on_writable()
            self.epoch += 1
            self.role = ROLE_PRIMARY
            self.fenced = False
            self._save_state()
            if peers is not None:
                self.config = dataclasses.replace(
                    self.config, peers=tuple(peers))
                self.peers = {u: _PeerState(u) for u in self.config.peers}
                self._peer_locks = {
                    u: threading.Lock() for u in self.config.peers}
            logger.warning("replication: PROMOTED to primary at epoch %d "
                           "(peers: %s)", self.epoch,
                           list(self.config.peers) or "none")
        return {"epoch": self.epoch, "role": self.role}

    # -- follower file plumbing -------------------------------------------
    def _close_writers(self) -> None:
        for f in self._writers.values():
            try:
                f.close()
            except OSError:  # pragma: no cover
                pass
        self._writers.clear()

    def _writer(self, name: str):
        import fcntl

        f = self._writers.get(name)
        if f is None:
            # pio-lint: disable=R3 (follower replica log: complete-record CRC-verified appends shipped from the primary; divergent suffixes are truncated by scrub, and flock guards single-writer)
            f = open(os.path.join(self.config.log_dir, name), "ab")
            try:
                fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                f.close()
                raise
            self._writers[name] = f
        return f

    # -- RPC handler table (shared by the HTTP routes and in-process
    #    tests: one implementation of the protocol) -----------------------
    def handle(self, verb: str, payload: dict) -> tuple[int, dict]:
        try:
            fn = getattr(self, f"_handle_{verb}", None)
            if fn is None:
                return 404, {"message": f"unknown repl verb {verb!r}"}
            return fn(payload)
        except FencedError as e:
            _FENCED_APPENDS.inc()
            return 409, {"message": str(e), "fenced": self.epoch,
                         "epoch": self.epoch}
        except (ValueError, KeyError) as e:
            return 400, {"message": repr(e)}
        except OSError as e:
            return 500, {"message": f"replication I/O failed: {e}"}

    def _check_epoch(self, remote_epoch: int) -> None:
        """Adopt newer epochs (demoting ourselves if we were primary);
        fence senders with older ones."""
        with self._lock:
            if remote_epoch < self.epoch:
                raise FencedError(self.epoch)
            if remote_epoch > self.epoch:
                if self.role == ROLE_PRIMARY:
                    self._fence(remote_epoch)
                else:
                    self.epoch = remote_epoch
                    self._save_state()

    def _touch_contact(self) -> None:
        """Refresh the bounded-staleness freshness token. ONLY traffic
        from the current primary counts (its ship-loop state polls,
        heartbeats, and appends) — a scrub/status CLI poking /repl/state
        must not make a partitioned follower look freshly-synced."""
        with self._lock:
            self._last_contact = self.clock.monotonic()

    def _handle_state(self, a: dict) -> tuple[int, dict]:
        self._check_epoch(int(a.get("epoch", self.epoch)))
        if a.get("role") == ROLE_PRIMARY:
            self._touch_contact()  # the primary's ship loop polling us
        return 200, {"epoch": self.epoch, "role": self.role,
                     "fenced": self.fenced,
                     "patches": self.patch_count,
                     "logs": list_logs(self.config.log_dir)}

    def _handle_heartbeat(self, a: dict) -> tuple[int, dict]:
        remote = int(a.get("epoch", 0))
        with self._lock:
            if a.get("role") == ROLE_PRIMARY and remote >= self.epoch:
                self._last_contact = self.clock.monotonic()
            if remote > self.epoch:
                if self.role == ROLE_PRIMARY:
                    self._fence(remote)
                else:
                    self.epoch = remote
                    self._save_state()
        # NO FencedError here: the reply itself carries our epoch — a
        # stale primary learns it was deposed from the body and fences
        # itself (boot announce), whether or not it out-epochs us.
        return 200, {"epoch": self.epoch, "role": self.role}

    def _handle_append(self, a: dict) -> tuple[int, dict]:
        self._check_epoch(int(a["epoch"]))
        self._touch_contact()  # appends only come from the current primary
        with self._lock:
            if self.role == ROLE_PRIMARY:
                # same-epoch append onto a primary: two primaries in one
                # epoch is impossible by construction — refuse loudly
                raise FencedError(self.epoch)
            name = _safe_log_name(a["log"])
            offset = int(a["offset"])
            data = base64.b64decode(a["data"])
            if _crc(data) != int(a["crc"]):
                _CRC_FAILURES.inc()
                return 400, {"message": "chunk crc mismatch on apply"}
            path = os.path.join(self.config.log_dir, name)
            size = os.path.getsize(path) if os.path.exists(path) else 0
            if offset != size:
                return 200, {"ok": False, "size": size,
                             "epoch": self.epoch}
            f = self._writer(name)
            f.write(data)
            f.flush()
            if self.config.fsync:
                os.fsync(f.fileno())
            _APPLIED.inc(len(data))
            if self.fenced:
                # the current-epoch primary is streaming onto our log —
                # and it only ships to a peer whose content it verified as
                # a clean prefix (the diverged gate), so this node has
                # rejoined as a consistent follower: stop reporting
                # fenced/red (writes stay role-fenced regardless)
                self.fenced = False
                self._save_state()
                logger.warning("replication: fence cleared at epoch %d — "
                               "rejoined as a consistent follower",
                               self.epoch)
            return 200, {"ok": True, "size": offset + len(data),
                         "epoch": self.epoch}

    def _handle_promote(self, a: dict) -> tuple[int, dict]:
        return 200, self.promote(a.get("peers"))

    def _handle_remove_log(self, a: dict) -> tuple[int, dict]:
        """Apply a log removal (``events.remove`` is an admin op: byte
        shipping only moves record data, so deletions travel explicitly —
        a retained follower copy would wedge shipping as divergent the
        moment the app is re-initialized). Refused on a healthy primary:
        the authoritative copy is never deleted from the outside."""
        self._check_epoch(int(a.get("epoch", 0)))
        with self._lock:
            if self.is_primary:
                return 409, {"message": "refusing to remove a log on the "
                                        "primary (authoritative copy)"}
            name = _safe_log_name(a["log"])
            w = self._writers.pop(name, None)
            if w is not None:
                w.close()
            path = os.path.join(self.config.log_dir, name)
            existed = os.path.exists(path)
            if existed:
                os.remove(path)
            self.patch_count += 1
            self._invalidate_read_views()
            return 200, {"removed": existed, "epoch": self.epoch}

    def _handle_status(self, a: dict) -> tuple[int, dict]:
        return 200, self.health()

    # anti-entropy surface (driven by replication/scrub.py) ----------------
    def _handle_digest(self, a: dict) -> tuple[int, dict]:
        from incubator_predictionio_tpu.replication.scrub import file_digests

        name = _safe_log_name(a["log"])
        path = os.path.join(self.config.log_dir, name)
        segment_bytes = int(a.get("segment_bytes", 1 << 20))
        size, segments = file_digests(path, segment_bytes)
        return 200, {"size": size, "segments": segments,
                     "epoch": self.epoch}

    def _handle_fetch(self, a: dict) -> tuple[int, dict]:
        name = _safe_log_name(a["log"])
        path = os.path.join(self.config.log_dir, name)
        offset, length = int(a["offset"]), int(a["length"])
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(length)
        return 200, {"data": base64.b64encode(data).decode(),
                     "crc": _crc(data), "epoch": self.epoch}

    def _handle_patch(self, a: dict) -> tuple[int, dict]:
        """Repair write: overwrite an exact byte range (and/or truncate)
        with authoritative bytes fetched from the primary. Refused on a
        healthy primary — the authority is never patched."""
        with self._lock:
            if self.is_primary:
                return 409, {"message": "refusing to patch the primary "
                                        "(it is the authoritative copy)"}
            name = _safe_log_name(a["log"])
            path = os.path.join(self.config.log_dir, name)
            data = base64.b64decode(a.get("data", "")) if a.get("data") \
                else b""
            if data and _crc(data) != int(a["crc"]):
                _CRC_FAILURES.inc()
                return 400, {"message": "patch crc mismatch"}
            # the append handle (if any) holds the flock; reuse its fd via
            # a fresh r+b handle only after closing it — flock conflicts
            # between two open descriptions even in one process
            w = self._writers.pop(name, None)
            if w is not None:
                w.close()
            mode = "r+b" if os.path.exists(path) else "w+b"
            with open(path, mode) as f:
                if data:
                    f.seek(int(a["offset"]))
                    f.write(data)
                if a.get("truncate_to") is not None:
                    f.truncate(int(a["truncate_to"]))
                f.flush()
                os.fsync(f.fileno())
            self.patch_count += 1
            self._invalidate_read_views()
            return 200, {"size": os.path.getsize(path),
                         "epoch": self.epoch}

    #: follower read views may have parsed the pre-repair bytes; the
    #: storage server installs a callback that drops them (set in
    #: storage_server — EventLogEvents.reopen)
    invalidate_read_views: Optional[Callable[[], None]] = None

    def _invalidate_read_views(self) -> None:
        if self.invalidate_read_views is not None:
            try:
                self.invalidate_read_views()
            except Exception:  # noqa: BLE001 - repair must not die on this
                logger.exception("replication: read-view invalidation failed")

    # -- primary-side shipping --------------------------------------------
    def announce(self) -> None:
        """One heartbeat round to every peer (the boot fence check): a
        primary restarted with a stale epoch learns it was deposed HERE,
        before the first client write can reach it."""
        for url in list(self.config.peers):
            try:
                status, body = self._rpc(url, "heartbeat",
                                         {"epoch": self.epoch,
                                          "role": self.role})
            except OSError as e:
                logger.info("replication: peer %s unreachable at announce "
                            "(%s)", url, e)
                continue
            remote = int(body.get("epoch", 0)) if isinstance(body, dict) \
                else 0
            if status == 409 or remote > self.epoch:
                if self.role == ROLE_PRIMARY:
                    self._fence(max(remote, self.epoch))
                    return
                # a follower merely BEHIND on epoch (restarted across a
                # failover it missed) is not deposed — adopt the epoch
                # without raising the fenced alarm, exactly like the
                # heartbeat/append adoption path
                with self._lock:
                    if remote > self.epoch:
                        self.epoch = remote
                        self._save_state()

    def propagate_remove(self, name: str) -> None:
        """Best-effort fan-out of a log removal to every follower (the
        storage server calls this after ``events.remove`` succeeds
        locally). An unreachable follower keeps its copy and is
        reconciled by ``store scrub`` (which deletes follower-only
        logs)."""
        for url in list(self.config.peers):
            peer = self.peers[url]
            with self._peer_locks[url]:
                try:
                    st, body = self._rpc(url, "remove_log",
                                         {"epoch": self.epoch,
                                          "log": name})
                except OSError as e:
                    peer.last_error = repr(e)
                    logger.warning(
                        "replication: remove of %s not propagated to %s "
                        "(%s) — `pio-tpu store scrub` reconciles it",
                        name, url, e)
                    continue
                if st == 409:
                    self._fence(int(body.get("fenced",
                                             body.get("epoch", 0))))
                    return
                peer.offsets.pop(name, None)

    def ship_once(self, url: str) -> bool:
        """Ship every log's outstanding complete-record bytes to one peer.
        Returns True when the peer ended the round fully caught up.
        Serialized per peer; safe to call from the background loop and
        from a quorum-acking write RPC concurrently."""
        peer = self.peers[url]
        with self._peer_locks[url]:
            return self._ship_once_locked(peer)

    def _ship_once_locked(self, peer: _PeerState) -> bool:
        if not self.is_primary:
            return False
        try:
            status, body = self._rpc(peer.url, "state",
                                     {"epoch": self.epoch,
                                      "role": self.role})
        except OSError as e:
            peer.reachable = False
            peer.last_error = repr(e)
            return False
        if status == 409:
            self._fence(int(body.get("fenced", body.get("epoch", 0))))
            return False
        if status != 200:
            peer.reachable = False
            peer.last_error = f"state: {status} {body.get('message', '')}"
            return False
        remote_epoch = int(body.get("epoch", 0))
        if remote_epoch > self.epoch:
            self._fence(remote_epoch)
            return False
        peer.reachable = True
        peer.last_error = None
        peer.offsets = {k: int(v) for k, v in body.get("logs", {}).items()}
        peer.patches = int(body.get("patches", 0))
        if not self._ensure_prefix_verified(peer):
            # NOTHING ships to an unverified/diverged peer: appending our
            # bytes after a divergent history would interleave two
            # histories into one log (per-chunk CRCs cannot catch it).
            # `store scrub` repairs it; the verification resumes shipping
            # once the peer's content is a CRC-identical prefix of ours.
            return False
        caught_up = True
        for name, local_size in list_logs(self.config.log_dir).items():
            offset = peer.offsets.get(name, 0)
            if offset > local_size:
                if not peer.diverged:
                    logger.error(
                        "replication: follower %s is AHEAD of the primary "
                        "on %s (%d > %d) — divergent history; run "
                        "`pio-tpu store scrub`", peer.url, name, offset,
                        local_size)
                    _DIVERGED.inc()
                peer.diverged = True
                peer.verified = False
                peer.diverged_sig = None
                return False
            max_bytes = self.config.chunk_bytes
            while offset < local_size:
                data, next_offset, status_ = tail_extent(
                    os.path.join(self.config.log_dir, name), offset,
                    max_bytes)
                if not data:
                    if status_ == "bounded":
                        # one record larger than the chunk bound: grow the
                        # read until it fits (the bytes exist on disk) —
                        # otherwise replication would stall forever on it
                        max_bytes *= 4
                        continue
                    break  # waiting on the writer's partial tail
                max_bytes = self.config.chunk_bytes
                try:
                    st, resp = self._rpc(peer.url, "append", {
                        "epoch": self.epoch, "log": name,
                        "offset": offset, "crc": _crc(data),
                        "data": base64.b64encode(data).decode()})
                except OSError as e:
                    peer.reachable = False
                    peer.last_error = repr(e)
                    return False
                if st == 409:
                    self._fence(int(resp.get("fenced",
                                             resp.get("epoch", 0))))
                    return False
                if st != 200 or not resp.get("ok", False):
                    if st == 200 and "size" in resp:
                        # offset mismatch: adopt the follower's position
                        newsize = int(resp["size"])
                        if newsize > offset:
                            peer.offsets[name] = newsize
                            offset = newsize
                            continue
                    peer.last_error = f"append: {st} {resp}"
                    caught_up = False
                    break
                _SHIPPED.inc(len(data))
                offset = next_offset
                peer.offsets[name] = offset
            if peer.offsets.get(name, 0) < local_size:
                caught_up = False
        return caught_up

    def _ensure_prefix_verified(self, peer: _PeerState) -> bool:
        """Gate every ship round: a peer's existing bytes must be a
        CRC-identical PREFIX of ours before anything is appended. Runs
        the O(size) comparison once per peer (and again only when a
        previously-failed peer's offsets change — i.e. a `store scrub`
        repaired it); a verified peer stays verified because appends at
        matching offsets preserve the invariant."""
        if peer.verified and not peer.diverged:
            return True
        sig = (tuple(sorted(peer.offsets.items())),
               getattr(peer, "patches", 0))
        if peer.diverged and peer.diverged_sig == sig:
            return False  # unchanged since the last failed check
        if self._prefix_matches(peer):
            if peer.diverged:
                logger.warning(
                    "replication: peer %s verified as a clean prefix "
                    "again — resuming shipping (divergence repaired)",
                    peer.url)
            peer.verified = True
            peer.diverged = False
            peer.diverged_sig = None
            return True
        if not peer.diverged:
            logger.error(
                "replication: follower %s holds a DIVERGENT history — "
                "nothing ships to it; run `pio-tpu store scrub`",
                peer.url)
            _DIVERGED.inc()
        peer.diverged = True
        peer.diverged_sig = sig
        return False

    #: prefix-verification window — bounded memory per comparison step
    #: whatever the log size (multi-GB logs must not be read in one gulp
    #: on either replica)
    VERIFY_WINDOW = 1 << 20

    def _prefix_matches(self, peer: _PeerState) -> bool:
        """True when every log the peer holds is a CRC-identical prefix
        of our copy (empty logs trivially match). Windowed on both sides:
        the peer answers its standard windowed digest and we stream our
        prefix through matching windows — O(window) memory, O(size) I/O."""
        for name, psize in peer.offsets.items():
            try:
                _safe_log_name(name)
            except ValueError:
                return False
            path = os.path.join(self.config.log_dir, name)
            lsize = os.path.getsize(path) if os.path.exists(path) else 0
            if psize > lsize:
                return False
            if psize == 0:
                continue
            try:
                st, body = self._rpc(
                    peer.url, "digest",
                    {"log": name, "segment_bytes": self.VERIFY_WINDOW})
            except OSError:
                return False
            if st != 200:
                return False
            remote = [tuple(seg) for seg in (body.get("segments") or [])]
            local: list[tuple[int, int, int]] = []
            with open(path, "rb") as f:
                off = 0
                while off < psize:
                    chunk = f.read(min(self.VERIFY_WINDOW, psize - off))
                    if not chunk:
                        break
                    local.append((off, len(chunk), _crc(chunk)))
                    off += len(chunk)
            if off != psize or remote != local:
                return False
        return True

    # -- lag / quorum ------------------------------------------------------
    def _lag_per_peer(self) -> dict[str, int]:
        local = list_logs(self.config.log_dir)
        out: dict[str, int] = {}
        for url, peer in self.peers.items():
            if not peer.verified or peer.diverged:
                # an unverified/diverged peer holds NOTHING durable of
                # our history, whatever its byte sizes claim — its lag
                # is everything
                out[url] = sum(local.values())
                continue
            lag = 0
            for name, size in local.items():
                lag += max(0, size - peer.offsets.get(name, 0))
            out[url] = lag
        return out

    def min_lag_bytes(self) -> int:
        """Bytes that exist on NO follower yet — the sole-copy window the
        async lag bound caps. 0 when there are no peers (a deliberately
        unreplicated deployment bounds nothing)."""
        lags = self._lag_per_peer()
        lag = min(lags.values()) if lags else 0
        _LAG_GAUGE.set(lag)
        return lag

    def check_async_bound(self) -> None:
        """Async mode's write-path gate: refuse (→ 503 → client spill)
        when the best follower is beyond the lag bound. Pull-forward is
        attempted first so a healthy-but-momentarily-behind follower
        doesn't bounce writes."""
        if self.config.sync == "quorum" or not self.config.peers \
                or self.config.max_lag_bytes <= 0:
            return
        if self.min_lag_bytes() <= self.config.max_lag_bytes:
            return
        for url in self.config.peers:
            self.ship_once(url)
        lag = self.min_lag_bytes()
        if lag > self.config.max_lag_bytes:
            raise ReplicationUnavailable(
                f"replication lag {lag}B exceeds the "
                f"{self.config.max_lag_bytes}B bound and no follower "
                "could be caught up")

    def sync_quorum(self) -> None:
        """Quorum-ack write path: ship until a majority of the replica
        set (self included) holds every byte written so far, or raise
        :class:`ReplicationUnavailable` at the timeout. With no peers the
        quorum is this process alone (the post-failover solo primary)."""
        target = list_logs(self.config.log_dir)
        needed = (len(self.config.peers) + 1) // 2
        if needed == 0:
            return
        deadline = self.clock.monotonic() + self.config.quorum_timeout

        def acked(peer: _PeerState) -> bool:
            # size comparison only counts for a peer whose content is a
            # VERIFIED prefix of ours: a diverged follower's equal-sized
            # log holds none of these bytes, whatever its size says
            return (peer.verified and not peer.diverged
                    and all(peer.offsets.get(name, 0) >= size
                            for name, size in target.items()))

        while True:
            count = 0
            for url in self.config.peers:
                peer = self.peers[url]
                if not acked(peer):
                    self.ship_once(url)
                if acked(peer):
                    count += 1
                if count >= needed:
                    return
            if self.clock.monotonic() >= deadline:
                _QUORUM_FAILURES.inc()
                raise ReplicationUnavailable(
                    f"quorum not reached: {count}/{needed} follower "
                    f"ack(s) within {self.config.quorum_timeout}s")
            self.clock.sleep(min(0.05, self.config.poll_interval))

    # -- background loop ---------------------------------------------------
    def start(self) -> None:
        """Announce once (the boot fence check), then run the async ship
        loop on a daemon thread (primary with peers only; followers are
        passive)."""
        self.announce()
        if self._thread is None and self.config.peers:
            self._thread = threading.Thread(
                target=self._run, name="pio-repl-ship", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            progressed = False
            if self.is_primary:
                for url in list(self.config.peers):
                    try:
                        if self.ship_once(url):
                            progressed = True
                    except Exception:  # noqa: BLE001 - loop must survive
                        logger.exception("replication: ship to %s failed",
                                         url)
                self.min_lag_bytes()  # keep the gauge fresh
            self._stop.wait(self.config.poll_interval
                            if progressed else 4 * self.config.poll_interval)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            self._close_writers()

    # -- health surface ----------------------------------------------------
    def contact_age(self) -> Optional[float]:
        if self._last_contact is None:
            return None
        return max(0.0, self.clock.monotonic() - self._last_contact)

    def health(self) -> dict:
        out: dict[str, Any] = {
            "role": self.role, "epoch": self.epoch, "fenced": self.fenced,
            "sync": self.config.sync,
            "fencedWrites": self.fenced_writes,
            "maxLagBytes": self.config.max_lag_bytes,
        }
        if self.role == ROLE_PRIMARY:
            lags = self._lag_per_peer()
            out["peers"] = {
                url: {"lagBytes": lags.get(url, 0),
                      "reachable": peer.reachable,
                      "diverged": peer.diverged,
                      "verified": peer.verified,
                      "lastError": peer.last_error}
                for url, peer in self.peers.items()}
            lag = min(lags.values()) if lags else 0
            out["lagBytes"] = lag
            out["lagExceeded"] = bool(
                self.config.peers and self.config.max_lag_bytes > 0
                and lag > self.config.max_lag_bytes)
        else:
            age = self.contact_age()
            out["contactAgeSeconds"] = (round(age, 3)
                                        if age is not None else None)
        return out
