"""Storage high availability: replicated eventlog with epoch-fenced
failover and anti-entropy repair (docs/replication.md).

The append-only eventlog (data/storage/eventlog_backend.py) is the
replicated substrate: byte offsets ARE sequence numbers (the same trick
``streaming/feed.py`` uses), so a primary storage server ships raw
complete-record byte ranges to followers and the files stay identical
bit for bit — every consumer that addresses the log by offset (the
streaming cursor, the scrubber's range digests) survives a failover
unchanged.

- :mod:`manager` — :class:`ReplicationManager`: primary→follower frame
  shipping with CRC verification on apply, monotonic persisted epochs,
  promote/demote/fence state machine, async bounded-lag and quorum-ack
  modes.
- :mod:`scrub` — anti-entropy: per-segment CRC range digests exchanged
  between replicas, divergence/bitrot detection, repair by re-fetching
  the authoritative range (``pio-tpu store scrub``).
"""

from incubator_predictionio_tpu.replication.manager import (  # noqa: F401
    FencedError,
    ReplicationConfig,
    ReplicationManager,
    ReplicationUnavailable,
    complete_extent,
    tail_extent,
)
from incubator_predictionio_tpu.replication.scrub import (  # noqa: F401
    file_digests,
    scrub_follower,
)
