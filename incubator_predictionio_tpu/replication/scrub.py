"""Anti-entropy scrubber: detect and repair replica divergence/bitrot.

Replication keeps follower eventlog files byte-identical to the primary
in the steady state, but three things can still rot a copy: silent disk
corruption (a flipped bit no append ever re-reads), a divergent suffix
left on a deposed primary (async-mode writes that never shipped before
the failover), and operator surgery. The scrubber closes all three:

1. exchange **per-segment CRC32 range digests** between the authoritative
   replica (the current primary) and a follower — fixed byte windows, so
   a digest is O(size) I/O and O(size/segment) wire bytes;
2. any mismatched window, and any length difference, is **repaired by
   re-fetching the authoritative byte range** and patching it into the
   follower (truncating a divergent over-long suffix);
3. the digests are re-exchanged and must come back **bit-identical** —
   the repair verifies itself.

Driven by ``pio-tpu store scrub <primary-url> <follower-url...>``; the
RPC verbs (``digest``/``fetch``/``patch``) live on the storage server's
``/repl/`` surface (replication/manager.py), and a healthy primary
refuses ``patch`` so the authority can never be "repaired" backwards.
"""

from __future__ import annotations

import base64
import logging
import os
import zlib
from typing import Callable

from incubator_predictionio_tpu.obs.metrics import REGISTRY

logger = logging.getLogger(__name__)

_CHECKED = REGISTRY.counter(
    "pio_scrub_segments_checked_total",
    "Digest windows compared between a primary and a follower")
_DIVERGENT = REGISTRY.counter(
    "pio_scrub_divergent_segments_total",
    "Digest windows that did not match (bitrot or divergent history)")
_REPAIRED = REGISTRY.counter(
    "pio_scrub_repaired_bytes_total",
    "Bytes rewritten on followers from the authoritative primary range")


def file_digests(path: str, segment_bytes: int = 1 << 20,
                 ) -> tuple[int, list[list[int]]]:
    """``(size, [[offset, length, crc32], ...])`` over fixed byte windows
    of ``path`` (missing file → size 0, no segments). Runs on both sides
    of the exchange — ONE implementation, so the two replicas cannot
    disagree about windowing."""
    segment_bytes = max(4096, segment_bytes)
    try:
        size = os.path.getsize(path)
    except FileNotFoundError:
        return 0, []
    segments: list[list[int]] = []
    with open(path, "rb") as f:
        offset = 0
        while offset < size:
            data = f.read(segment_bytes)
            if not data:
                break
            segments.append(
                [offset, len(data), zlib.crc32(data) & 0xFFFFFFFF])
            offset += len(data)
    return size, segments


#: RPC callable shape: (base_url, verb, payload) -> (status, body).
RpcFn = Callable[[str, str, dict], tuple[int, dict]]


class ScrubError(Exception):
    """A replica answered the scrub RPC surface with an error."""


def _call(rpc: RpcFn, url: str, verb: str, payload: dict) -> dict:
    try:
        status, body = rpc(url, verb, payload)
    except OSError as e:
        raise ScrubError(f"{url} unreachable for {verb}: {e}") from e
    if status != 200:
        raise ScrubError(
            f"{url} {verb} failed: {status} {body.get('message', body)}")
    return body


def scrub_follower(primary_url: str, follower_url: str, rpc: RpcFn,
                   segment_bytes: int = 1 << 20,
                   repair: bool = True) -> dict:
    """Compare (and by default repair) one follower against the primary.

    Returns a report::

        {"logs": {name: {"segmentsChecked", "divergent": [offsets...],
                         "repairedBytes", "sizePrimary", "sizeFollower",
                         "verified": bool}},
         "divergentSegments": N, "repairedBytes": N, "clean": bool}

    ``clean`` means every log's post-repair digests were bit-identical
    (or nothing diverged in the first place). With ``repair=False`` the
    report only detects — ``clean`` is False when anything differs.
    """
    state = _call(rpc, primary_url, "state", {})
    logs = sorted(state.get("logs", {}))
    f_state = _call(rpc, follower_url, "state", {})
    report: dict = {"logs": {}, "divergentSegments": 0,
                    "repairedBytes": 0, "removedLogs": [], "clean": True}
    # follower-only logs (the primary removed an app the follower never
    # heard about): byte shipping can't delete them, so the scrub does —
    # a retained copy both serves deleted events forever and wedges
    # shipping as divergent if the app is ever re-initialized
    for name in sorted(set(f_state.get("logs", {})) - set(logs)):
        if repair:
            _call(rpc, follower_url, "remove_log",
                  {"log": name, "epoch": f_state.get("epoch", 0)})
            report["removedLogs"].append(name)
        else:
            report["clean"] = False
            report["logs"][name] = {
                "sizePrimary": 0,
                "sizeFollower": f_state["logs"][name],
                "segmentsChecked": 0, "divergent": [],
                "repairedBytes": 0, "verified": False}
    for name in logs:
        row = _scrub_log(primary_url, follower_url, rpc, name,
                         segment_bytes, repair)
        report["logs"][name] = row
        report["divergentSegments"] += len(row["divergent"])
        report["repairedBytes"] += row["repairedBytes"]
        if not row["verified"]:
            report["clean"] = False
    return report


def _diverging_ranges(p_segs: list[list[int]],
                      f_segs: list[list[int]],
                      ) -> list[tuple[int, int]]:
    """Byte ranges of the primary that must be re-fetched: windows whose
    CRC differs, plus any primary suffix the follower lacks."""
    f_by_off = {off: (length, crc) for off, length, crc in f_segs}
    out: list[tuple[int, int]] = []
    for off, length, crc in p_segs:
        _CHECKED.inc()
        got = f_by_off.get(off)
        if got is None or got != (length, crc):
            out.append((off, length))
    return out


def _scrub_log(primary_url: str, follower_url: str, rpc: RpcFn, name: str,
               segment_bytes: int, repair: bool) -> dict:
    p = _call(rpc, primary_url, "digest",
              {"log": name, "segment_bytes": segment_bytes})
    f = _call(rpc, follower_url, "digest",
              {"log": name, "segment_bytes": segment_bytes})
    ranges = _diverging_ranges(p["segments"], f["segments"])
    row = {"sizePrimary": p["size"], "sizeFollower": f["size"],
           "segmentsChecked": len(p["segments"]),
           "divergent": [off for off, _ in ranges],
           "repairedBytes": 0,
           "verified": not ranges and p["size"] == f["size"]}
    if ranges:
        _DIVERGENT.inc(len(ranges))
        logger.warning("scrub %s: %d divergent window(s) on %s "
                       "(follower size %d vs primary %d)", name,
                       len(ranges), follower_url, f["size"], p["size"])
    if row["verified"] or not repair:
        return row
    for off, length in ranges:
        chunk = _call(rpc, primary_url, "fetch",
                      {"log": name, "offset": off, "length": length})
        _call(rpc, follower_url, "patch", {
            "log": name, "offset": off,
            "data": chunk["data"], "crc": chunk["crc"]})
        n = len(base64.b64decode(chunk["data"]))
        row["repairedBytes"] += n
        _REPAIRED.inc(n)
    if f["size"] > p["size"]:
        # divergent over-long suffix (async writes a deposed primary never
        # shipped): the authoritative history wins, the extras go
        _call(rpc, follower_url, "patch",
              {"log": name, "truncate_to": p["size"], "offset": p["size"],
               "crc": 0})
        row["repairedBytes"] += f["size"] - p["size"]
    # verify: the repair must leave the copies bit-identical
    p2 = _call(rpc, primary_url, "digest",
               {"log": name, "segment_bytes": segment_bytes})
    f2 = _call(rpc, follower_url, "digest",
               {"log": name, "segment_bytes": segment_bytes})
    row["verified"] = (p2["size"] == f2["size"]
                       and p2["segments"] == f2["segments"])
    if not row["verified"]:  # pragma: no cover - a live writer moved it
        logger.warning("scrub %s: digests still differ after repair "
                       "(live writer racing the scrub? re-run)", name)
    return row
