"""incubator_predictionio_tpu — a TPU-native machine-learning server framework.

A fresh implementation of Apache PredictionIO's contracts (DASE engines, event
server, storage registry, CLI) with the Spark-on-JVM execution layer replaced by
an idiomatic JAX/XLA stack: training runs as jit/pjit programs sharded over the
TPU ICI mesh, serving calls into a resident TPU inference shard.

Reference structural analysis: SURVEY.md at the repo root.
"""

__version__ = "0.1.0"

# Short convenience alias used throughout docs/tests:  import incubator_predictionio_tpu as piotpu
