"""TTL + single-flight cache for serving-time live event-store reads.

The reference reads the ``unavailableItems`` constraint from the event store
on EVERY query (ECommAlgorithm.scala:150-180) — correct, but it turns the
serving hot path into a storage benchmark. This cache bounds that to one
read per TTL window per process, with single-flight coalescing so a thundering
herd of coalesced queries behind an expired entry triggers exactly one
storage read (followers block on the leader's result instead of stampeding
the backend).

Determinism contract (the resilience-layer pattern, resilience/clock.py):
the cache takes an injectable :class:`Clock`, so tests script expiry by
advancing a ``FakeClock`` — zero wall sleeps.

Staleness is explicit and bounded: a constraint write becomes visible at
most ``ttl`` seconds later. ``PIO_SERVING_CONSTRAINT_TTL_MS=0`` disables
caching entirely and restores the reference's read-per-query semantics
(every ``get`` invokes the loader and counts a miss). See docs/serving.md.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, TypeVar

from incubator_predictionio_tpu.obs.metrics import REGISTRY
from incubator_predictionio_tpu.resilience.clock import SYSTEM_CLOCK, Clock

T = TypeVar("T")

_HITS = REGISTRY.counter(
    "pio_serving_store_read_cache_hits_total",
    "Serving-time store reads answered from the TTL constraint cache "
    "(single-flight followers count as hits — they performed no read)")
_MISSES = REGISTRY.counter(
    "pio_serving_store_read_cache_misses_total",
    "Serving-time store reads that went to the backend (TTL expired, first "
    "read, or caching disabled via PIO_SERVING_CONSTRAINT_TTL_MS=0)")

#: Default constraint-read TTL when ``PIO_SERVING_CONSTRAINT_TTL_MS`` is
#: unset: 1s bounds constraint staleness to human-imperceptible while
#: capping the read rate at 1/s/process regardless of query load.
DEFAULT_CONSTRAINT_TTL_MS = 1000.0


def constraint_ttl_sec() -> float:
    """The serving constraint-read TTL in seconds, from
    ``PIO_SERVING_CONSTRAINT_TTL_MS`` (``0`` → read per query)."""
    raw = os.environ.get("PIO_SERVING_CONSTRAINT_TTL_MS")
    try:
        ms = float(raw) if raw is not None else DEFAULT_CONSTRAINT_TTL_MS
    except ValueError:
        ms = DEFAULT_CONSTRAINT_TTL_MS
    return max(0.0, ms) / 1000.0


class _Load:
    """One in-flight loader call: followers wait on the event, the leader
    resolves with a value or an exception. ``started`` lets the cache
    detect an abandoned (hung) leader and elect a new one."""

    __slots__ = ("_event", "value", "error", "started")

    def __init__(self, started: float = 0.0) -> None:
        self._event = threading.Event()
        self.value: Any = None
        self.error: BaseException | None = None
        self.started = started

    def resolve(self, value: Any) -> None:
        self.value = value
        self._event.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self._event.set()

    def wait(self, timeout: float | None = None) -> Any:
        """Returns the leader's result; raises TimeoutError if it does not
        arrive within ``timeout`` seconds."""
        if not self._event.wait(timeout):
            raise TimeoutError("single-flight leader did not resolve in time")
        if self.error is not None:
            raise self.error
        return self.value


class TTLCache:
    """Keyed TTL cache with single-flight loading.

    ``get(key, loader)`` returns the cached value while it is fresh; on
    expiry exactly one caller (the leader) runs ``loader``. Concurrent
    callers serve the STALE value while the refresh is in flight
    (stale-while-revalidate — nobody queues behind a slow backend read);
    only a cold key with no previous value blocks followers on the
    leader's result. A failed load caches nothing — the stale value
    survives and the next caller becomes the new leader.

    ``ttl_sec <= 0`` disables caching: every ``get`` calls ``loader``
    directly (reference read-per-query semantics), counted as misses so the
    /metrics counters still describe the true read rate.
    """

    def __init__(self, ttl_sec: float, clock: Clock = SYSTEM_CLOCK):
        self.ttl_sec = ttl_sec
        self.clock = clock
        # a refresh leader whose read has been in flight this long is
        # presumed hung (black-holed connection with no deadline scope):
        # the next caller elects itself the new leader, so staleness can
        # never freeze at one snapshot for the process lifetime
        self.leader_timeout_sec = max(5.0, ttl_sec)
        self._lock = threading.Lock()
        self._entries: dict[Any, tuple[Any, float]] = {}  # key -> (value, expires)
        self._loads: dict[Any, _Load] = {}

    def get(self, key: Any, loader: Callable[[], T]) -> T:
        if self.ttl_sec <= 0:
            _MISSES.inc()
            return loader()
        now = self.clock.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry[1] > now:
                _HITS.inc()
                return entry[0]
            load = self._loads.get(key)
            if load is not None and \
                    now - load.started > self.leader_timeout_sec:
                load = None  # abandoned leader — take over the slot
            if load is None:
                load = self._loads[key] = _Load(started=now)
                leader = True
            else:
                leader = False
                if entry is not None:
                    # stale-while-revalidate: a refresh is already in
                    # flight — serve the expired value instead of queueing
                    # behind it (a slow/faulted leader read must not
                    # head-of-line-block every concurrent query past its
                    # own deadline; the leader runs under ITS caller's
                    # deadline scope and staleness is bounded by that)
                    _HITS.inc()
                    return entry[0]
        if not leader:
            # cold key (no previous value): join the in-flight read — but
            # only for as long as THIS caller's ambient deadline allows. A
            # slow leader must not hold a tighter-budgeted follower past
            # its own budget; on timeout the follower falls through to its
            # own read, which fails fast under its own deadline_scope.
            from incubator_predictionio_tpu.resilience.policy import (
                current_deadline,
            )

            ambient = current_deadline()
            budget = ambient.remaining() if ambient is not None else None
            if budget is None:
                # no ambient deadline: still never park forever on a hung
                # leader's Event (takeover replaces the slot for LATER
                # callers only — already-parked waiters must time out on
                # their own and fall through to a direct read)
                budget = self.leader_timeout_sec
            try:
                value = load.wait(budget)
            except TimeoutError:
                _MISSES.inc()
                return loader()
            _HITS.inc()  # no storage call happened on this caller's behalf
            return value
        _MISSES.inc()
        try:
            value = loader()
        except BaseException as e:
            with self._lock:
                # identity check: a taken-over slot belongs to the NEW
                # leader — an old hung leader waking up must not evict it
                if self._loads.get(key) is load:
                    self._loads.pop(key)
            load.fail(e)
            raise
        with self._lock:
            # expiry is measured from load COMPLETION — a slow storage read
            # must not eat into the freshness window
            self._entries[key] = (value, self.clock.monotonic() + self.ttl_sec)
            if self._loads.get(key) is load:
                self._loads.pop(key)
        load.resolve(value)
        return value

    def invalidate(self, key: Any = None) -> None:
        """Drop one key (or everything when ``key`` is None)."""
        with self._lock:
            if key is None:
                self._entries.clear()
            else:
                self._entries.pop(key, None)
