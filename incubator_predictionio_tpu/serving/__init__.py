"""Vectorized batched serving for the rule-filtered templates.

The reference evaluates every business rule (category filter, white/black
lists, live unavailable-items constraint, unseen-only) per query with
per-item Scala closures (ECommAlgorithm.scala isCandidateItem); the seed
port kept that shape as per-item Python loops, so a coalesced micro-batch
of B queries still ran O(B × catalog) interpreter work plus O(B) live
event-store reads. This package is the batched replacement:

- :mod:`masks <incubator_predictionio_tpu.serving.masks>` — compile the
  catalog's category metadata once at ``prepare_for_serving`` into a
  :class:`~incubator_predictionio_tpu.serving.masks.CategoryIndex`
  (category → member-row arrays), then assemble every query's filter as
  vectorized index scatters into a ``[B, N]`` additive -inf mask.
- :mod:`cache <incubator_predictionio_tpu.serving.cache>` — a TTL +
  single-flight cache for serving-time live store reads (the per-query
  ``unavailableItems`` constraint read), clock-injectable so tests script
  expiry deterministically. ``PIO_SERVING_CONSTRAINT_TTL_MS=0`` restores
  the reference's read-per-query semantics.

- :mod:`ann <incubator_predictionio_tpu.serving.ann>` — two-stage
  retrieval for big catalogs: a trained IVF partition over the item
  embeddings prunes each query to the top-``nprobe`` partitions' members,
  then the exact scoring math reranks only the gathered candidates
  (``PIO_RETRIEVAL_*`` knobs; the full-catalog path stays the recall
  oracle).

See docs/serving.md ("Batched serving & mask compilation",
"Two-stage retrieval").
"""

from incubator_predictionio_tpu.serving.ann import IVFIndex, build_ivf
from incubator_predictionio_tpu.serving.cache import TTLCache, constraint_ttl_sec
from incubator_predictionio_tpu.serving.masks import (
    CategoryIndex,
    HasCategoryIndex,
    ban_rows,
    whitelist_vec,
)
from incubator_predictionio_tpu.serving.topk import grouped_topk, topk_row

__all__ = [
    "CategoryIndex",
    "HasCategoryIndex",
    "IVFIndex",
    "TTLCache",
    "ban_rows",
    "build_ivf",
    "constraint_ttl_sec",
    "grouped_topk",
    "topk_row",
    "whitelist_vec",
]
