"""Two-stage retrieval: trained IVF coarse pruning + exact candidate rerank.

Exact serving scores every query against the whole catalog — an O(catalog)
``[B, N]`` matmul per batch that stops being "as fast as the hardware
allows" at the 10M-item shapes ALX (arxiv 2112.02194) targets. This module
is the coarse-to-fine answer:

- **Build** (deploy time, :func:`build_ivf`): k-means over the item
  embeddings *augmented with the item bias as an extra coordinate* (the
  query side implicitly carries a 1.0 there, so a centroid's coarse score
  ``q·c_emb + c_bias`` is an unbiased estimate of its members' exact
  scores — popular-but-orthogonal items don't fall out of the probe set).
  Members are laid out contiguously per partition (CSR: ``member_ids`` +
  ``offsets``), so gathering a partition's candidates is a slice, never a
  fancy-index gather.
- **Coarse stage**: score the ``[C]`` centroids per query and keep the
  top-``nprobe`` partitions — pruning the catalog to a few percent.
- **Rerank stage**: the surviving candidates are scored with the *exact*
  serving math (fp32 rows + bias, optionally int8 rows through the same
  symmetric row quantization the Pallas kernel uses —
  :func:`~incubator_predictionio_tpu.ops.retrieval.quantize_rows`), then
  the shared serial-parity top-k chain picks the result.

Rule filters (``exclude`` / ``row_mask``) are applied **in candidate-index
space after the gather**, as -inf on the exact rerank scores — a filtered
candidate can therefore never displace an unfiltered one, exactly like the
full-catalog path. The exact path itself stays untouched as the recall
oracle; tests assert a recall@k floor against it
(tests/test_two_stage_retrieval.py).

Mode selection is env-driven (``PIO_RETRIEVAL_MODE`` = ``exact`` |
``two_stage`` | ``auto``; auto keeps catalogs under
``PIO_RETRIEVAL_MIN_ITEMS`` on the exact path so small templates keep
bitwise parity). See docs/serving.md ("Two-stage retrieval").
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Optional

import numpy as np

from incubator_predictionio_tpu.obs.metrics import REGISTRY
from incubator_predictionio_tpu.serving.topk import topk_row

#: Rows per chunk for the full-catalog assignment pass at build time — keeps
#: the [chunk, C] distance buffer bounded regardless of catalog size.
ASSIGN_CHUNK = 131_072

COARSE_SEC = REGISTRY.histogram(
    "pio_retrieval_coarse_seconds",
    "Two-stage retrieval: centroid scoring + partition selection per batch")
RERANK_SEC = REGISTRY.histogram(
    "pio_retrieval_rerank_seconds",
    "Two-stage retrieval: exact candidate rerank per batch")
CANDIDATES = REGISTRY.histogram(
    "pio_retrieval_candidates",
    "Candidates gathered per query by the coarse stage",
    buckets=(64, 256, 1024, 4096, 16384, 65536, 262144, 1048576))
TWO_STAGE_BATCHES = REGISTRY.counter(
    "pio_retrieval_two_stage_total",
    "Batches served through the two-stage (pruned) path")
FALLBACKS = REGISTRY.counter(
    "pio_retrieval_fallback_total",
    "Two-stage-eligible batches that fell back to the exact path "
    "(probed partitions held fewer raw — or post-rule-filter finite — "
    "candidates than the requested top-k)")


# -- env knobs ---------------------------------------------------------------

def retrieval_mode() -> str:
    """``PIO_RETRIEVAL_MODE``: ``exact`` | ``two_stage`` | ``auto``."""
    mode = os.environ.get("PIO_RETRIEVAL_MODE", "auto").strip().lower()
    if mode not in ("exact", "two_stage", "auto"):
        raise ValueError(
            f"PIO_RETRIEVAL_MODE={mode!r} (want exact|two_stage|auto)")
    return mode


def min_items() -> int:
    return int(os.environ.get("PIO_RETRIEVAL_MIN_ITEMS", "100000"))


def two_stage_enabled(n_items: int) -> bool:
    """Whether a catalog of ``n_items`` should serve two-stage right now."""
    mode = retrieval_mode()
    if mode == "two_stage":
        return True
    return mode == "auto" and n_items >= min_items()


def default_partitions(n_items: int) -> int:
    """√N partitions, clamped — the classic IVF sizing."""
    if n_items <= 0:
        return 1
    c = int(round(np.sqrt(n_items)))
    return max(1, min(c, max(1, n_items // 4), 65_536))


def resolved_partitions(n_items: int) -> int:
    c = int(os.environ.get("PIO_RETRIEVAL_PARTITIONS", "0"))
    return c if c > 0 else default_partitions(n_items)


def resolved_nprobe(n_partitions: int) -> int:
    """√C probes by default, clamped to the partition count."""
    p = int(os.environ.get("PIO_RETRIEVAL_NPROBE", "0"))
    if p <= 0:
        p = max(1, int(round(np.sqrt(n_partitions))))
    return min(p, n_partitions)


def quantize_enabled() -> bool:
    return os.environ.get("PIO_RETRIEVAL_QUANTIZE", "0") == "1"


def build_key(n_items: int) -> dict:
    """Everything that invalidates a built index when it changes — a
    persisted index whose key still matches is reused instead of rebuilt."""
    return {
        "n_items": n_items,
        "n_partitions": resolved_partitions(n_items),
        "quantize": quantize_enabled(),
        "kmeans_iters": int(os.environ.get("PIO_RETRIEVAL_KMEANS_ITERS", "6")),
        "train_sample": int(
            os.environ.get("PIO_RETRIEVAL_TRAIN_SAMPLE", "65536")),
        "seed": int(os.environ.get("PIO_RETRIEVAL_SEED", "0")),
    }


# -- the index ---------------------------------------------------------------

@dataclasses.dataclass
class IVFIndex:
    """Trained partition of the catalog + member-order rerank tables.

    ``centroids`` is ``[C, D+1]`` — the last column is the partition's mean
    item bias (see the module docstring). Members are stored sorted by
    partition: ``member_ids[offsets[p]:offsets[p+1]]`` are partition ``p``'s
    catalog indices, and ``emb_m``/``bias_m`` (or ``emb_q``/``scales_m``
    when quantized) hold the matching rows contiguously, so the rerank
    reads each probed partition as one slice. Read-only after build —
    serving threads share it without locks. Pickles with the model (host
    numpy only), so a persisted model redeploys without re-clustering.
    """

    centroids: np.ndarray        # [C, D+1] f32 (last col = mean member bias)
    member_ids: np.ndarray       # [N] int32, partition-sorted catalog indices
    offsets: np.ndarray          # [C+1] int64 partition boundaries
    bias_m: np.ndarray           # [N] f32 item bias in member order
    key: dict                    # build_key() this index was built under
    emb_m: Optional[np.ndarray] = None     # [N, D] f32 (fp32 rerank mode)
    emb_q: Optional[np.ndarray] = None     # [N, D] int8 (quantized mode)
    scales_m: Optional[np.ndarray] = None  # [N] f32 dequant scales
    build_seconds: float = 0.0
    # -- streaming staleness overlay (docs/streaming.md) -------------------
    # Rows a delta deploy updated AFTER this index was built: the k-means
    # assignment (and the member-order rerank tables, which older deployed
    # models may still share) hold their PRE-update embeddings. The overlay
    # keeps the current rows; search (a) rescores any gathered stale
    # candidate from the overlay and (b) appends stale ids a probe missed
    # to every candidate set — so a pruned probe never serves a pre-update
    # embedding as if it were current, and a row that moved INTO a user's
    # taste stays reachable until the rebuild threshold re-clusters.
    stale_ids: Optional[np.ndarray] = None      # sorted int64 catalog ids
    stale_emb: Optional[np.ndarray] = None      # [S, D] f32 current rows
    stale_bias: Optional[np.ndarray] = None     # [S] f32 current biases

    @property
    def n_partitions(self) -> int:
        return self.centroids.shape[0]

    @property
    def n_items(self) -> int:
        return self.member_ids.shape[0]

    @property
    def quantized(self) -> bool:
        return self.emb_q is not None

    def matches(self, key: dict) -> bool:
        return self.key == key

    # -- persistence -------------------------------------------------------
    #
    # The member-order rerank tables duplicate the catalog (emb_m is a full
    # fp32 copy of item_emb) — at the 10M-item scales two-stage targets that
    # would DOUBLE the persisted model artifact and every deploy transfer.
    # Only the clustering (centroids/member_ids/offsets/key — the part that
    # is expensive to recompute) pickles; load rehydrates the tables with
    # one O(N) gather from arrays the model blob already carries.

    def __post_init__(self):
        self._rehydrate_lock = threading.Lock()

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_rehydrate_lock", None)
        for k in ("emb_m", "emb_q", "scales_m", "bias_m"):
            state[k] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._rehydrate_lock = threading.Lock()

    @property
    def hydrated(self) -> bool:
        """Whether the rerank tables are resident (False right after
        unpickling — :meth:`rehydrate` before :meth:`search`)."""
        return self.bias_m is not None and (
            self.emb_m is not None or self.emb_q is not None)

    def rehydrate(self, item_emb: np.ndarray,
                  item_bias: np.ndarray) -> "IVFIndex":
        """Rebuild the member-order rerank tables after unpickling.

        Lock-guarded: a runtime mode flip (exact → two_stage) can land the
        first rehydration on overlapped serving threads. ``bias_m`` is
        assigned LAST — :attr:`hydrated` requires it, so a concurrent
        reader can never observe a half-built table set."""
        if self.hydrated:
            return self
        with self._rehydrate_lock:
            if self.hydrated:
                return self
            order = self.member_ids.astype(np.int64)
            emb_m = np.ascontiguousarray(
                np.asarray(item_emb, np.float32)[order])
            bias_m = np.ascontiguousarray(
                np.asarray(item_bias, np.float32)[order])
            if self.key.get("quantize"):
                from incubator_predictionio_tpu.ops.retrieval import (
                    quantize_rows,
                )

                self.emb_q, self.scales_m = quantize_rows(emb_m)
            else:
                self.emb_m = emb_m
            self.bias_m = bias_m
        return self

    # -- streaming staleness ----------------------------------------------
    @property
    def stale_count(self) -> int:
        return 0 if self.stale_ids is None else int(len(self.stale_ids))

    @property
    def stale_fraction(self) -> float:
        n = self.n_items
        return (self.stale_count / n) if n else 0.0

    def with_updated_rows(self, ids: np.ndarray, emb_rows: np.ndarray,
                          bias_rows: np.ndarray) -> "IVFIndex":
        """A NEW index view with ``ids``' current rows overlaid. The big
        arrays (centroids, member layout, rerank tables) are shared with
        this index — the old deployed model keeps serving its own view
        untouched while the delta-applied model serves the overlay."""
        ids = np.asarray(ids, np.int64)
        emb_rows = np.asarray(emb_rows, np.float32).reshape(len(ids), -1)
        bias_rows = np.asarray(bias_rows, np.float32).reshape(len(ids))
        merged: dict[int, tuple[np.ndarray, float]] = {}
        if self.stale_ids is not None:
            for i, sid in enumerate(self.stale_ids):
                merged[int(sid)] = (self.stale_emb[i], float(self.stale_bias[i]))
        for i, sid in enumerate(ids):
            merged[int(sid)] = (emb_rows[i], float(bias_rows[i]))
        order = np.asarray(sorted(merged), np.int64)
        new = dataclasses.replace(
            self,
            stale_ids=order,
            stale_emb=np.stack([merged[int(s)][0] for s in order]).astype(
                np.float32),
            stale_bias=np.asarray(
                [merged[int(s)][1] for s in order], np.float32),
        )
        return new

    def _apply_stale_overlay(
        self, ids: np.ndarray, scores: np.ndarray, qrow: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Rescore gathered stale candidates from the overlay and append
        the stale ids this probe missed (pre-bias score space)."""
        s_ids = self.stale_ids
        pos = np.minimum(np.searchsorted(s_ids, ids), len(s_ids) - 1)
        hit = s_ids[pos] == ids
        if hit.any():
            sel = pos[hit]
            scores[hit] = self.stale_emb[sel] @ qrow + self.stale_bias[sel]
        present = np.zeros(len(s_ids), bool)
        present[pos[hit]] = True
        missing = ~present
        if missing.any():
            add_scores = (self.stale_emb[missing] @ qrow
                          + self.stale_bias[missing])
            ids = np.concatenate([ids, s_ids[missing]])
            scores = np.concatenate([scores, add_scores])
        return ids, scores

    def stats(self) -> dict:
        """Partition-shape summary for ``pio-tpu index`` / status pages."""
        sizes = np.diff(self.offsets)
        mean = float(sizes.mean()) if len(sizes) else 0.0
        nbytes = sum(
            a.nbytes for a in (
                self.centroids, self.member_ids, self.offsets, self.bias_m,
                self.emb_m, self.emb_q, self.scales_m)
            if a is not None)
        return {
            "n_partitions": int(self.n_partitions),
            "n_items": int(self.n_items),
            # which table shard this index covers, when it is one of a
            # sharded model's per-shard partitions (docs/sharding.md);
            # None for a whole-catalog index
            "shard": self.key.get("shard"),
            "partition_size_min": int(sizes.min()) if len(sizes) else 0,
            "partition_size_mean": round(mean, 1),
            "partition_size_max": int(sizes.max()) if len(sizes) else 0,
            "size_skew": round(float(sizes.max()) / mean, 2) if mean else 0.0,
            "empty_partitions": int((sizes == 0).sum()),
            "quantized": self.quantized,
            "default_nprobe": resolved_nprobe(self.n_partitions),
            "index_bytes": int(nbytes),
            "build_seconds": round(self.build_seconds, 2),
            "stale_rows": self.stale_count,
        }

    # -- search -----------------------------------------------------------

    def probe(self, q: np.ndarray, nprobe: int) -> np.ndarray:
        """Top-``nprobe`` partition ids per query row (``[B, nprobe]``)."""
        coarse = q @ self.centroids[:, :-1].T + self.centroids[:, -1][None, :]
        if nprobe >= self.n_partitions:
            return np.tile(np.arange(self.n_partitions), (len(q), 1))
        return np.argpartition(-coarse, nprobe - 1, axis=1)[:, :nprobe]

    def candidate_ids(self, qrow: np.ndarray, nprobe: int) -> np.ndarray:
        """One query's gathered candidate set (tests / inspection)."""
        parts = np.sort(self.probe(qrow[None, :], nprobe)[0])
        return np.concatenate([
            self.member_ids[self.offsets[p]:self.offsets[p + 1]]
            for p in parts]) if len(parts) else np.empty(0, np.int32)

    def search(
        self,
        q: np.ndarray,               # [B, D] f32 user vectors
        user_bias: np.ndarray,       # [B] f32
        mean: float,
        num: int,
        nprobe: Optional[int] = None,
        exclude: Optional[np.ndarray] = None,
        row_mask: Optional[np.ndarray] = None,
        observe: bool = True,
    ) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Two-stage top-``num``: returns ``(idx [B, num] int64, scores
        [B, num] f32)`` with the exact path's score semantics, or ``None``
        when some row's probed partitions hold fewer than ``num`` raw
        candidates — or fewer than ``num`` candidates that survive the
        rule filters with a finite score (the caller falls back to the
        exact path, which sees the whole catalog — the pruned path never
        returns a short result, and never serves a masked item in place
        of an unmasked one the probe missed).

        ``exclude``/``row_mask`` are in catalog-index space and are applied
        to the exact rerank scores AFTER the gather (candidate-index
        space): masked candidates score -inf and can only fill trailing
        slots once every unmasked candidate is placed, mirroring the
        full-catalog mask semantics.
        """
        b = q.shape[0]
        if num <= 0:
            return (np.zeros((b, 0), np.int64), np.zeros((b, 0), np.float32))
        if b == 0:
            return (np.zeros((0, num), np.int64), np.zeros((0, num), np.float32))
        nprobe = resolved_nprobe(self.n_partitions) if nprobe is None \
            else min(max(1, nprobe), self.n_partitions)
        t0 = time.perf_counter()
        probe = self.probe(q, nprobe)
        counts = np.diff(self.offsets)[probe].sum(axis=1)
        if observe:
            COARSE_SEC.observe(time.perf_counter() - t0)
        if int(counts.min()) < num:
            if observe:
                FALLBACKS.inc()
            return None
        # exclude lands per row via searchsorted over the SORTED exclude set
        # — O(cnt log E) in candidate space; an n_items-sized lookup table
        # would put O(catalog) allocation back on the path built to avoid it
        excl_sorted = None
        if exclude is not None and len(exclude):
            excl_sorted = np.sort(np.asarray(exclude, np.int64))
        t0 = time.perf_counter()
        out_idx = np.empty((b, num), np.int64)
        out_scores = np.empty((b, num), np.float32)
        for r in range(b):
            parts = np.sort(probe[r])  # ordered slices walk memory forward
            cnt = int(counts[r])
            ids = np.empty(cnt, np.int32)
            scores = np.empty(cnt, np.float32)
            qrow = q[r]
            pos = 0
            for p in parts:
                lo, hi = int(self.offsets[p]), int(self.offsets[p + 1])
                m = hi - lo
                if not m:
                    continue
                ids[pos:pos + m] = self.member_ids[lo:hi]
                if self.quantized:
                    scores[pos:pos + m] = (
                        self.emb_q[lo:hi].astype(np.float32) @ qrow
                    ) * self.scales_m[lo:hi] + self.bias_m[lo:hi]
                else:
                    scores[pos:pos + m] = \
                        self.emb_m[lo:hi] @ qrow + self.bias_m[lo:hi]
                pos += m
            if self.stale_ids is not None and len(self.stale_ids):
                ids, scores = self._apply_stale_overlay(ids, scores, qrow)
            scores += user_bias[r] + mean
            if excl_sorted is not None:
                pos = np.minimum(np.searchsorted(excl_sorted, ids),
                                 len(excl_sorted) - 1)
                scores[excl_sorted[pos] == ids] = -np.inf
            if row_mask is not None:
                scores += row_mask[r, ids]
            top = topk_row(scores, num)
            if not np.isfinite(scores[top[-1]]):
                # fewer than num candidates survived the rule filters in
                # THIS probe set — a masked (-inf) item would fill the
                # trailing slots where the exact path, seeing the whole
                # catalog, still has unmasked items to place. Fall back.
                if observe:
                    FALLBACKS.inc()
                return None
            out_idx[r] = ids[top]
            out_scores[r] = scores[top]
            if observe:
                CANDIDATES.observe(cnt)
        if observe:
            RERANK_SEC.observe(time.perf_counter() - t0)
            TWO_STAGE_BATCHES.inc()
        return out_idx, out_scores


# -- build -------------------------------------------------------------------

def _assign(x: np.ndarray, cent: np.ndarray,
            chunk: int = ASSIGN_CHUNK) -> np.ndarray:
    """Nearest-centroid (euclidean) assignment, chunked over rows."""
    half = 0.5 * np.einsum("cd,cd->c", cent, cent)
    out = np.empty(len(x), np.int32)
    for lo in range(0, len(x), chunk):
        d = x[lo:lo + chunk] @ cent.T
        d -= half[None, :]
        out[lo:lo + chunk] = np.argmax(d, axis=1)
    return out


def _kmeans(x: np.ndarray, c: int, iters: int,
            rng: np.random.Generator) -> np.ndarray:
    """Lloyd's k-means on (a sample of) the augmented rows. Per-dimension
    ``bincount`` accumulation keeps the update pass in C loops; empty
    clusters reseed from random rows so every centroid stays live."""
    cent = x[rng.choice(len(x), size=c, replace=False)].copy()
    d = x.shape[1]
    for _ in range(iters):
        a = _assign(x, cent)
        counts = np.bincount(a, minlength=c).astype(np.float64)
        for j in range(d):
            cent[:, j] = np.bincount(a, weights=x[:, j], minlength=c)
        live = counts > 0
        cent[live] /= counts[live, None]
        n_dead = int((~live).sum())
        if n_dead:
            cent[~live] = x[rng.choice(len(x), size=n_dead, replace=False)]
    return cent


def build_ivf(item_emb: np.ndarray, item_bias: np.ndarray,
              key: Optional[dict] = None) -> IVFIndex:
    """Cluster the catalog and lay out the member-order rerank tables.

    Deploy-time cost: k-means on a bounded sample plus ONE full-catalog
    assignment pass (chunked matmuls) — minutes at 10M rows, amortized over
    every query the deployment serves.
    """
    n, d = item_emb.shape
    key = dict(key if key is not None else build_key(n))
    if key.get("n_items") != n:
        key["n_items"] = n
    rng = np.random.default_rng(key["seed"])
    c = min(key["n_partitions"], max(1, n))
    t0 = time.perf_counter()
    item_emb = np.asarray(item_emb, np.float32)
    item_bias = np.asarray(item_bias, np.float32)
    aug = np.concatenate([item_emb, item_bias[:, None]], axis=1)
    sample = min(int(key["train_sample"]), n)
    train = aug if sample >= n else \
        aug[rng.choice(n, size=sample, replace=False)]
    c = min(c, len(train))  # can't seed more centroids than training rows
    cent = _kmeans(train, c, int(key["kmeans_iters"]), rng)
    assign = _assign(aug, cent)
    order = np.argsort(assign, kind="stable")
    sizes = np.bincount(assign, minlength=c)
    offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
    emb_m = np.ascontiguousarray(item_emb[order])
    index = IVFIndex(
        centroids=cent,
        member_ids=order.astype(np.int32),
        offsets=offsets,
        bias_m=np.ascontiguousarray(item_bias[order]),
        key=key,
    )
    if key["quantize"]:
        from incubator_predictionio_tpu.ops.retrieval import quantize_rows

        index.emb_q, index.scales_m = quantize_rows(emb_m)
    else:
        index.emb_m = emb_m
    index.build_seconds = time.perf_counter() - t0
    return index
