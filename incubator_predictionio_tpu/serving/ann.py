"""Two-stage retrieval: trained IVF coarse pruning + exact candidate rerank.

Exact serving scores every query against the whole catalog — an O(catalog)
``[B, N]`` matmul per batch that stops being "as fast as the hardware
allows" at the 10M-item shapes ALX (arxiv 2112.02194) targets. This module
is the coarse-to-fine answer:

- **Build** (deploy time, :func:`build_ivf`): k-means over the item
  embeddings *augmented with the item bias as an extra coordinate* (the
  query side implicitly carries a 1.0 there, so a centroid's coarse score
  ``q·c_emb + c_bias`` is an unbiased estimate of its members' exact
  scores — popular-but-orthogonal items don't fall out of the probe set).
  Members are laid out contiguously per partition (CSR: ``member_ids`` +
  ``offsets``), so gathering a partition's candidates is a slice, never a
  fancy-index gather.
- **Coarse stage**: score the ``[C]`` centroids per query and keep the
  top-``nprobe`` partitions — pruning the catalog to a few percent.
- **Rerank stage**: int8 storage is the DEFAULT — member rows are held
  quantized (the same symmetric row quantization the Pallas kernel uses,
  :func:`~incubator_predictionio_tpu.ops.retrieval.quantize_rows`) and
  scored int8×int8→int32 with ONE fp32 rescale per candidate, grouped by
  partition across the batch so each probed int8 block is read once.
  The coarse stage quantizes alongside it (``PIO_RETRIEVAL_QUANT_COARSE``).
  ``PIO_RETRIEVAL_QUANTIZE=0`` opts a deployment back onto fp32 rows +
  exact serving math for the rerank (the recall-oracle path, always kept).
  Either way the shared serial-parity top-k chain picks the result.

Rule filters (``exclude`` / ``row_mask``) are applied **in candidate-index
space after the gather**, as -inf on the exact rerank scores — a filtered
candidate can therefore never displace an unfiltered one, exactly like the
full-catalog path. The exact path itself stays untouched as the recall
oracle; tests assert a recall@k floor against it
(tests/test_two_stage_retrieval.py).

Mode selection is env-driven (``PIO_RETRIEVAL_MODE`` = ``exact`` |
``two_stage`` | ``auto``; auto keeps catalogs under
``PIO_RETRIEVAL_MIN_ITEMS`` on the exact path so small templates keep
bitwise parity). See docs/serving.md ("Two-stage retrieval").
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Iterator, Optional

import numpy as np

from incubator_predictionio_tpu.obs.metrics import REGISTRY
from incubator_predictionio_tpu.serving.topk import topk_row

#: Rows per chunk for the full-catalog assignment pass at build time — keeps
#: the [chunk, C] distance buffer bounded regardless of catalog size.
ASSIGN_CHUNK = 131_072

COARSE_SEC = REGISTRY.histogram(
    "pio_retrieval_coarse_seconds",
    "Two-stage retrieval: centroid scoring + partition selection per batch")
RERANK_SEC = REGISTRY.histogram(
    "pio_retrieval_rerank_seconds",
    "Two-stage retrieval: exact candidate rerank per batch")
CANDIDATES = REGISTRY.histogram(
    "pio_retrieval_candidates",
    "Candidates gathered per query by the coarse stage",
    buckets=(64, 256, 1024, 4096, 16384, 65536, 262144, 1048576))
TWO_STAGE_BATCHES = REGISTRY.counter(
    "pio_retrieval_two_stage_total",
    "Batches served through the two-stage (pruned) path")
FALLBACKS = REGISTRY.counter(
    "pio_retrieval_fallback_total",
    "Two-stage-eligible batches that fell back to the exact path "
    "(probed partitions held fewer raw — or post-rule-filter finite — "
    "candidates than the requested top-k)")
INT8_COARSE = REGISTRY.counter(
    "pio_retrieval_int8_coarse_total",
    "Batches whose coarse (centroid) stage scored int8×int8→int32 "
    "against the quantized centroid table (PIO_RETRIEVAL_QUANT_COARSE)")
INT8_RERANK = REGISTRY.counter(
    "pio_retrieval_int8_rerank_total",
    "Batches whose candidate rerank scored int8×int8→int32 over the "
    "quantized member slices (one fp32 rescale per candidate; the fp32 "
    "dequantize-first path is retired)")


# -- env knobs ---------------------------------------------------------------

def retrieval_mode() -> str:
    """``PIO_RETRIEVAL_MODE``: ``exact`` | ``two_stage`` | ``auto``."""
    mode = os.environ.get("PIO_RETRIEVAL_MODE", "auto").strip().lower()
    if mode not in ("exact", "two_stage", "auto"):
        raise ValueError(
            f"PIO_RETRIEVAL_MODE={mode!r} (want exact|two_stage|auto)")
    return mode


def min_items() -> int:
    return int(os.environ.get("PIO_RETRIEVAL_MIN_ITEMS", "100000"))


def two_stage_enabled(n_items: int) -> bool:
    """Whether a catalog of ``n_items`` should serve two-stage right now."""
    mode = retrieval_mode()
    if mode == "two_stage":
        return True
    return mode == "auto" and n_items >= min_items()


def default_partitions(n_items: int) -> int:
    """√N partitions, clamped — the classic IVF sizing."""
    if n_items <= 0:
        return 1
    c = int(round(np.sqrt(n_items)))
    return max(1, min(c, max(1, n_items // 4), 65_536))


def resolved_partitions(n_items: int) -> int:
    c = int(os.environ.get("PIO_RETRIEVAL_PARTITIONS", "0"))
    return c if c > 0 else default_partitions(n_items)


def resolved_nprobe(n_partitions: int) -> int:
    """√C probes by default, clamped to the partition count."""
    p = int(os.environ.get("PIO_RETRIEVAL_NPROBE", "0"))
    if p <= 0:
        p = max(1, int(round(np.sqrt(n_partitions))))
    return min(p, n_partitions)


def quantize_enabled() -> bool:
    """int8 rerank storage is the default; ``PIO_RETRIEVAL_QUANTIZE=0``
    opts a deployment back onto the fp32 exact-math rerank."""
    return os.environ.get("PIO_RETRIEVAL_QUANTIZE", "1") != "0"


def quant_coarse_enabled(index_quantized: bool) -> bool:
    """``PIO_RETRIEVAL_QUANT_COARSE``: ``auto`` | ``1`` | ``0``.

    Whether the coarse (centroid) stage scores int8×int8→int32 against the
    quantized centroid table. ``auto`` (default) follows the index's rerank
    storage — a quantized index probes quantized, an fp32 index probes
    fp32; ``1``/``0`` force it per deployment. int8 coarse always requires
    a quantized index (the centroid tables quantize alongside the member
    rows)."""
    val = os.environ.get("PIO_RETRIEVAL_QUANT_COARSE", "auto").strip().lower()
    if val not in ("auto", "1", "0"):
        raise ValueError(
            f"PIO_RETRIEVAL_QUANT_COARSE={val!r} (want auto|1|0)")
    if not index_quantized:
        return False
    return val != "0"


def build_key(n_items: int) -> dict:
    """Everything that invalidates a built index when it changes — a
    persisted index whose key still matches is reused instead of rebuilt."""
    return {
        "n_items": n_items,
        "n_partitions": resolved_partitions(n_items),
        "quantize": quantize_enabled(),
        "kmeans_iters": int(os.environ.get("PIO_RETRIEVAL_KMEANS_ITERS", "6")),
        "train_sample": int(
            os.environ.get("PIO_RETRIEVAL_TRAIN_SAMPLE", "65536")),
        "seed": int(os.environ.get("PIO_RETRIEVAL_SEED", "0")),
    }


# -- the index ---------------------------------------------------------------

@dataclasses.dataclass
class IVFIndex:
    """Trained partition of the catalog + member-order rerank tables.

    ``centroids`` is ``[C, D+1]`` — the last column is the partition's mean
    item bias (see the module docstring). Members are stored sorted by
    partition: ``member_ids[offsets[p]:offsets[p+1]]`` are partition ``p``'s
    catalog indices, and ``emb_m``/``bias_m`` (or ``emb_q``/``scales_m``
    when quantized) hold the matching rows contiguously, so the rerank
    reads each probed partition as one slice. Read-only after build —
    serving threads share it without locks. Pickles with the model (host
    numpy only), so a persisted model redeploys without re-clustering.
    """

    centroids: np.ndarray        # [C, D+1] f32 (last col = mean member bias)
    member_ids: np.ndarray       # [N] int32, partition-sorted catalog indices
    offsets: np.ndarray          # [C+1] int64 partition boundaries
    bias_m: np.ndarray           # [N] f32 item bias in member order
    key: dict                    # build_key() this index was built under
    emb_m: Optional[np.ndarray] = None     # [N, D] f32 (fp32 rerank mode)
    emb_q: Optional[np.ndarray] = None     # [N, D] int8 (quantized mode)
    scales_m: Optional[np.ndarray] = None  # [N] f32 dequant scales
    build_seconds: float = 0.0
    # -- streaming staleness overlay (docs/streaming.md) -------------------
    # Rows a delta deploy updated AFTER this index was built: the k-means
    # assignment (and the member-order rerank tables, which older deployed
    # models may still share) hold their PRE-update embeddings. The overlay
    # keeps the current rows; search (a) rescores any gathered stale
    # candidate from the overlay and (b) appends stale ids a probe missed
    # to every candidate set — so a pruned probe never serves a pre-update
    # embedding as if it were current, and a row that moved INTO a user's
    # taste stays reachable until the rebuild threshold re-clusters.
    stale_ids: Optional[np.ndarray] = None      # sorted int64 catalog ids
    stale_emb: Optional[np.ndarray] = None      # [S, D] f32 current rows
    stale_bias: Optional[np.ndarray] = None     # [S] f32 current biases

    @property
    def n_partitions(self) -> int:
        return self.centroids.shape[0]

    @property
    def n_items(self) -> int:
        return self.member_ids.shape[0]

    @property
    def quantized(self) -> bool:
        return self.emb_q is not None

    def matches(self, key: dict) -> bool:
        return self.key == key

    # -- persistence -------------------------------------------------------
    #
    # The member-order rerank tables duplicate the catalog (emb_m is a full
    # fp32 copy of item_emb) — at the 10M-item scales two-stage targets that
    # would DOUBLE the persisted model artifact and every deploy transfer.
    # Only the clustering (centroids/member_ids/offsets/key — the part that
    # is expensive to recompute) pickles; load rehydrates the tables with
    # one O(N) gather from arrays the model blob already carries.

    def __post_init__(self):
        self._rehydrate_lock = threading.Lock()
        self._cent_quant = None
        self._cent_device = None

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_rehydrate_lock", None)
        state.pop("_cent_quant", None)
        state.pop("_cent_device", None)
        for k in ("emb_m", "emb_q", "scales_m", "bias_m"):
            state[k] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._rehydrate_lock = threading.Lock()
        self._cent_quant = None
        self._cent_device = None

    def _coarse_quant(self) -> tuple[np.ndarray, np.ndarray]:
        """Lazy ``(cent_q [C, D] int8, cent_scales [C] f32)`` — the
        quantized twin of the centroid embedding columns (the mean-bias
        column stays fp32 and is added after the rescale). Derived data:
        cheap to recompute, so it never pickles (the slim-persistence
        contract) and rebuilds on first int8 probe after a load."""
        cq = self._cent_quant
        if cq is None:
            with self._rehydrate_lock:
                cq = self._cent_quant
                if cq is None:
                    from incubator_predictionio_tpu.ops.retrieval import (
                        quantize_rows,
                    )

                    q8, scales = quantize_rows(
                        np.asarray(self.centroids[:, :-1], np.float32))
                    cq = self._cent_quant = (q8, scales)
        return cq

    @property
    def hydrated(self) -> bool:
        """Whether the rerank tables are resident (False right after
        unpickling — :meth:`rehydrate` before :meth:`search`)."""
        return self.bias_m is not None and (
            self.emb_m is not None or self.emb_q is not None)

    def rehydrate(self, item_emb: np.ndarray,
                  item_bias: np.ndarray) -> "IVFIndex":
        """Rebuild the member-order rerank tables after unpickling.

        Lock-guarded: a runtime mode flip (exact → two_stage) can land the
        first rehydration on overlapped serving threads. ``bias_m`` is
        assigned LAST — :attr:`hydrated` requires it, so a concurrent
        reader can never observe a half-built table set."""
        if self.hydrated:
            return self
        with self._rehydrate_lock:
            if self.hydrated:
                return self
            order = self.member_ids.astype(np.int64)
            emb_m = np.ascontiguousarray(
                np.asarray(item_emb, np.float32)[order])
            bias_m = np.ascontiguousarray(
                np.asarray(item_bias, np.float32)[order])
            if self.key.get("quantize"):
                from incubator_predictionio_tpu.ops.retrieval import (
                    quantize_rows,
                )

                self.emb_q, self.scales_m = quantize_rows(emb_m)
            else:
                self.emb_m = emb_m
            self.bias_m = bias_m
        return self

    # -- streaming staleness ----------------------------------------------
    @property
    def stale_count(self) -> int:
        return 0 if self.stale_ids is None else int(len(self.stale_ids))

    @property
    def stale_fraction(self) -> float:
        n = self.n_items
        return (self.stale_count / n) if n else 0.0

    def with_updated_rows(self, ids: np.ndarray, emb_rows: np.ndarray,
                          bias_rows: np.ndarray) -> "IVFIndex":
        """A NEW index view with ``ids``' current rows overlaid. The big
        arrays (centroids, member layout, rerank tables) are shared with
        this index — the old deployed model keeps serving its own view
        untouched while the delta-applied model serves the overlay."""
        ids = np.asarray(ids, np.int64)
        emb_rows = np.asarray(emb_rows, np.float32).reshape(len(ids), -1)
        bias_rows = np.asarray(bias_rows, np.float32).reshape(len(ids))
        merged: dict[int, tuple[np.ndarray, float]] = {}
        if self.stale_ids is not None:
            for i, sid in enumerate(self.stale_ids):
                merged[int(sid)] = (self.stale_emb[i], float(self.stale_bias[i]))
        for i, sid in enumerate(ids):
            merged[int(sid)] = (emb_rows[i], float(bias_rows[i]))
        order = np.asarray(sorted(merged), np.int64)
        new = dataclasses.replace(
            self,
            stale_ids=order,
            stale_emb=np.stack([merged[int(s)][0] for s in order]).astype(
                np.float32),
            stale_bias=np.asarray(
                [merged[int(s)][1] for s in order], np.float32),
        )
        return new

    def _apply_stale_overlay(
        self, ids: np.ndarray, scores: np.ndarray, qrow: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Rescore gathered stale candidates from the overlay and append
        the stale ids this probe missed (pre-bias score space)."""
        s_ids = self.stale_ids
        pos = np.minimum(np.searchsorted(s_ids, ids), len(s_ids) - 1)
        hit = s_ids[pos] == ids
        if hit.any():
            sel = pos[hit]
            scores[hit] = self.stale_emb[sel] @ qrow + self.stale_bias[sel]
        present = np.zeros(len(s_ids), bool)
        present[pos[hit]] = True
        missing = ~present
        if missing.any():
            add_scores = (self.stale_emb[missing] @ qrow
                          + self.stale_bias[missing])
            ids = np.concatenate([ids, s_ids[missing]])
            scores = np.concatenate([scores, add_scores])
        return ids, scores

    def stats(self) -> dict:
        """Partition-shape summary for ``pio-tpu index`` / status pages."""
        sizes = np.diff(self.offsets)
        mean = float(sizes.mean()) if len(sizes) else 0.0
        nbytes = sum(
            a.nbytes for a in (
                self.centroids, self.member_ids, self.offsets, self.bias_m,
                self.emb_m, self.emb_q, self.scales_m)
            if a is not None)
        # analytic rerank-storage accounting (stable whether or not the
        # tables are hydrated): int8 layout = 1 byte/coord + one f32 scale
        # per row; the fp32 equivalent is what the same rows cost unquantized
        n = self.n_items
        d = self.centroids.shape[1] - 1
        fp32_bytes = n * d * 4
        rerank_bytes = (n * d + n * 4) if self.quantized else fp32_bytes
        return {
            "n_partitions": int(self.n_partitions),
            "n_items": int(self.n_items),
            # which table shard this index covers, when it is one of a
            # sharded model's per-shard partitions (docs/sharding.md);
            # None for a whole-catalog index
            "shard": self.key.get("shard"),
            "partition_size_min": int(sizes.min()) if len(sizes) else 0,
            "partition_size_mean": round(mean, 1),
            "partition_size_max": int(sizes.max()) if len(sizes) else 0,
            "size_skew": round(float(sizes.max()) / mean, 2) if mean else 0.0,
            "empty_partitions": int((sizes == 0).sum()),
            "quantized": self.quantized,
            "quant_coarse": quant_coarse_enabled(self.quantized),
            "rerank_bytes": int(rerank_bytes),
            "rerank_bytes_fp32": int(fp32_bytes),
            "bytes_saved": int(fp32_bytes - rerank_bytes),
            "default_nprobe": resolved_nprobe(self.n_partitions),
            "index_bytes": int(nbytes),
            "build_seconds": round(self.build_seconds, 2),
            "stale_rows": self.stale_count,
        }

    # -- search -----------------------------------------------------------

    def probe(self, q: np.ndarray, nprobe: int,
              q_quant: Optional[tuple] = None) -> np.ndarray:
        """Top-``nprobe`` partition ids per query row (``[B, nprobe]``).

        With ``q_quant`` (the ``(q_q int8, q_scales f32)`` pair from
        ``quantize_rows``) the centroid scores run int8×int8→int32 with one
        fp32 rescale — the host-exact twin of the Pallas coarse kernel
        (ops/retrieval.py ``score_centroids_quantized``); the fp32
        mean-member-bias column is added after the rescale."""
        if q_quant is not None:
            import sys

            from incubator_predictionio_tpu.ops.retrieval import (
                int8_matmul_exact,
            )

            q_q, q_scales = q_quant
            if "jax" in sys.modules and \
                    sys.modules["jax"].default_backend() == "tpu":
                # the Pallas int8 coarse kernel (ops/retrieval.py). Same
                # int8×int8→int32 + one-rescale contract as the host twin
                # below — the accumulation is exact integers either way;
                # only the final rescale may FMA-contract (≤1 ulp), so
                # probe sets agree except exact near-ties at the boundary
                coarse = self._probe_tpu(q_q, q_scales)
            else:
                cent_q, cent_scales = self._coarse_quant()
                coarse = (int8_matmul_exact(q_q, cent_q)
                          * (q_scales[:, None] * cent_scales[None, :])
                          + self.centroids[:, -1][None, :])
        else:
            coarse = (q @ self.centroids[:, :-1].T
                      + self.centroids[:, -1][None, :])
        if nprobe >= self.n_partitions:
            return np.tile(np.arange(self.n_partitions), (len(q), 1))
        return np.argpartition(-coarse, nprobe - 1, axis=1)[:, :nprobe]

    def _probe_tpu(self, q_q: np.ndarray, q_scales: np.ndarray) -> np.ndarray:
        """Coarse scores through the Pallas int8 kernel on a resident
        device copy of the quantized centroid table. The batch pads to a
        power-of-two bucket (≥ 8) so the query mix shares a handful of
        executables; centroid padding carries -inf bias and can never win
        a probe slot."""
        import jax
        import jax.numpy as jnp

        from incubator_predictionio_tpu.ops.retrieval import (
            pad_centroids,
            score_centroids_quantized,
        )
        from incubator_predictionio_tpu.utils import jitstats

        dev = self._cent_device
        if dev is None:
            with self._rehydrate_lock:
                dev = self._cent_device
                if dev is None:
                    cent_q, cent_scales = self._coarse_quant()
                    cq, cs, cb = pad_centroids(
                        cent_q, cent_scales,
                        np.asarray(self.centroids[:, -1], np.float32))
                    dev = self._cent_device = tuple(
                        jax.device_put(v) for v in (cq, cs, cb))
        cq, cs, cb = dev
        b = q_q.shape[0]
        bp = 1 << max(3, (b - 1).bit_length())
        qq = np.zeros((bp, q_q.shape[1]), np.int8)
        qq[:b] = q_q
        qs = np.zeros(bp, np.float32)
        qs[:b] = q_scales
        with jitstats.dispatch_timer(
                ("ivf_coarse_int8", bp, int(cq.shape[0]))):
            out = jax.device_get(score_centroids_quantized(
                jnp.asarray(qq), jnp.asarray(qs), cq, cs, cb))
        return np.asarray(out)[:b, : self.n_partitions]

    def candidate_ids(self, qrow: np.ndarray, nprobe: int) -> np.ndarray:
        """One query's gathered candidate set (tests / inspection)."""
        parts = np.sort(self.probe(qrow[None, :], nprobe)[0])
        return np.concatenate([
            self.member_ids[self.offsets[p]:self.offsets[p + 1]]
            for p in parts]) if len(parts) else np.empty(0, np.int32)

    def _int8_partition_scores(
        self, probe: np.ndarray, q_quant: tuple,
    ) -> dict[int, "Iterator[np.ndarray]"]:
        """int8×int8→int32 rerank scores for every probed partition,
        grouped by partition across the batch: each probed partition's int8
        member block is upcast (and its scores rescaled) ONCE for all the
        queries that probe it — one ``[probers, members]`` GEMM per
        partition instead of a GEMV per (query, partition) pair. Because
        the int8 accumulation is exact integers in f32
        (ops/retrieval.int8_matmul_exact), the batched GEMM scores are
        bit-identical to what per-query GEMVs would produce — batching is
        free of reduction-order drift, something the fp32 path can't claim.
        This cross-query amortization is where the int8 lane's serve-side
        speedup comes from, so it grows with the coalesced batch size.

        The (query, partition) grouping comes from ONE stable argsort of
        the probe matrix — no per-partition membership scans. Returns
        ``{partition: row-iterator}`` where the iterator yields that
        partition's ``[members]`` f32 score rows in ascending query order:
        the rescale (``scale_query · scale_row``) and member bias are
        already applied, and because :meth:`search` walks queries in
        ascending order and each query probes a partition at most once,
        ``next()`` hands every consumer exactly its row with no lookup."""
        from incubator_predictionio_tpu.ops.retrieval import (
            INT8_EXACT_MAX_RANK,
            int8_matmul_exact,
        )

        q_q, q_scales = q_quant
        flat = probe.ravel()
        order = np.argsort(flat, kind="stable")  # stable ⇒ ascending query
        qidx = order // probe.shape[1]
        sflat = flat[order]
        bounds = np.flatnonzero(np.diff(sflat)) + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [len(sflat)]))
        # the exact-accumulation dtype decision is per BATCH, not per GEMM:
        # upcast the query block once and inline the per-partition matmul
        # (int8_matmul_exact's math, minus its per-call dispatch overhead)
        exact_f32 = q_q.shape[1] <= INT8_EXACT_MAX_RANK
        qf = q_q.astype(np.float32 if exact_f32 else np.float64)
        emb_q, offsets = self.emb_q, self.offsets
        scales_m, bias_m = self.scales_m, self.bias_m
        out: dict[int, Iterator[np.ndarray]] = {}
        for a, e in zip(starts.tolist(), ends.tolist()):
            p = int(sflat[a])
            lo, hi = int(offsets[p]), int(offsets[p + 1])
            if hi == lo:
                continue
            who = qidx[a:e]
            if exact_f32:
                acc = qf[who] @ emb_q[lo:hi].astype(np.float32).T
            else:
                acc = int8_matmul_exact(q_q[who], emb_q[lo:hi])
            acc *= q_scales[who][:, None] * scales_m[lo:hi][None, :]
            acc += bias_m[lo:hi][None, :]
            out[p] = iter(acc)
        return out

    def search(
        self,
        q: np.ndarray,               # [B, D] f32 user vectors
        user_bias: np.ndarray,       # [B] f32
        mean: float,
        num: int,
        nprobe: Optional[int] = None,
        exclude: Optional[np.ndarray] = None,
        row_mask: Optional[np.ndarray] = None,
        observe: bool = True,
    ) -> Optional[tuple[np.ndarray, np.ndarray]]:
        """Two-stage top-``num``: returns ``(idx [B, num] int64, scores
        [B, num] f32)`` with the exact path's score semantics, or ``None``
        when some row's probed partitions hold fewer than ``num`` raw
        candidates — or fewer than ``num`` candidates that survive the
        rule filters with a finite score (the caller falls back to the
        exact path, which sees the whole catalog — the pruned path never
        returns a short result, and never serves a masked item in place
        of an unmasked one the probe missed).

        ``exclude``/``row_mask`` are in catalog-index space and are applied
        to the exact rerank scores AFTER the gather (candidate-index
        space): masked candidates score -inf and can only fill trailing
        slots once every unmasked candidate is placed, mirroring the
        full-catalog mask semantics.
        """
        b = q.shape[0]
        if num <= 0:
            return (np.zeros((b, 0), np.int64), np.zeros((b, 0), np.float32))
        if b == 0:
            return (np.zeros((0, num), np.int64), np.zeros((0, num), np.float32))
        nprobe = resolved_nprobe(self.n_partitions) if nprobe is None \
            else min(max(1, nprobe), self.n_partitions)
        t0 = time.perf_counter()
        q_quant = None
        if self.quantized:
            from incubator_predictionio_tpu.ops.retrieval import quantize_rows

            # one per-row query quantization serves BOTH stages (the int8
            # coarse probe and the int8 rerank share q_q/q_scales)
            q_quant = quantize_rows(np.asarray(q, np.float32))
        int8_coarse = q_quant is not None and quant_coarse_enabled(True)
        probe = self.probe(q, nprobe, q_quant=q_quant if int8_coarse else None)
        counts = np.diff(self.offsets)[probe].sum(axis=1)
        if observe:
            COARSE_SEC.observe(time.perf_counter() - t0)
            if int8_coarse:
                INT8_COARSE.inc()
        if int(counts.min()) < num:
            if observe:
                FALLBACKS.inc()
            return None
        # exclude lands per row via searchsorted over the SORTED exclude set
        # — O(cnt log E) in candidate space; an n_items-sized lookup table
        # would put O(catalog) allocation back on the path built to avoid it
        excl_sorted = None
        if exclude is not None and len(exclude):
            excl_sorted = np.sort(np.asarray(exclude, np.int64))
        t0 = time.perf_counter()
        part_scores = None
        if q_quant is not None:
            part_scores = self._int8_partition_scores(probe, q_quant)
            if observe:
                INT8_RERANK.inc()
        out_idx = np.empty((b, num), np.int64)
        out_scores = np.empty((b, num), np.float32)
        for r in range(b):
            parts = np.sort(probe[r])  # ordered slices walk memory forward
            cnt = int(counts[r])
            ids = np.empty(cnt, np.int32)
            scores = np.empty(cnt, np.float32)
            qrow = q[r]
            pos = 0
            bnds = self.offsets[parts].tolist()
            ubnds = self.offsets[parts + 1].tolist()
            for p, lo, hi in zip(parts.tolist(), bnds, ubnds):
                m = hi - lo
                if not m:
                    continue
                ids[pos:pos + m] = self.member_ids[lo:hi]
                if part_scores is not None:
                    # rows come off each partition's iterator in ascending
                    # query order — exactly this loop's visit order
                    scores[pos:pos + m] = next(part_scores[p])
                else:
                    scores[pos:pos + m] = \
                        self.emb_m[lo:hi] @ qrow + self.bias_m[lo:hi]
                pos += m
            if self.stale_ids is not None and len(self.stale_ids):
                ids, scores = self._apply_stale_overlay(ids, scores, qrow)
            scores += user_bias[r] + mean
            if excl_sorted is not None:
                pos = np.minimum(np.searchsorted(excl_sorted, ids),
                                 len(excl_sorted) - 1)
                scores[excl_sorted[pos] == ids] = -np.inf
            if row_mask is not None:
                scores += row_mask[r, ids]
            top = topk_row(scores, num)
            if not np.isfinite(scores[top[-1]]):
                # fewer than num candidates survived the rule filters in
                # THIS probe set — a masked (-inf) item would fill the
                # trailing slots where the exact path, seeing the whole
                # catalog, still has unmasked items to place. Fall back.
                if observe:
                    FALLBACKS.inc()
                return None
            out_idx[r] = ids[top]
            out_scores[r] = scores[top]
            if observe:
                CANDIDATES.observe(cnt)
        if observe:
            RERANK_SEC.observe(time.perf_counter() - t0)
            TWO_STAGE_BATCHES.inc()
        return out_idx, out_scores


# -- build -------------------------------------------------------------------

def _assign(x: np.ndarray, cent: np.ndarray,
            chunk: int = ASSIGN_CHUNK) -> np.ndarray:
    """Nearest-centroid (euclidean) assignment, chunked over rows."""
    half = 0.5 * np.einsum("cd,cd->c", cent, cent)
    out = np.empty(len(x), np.int32)
    for lo in range(0, len(x), chunk):
        d = x[lo:lo + chunk] @ cent.T
        d -= half[None, :]
        out[lo:lo + chunk] = np.argmax(d, axis=1)
    return out


def _kmeans(x: np.ndarray, c: int, iters: int,
            rng: np.random.Generator) -> np.ndarray:
    """Lloyd's k-means on (a sample of) the augmented rows. Per-dimension
    ``bincount`` accumulation keeps the update pass in C loops; empty
    clusters reseed from random rows so every centroid stays live."""
    cent = x[rng.choice(len(x), size=c, replace=False)].copy()
    d = x.shape[1]
    for _ in range(iters):
        a = _assign(x, cent)
        counts = np.bincount(a, minlength=c).astype(np.float64)
        for j in range(d):
            cent[:, j] = np.bincount(a, weights=x[:, j], minlength=c)
        live = counts > 0
        cent[live] /= counts[live, None]
        n_dead = int((~live).sum())
        if n_dead:
            cent[~live] = x[rng.choice(len(x), size=n_dead, replace=False)]
    return cent


def build_ivf(item_emb: np.ndarray, item_bias: np.ndarray,
              key: Optional[dict] = None) -> IVFIndex:
    """Cluster the catalog and lay out the member-order rerank tables.

    Deploy-time cost: k-means on a bounded sample plus ONE full-catalog
    assignment pass (chunked matmuls) — minutes at 10M rows, amortized over
    every query the deployment serves.
    """
    n, d = item_emb.shape
    key = dict(key if key is not None else build_key(n))
    if key.get("n_items") != n:
        key["n_items"] = n
    rng = np.random.default_rng(key["seed"])
    c = min(key["n_partitions"], max(1, n))
    t0 = time.perf_counter()
    item_emb = np.asarray(item_emb, np.float32)
    item_bias = np.asarray(item_bias, np.float32)
    aug = np.concatenate([item_emb, item_bias[:, None]], axis=1)
    sample = min(int(key["train_sample"]), n)
    train = aug if sample >= n else \
        aug[rng.choice(n, size=sample, replace=False)]
    c = min(c, len(train))  # can't seed more centroids than training rows
    cent = _kmeans(train, c, int(key["kmeans_iters"]), rng)
    assign = _assign(aug, cent)
    order = np.argsort(assign, kind="stable")
    sizes = np.bincount(assign, minlength=c)
    offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
    emb_m = np.ascontiguousarray(item_emb[order])
    index = IVFIndex(
        centroids=cent,
        member_ids=order.astype(np.int32),
        offsets=offsets,
        bias_m=np.ascontiguousarray(item_bias[order]),
        key=key,
    )
    if key["quantize"]:
        from incubator_predictionio_tpu.ops.retrieval import quantize_rows

        index.emb_q, index.scales_m = quantize_rows(emb_m)
    else:
        index.emb_m = emb_m
    index.build_seconds = time.perf_counter() - t0
    return index
