"""Axis-wise grouped top-k for batched serving.

ONE implementation of the batched selection chain, shared by every
template's ``batch_predict`` so the bitwise contract with the serial oracle
(``argpartition`` → ``argsort`` on the selected columns, numpy default
kinds) lives in exactly one place. Rows are grouped by their requested
``num`` and each group runs one vectorized ``axis=1`` pass — per-row
results are identical to running the serial chain row by row, including
tie resolution (introselect/quicksort are applied per 1-D slice either
way).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def topk_row(scores: np.ndarray, num: int) -> np.ndarray:
    """Top-``num`` indices of ONE 1-D score row, best-first — the same
    ``argpartition`` → ``argsort`` chain :func:`grouped_topk` runs axis-wise,
    so single-row consumers (the two-stage rerank) share the serial oracle's
    tie resolution instead of re-implementing the selection."""
    num = min(num, scores.shape[0])
    if num <= 0:
        return np.empty(0, np.int64)
    part = np.argpartition(-scores, num - 1)[:num]
    return part[np.argsort(-scores[part])]


def merge_topk(
    cand_ids: np.ndarray, cand_scores: np.ndarray, num: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Row-wise top-``num`` over gathered candidate lists (the cross-shard
    merge of sharded serving, and the fleet router's cross-PROCESS fan-in
    of shard-owner partials — docs/sharding.md "Multi-host shard owners"):
    ``cand_ids``/``cand_scores`` are ``[B, C]`` with each shard's
    candidates already best-first and shards concatenated in
    ascending-row-range order. Runs the same axis-wise
    ``argpartition`` → ``argsort`` chain as :func:`grouped_topk`, so merged
    results match the single-host serial oracle's selection (ids resolve
    through ``cand_ids``). Callers may pad short candidate lists with
    ``-inf`` scores; a padded slot can never displace a real candidate."""
    b, c = cand_scores.shape
    num = min(num, c)
    if num <= 0 or b == 0:
        return (np.empty((b, 0), cand_ids.dtype),
                np.empty((b, 0), cand_scores.dtype))
    part = np.argpartition(-cand_scores, num - 1, axis=1)[:, :num]
    row = np.arange(b)[:, None]
    order = np.argsort(-cand_scores[row, part], axis=1)
    top = np.take_along_axis(part, order, 1)
    return np.take_along_axis(cand_ids, top, 1), cand_scores[row, top]


def grouped_topk(
    scored: np.ndarray, nums: Sequence[int],
) -> list[tuple[np.ndarray, np.ndarray]]:
    """Per-row top-``nums[r]`` of ``scored[r]``, selection-parity with the
    serial ``argpartition(-s, num-1)[:num]`` → ``argsort`` chain.

    Returns one ``(indices, scores)`` pair per row, ordered best-first.
    ``num <= 0`` rows return empty results (templates normalize their
    serial paths the same way — a non-positive ``num`` is a degenerate
    query, not a catalog dump). Callers apply their own keep-predicates
    (finiteness, score cuts) on the returned score rows.
    """
    out: list[tuple[np.ndarray, np.ndarray]] = [None] * len(nums)  # type: ignore[list-item]
    empty = (np.empty(0, np.int64), np.empty(0, np.float32))
    by_num: dict[int, list[int]] = {}
    for r, num in enumerate(nums):
        if num <= 0:
            out[r] = empty
        else:
            by_num.setdefault(int(num), []).append(r)
    for num, rows in by_num.items():
        sub = scored[rows]
        part = np.argpartition(-sub, num - 1, axis=1)[:, :num]
        top_scores = np.take_along_axis(sub, part, 1)
        order = np.argsort(-top_scores, axis=1)
        top = np.take_along_axis(part, order, 1)
        top_scores = np.take_along_axis(top_scores, order, 1)
        for rr, r in enumerate(rows):
            out[r] = (top[rr], top_scores[rr])
    return out
