"""Compiled filter masks: per-item Python loops → vectorized index scatters.

Every business-rule filter reduces to the same primitive: a set of catalog
rows that must score -inf. The seed templates computed those sets with
per-item interpreter loops (the category filter iterated the whole
``item_map`` per query — O(catalog) Python); here the loops happen ONCE at
``prepare_for_serving`` when :class:`CategoryIndex` inverts the catalog's
category metadata, and query time is numpy scatters:

- category allow/ban → union of the precompiled per-category row arrays;
- white/black lists, seen items, unavailable items → ``BiMap.lookup_array``
  index scatters.

Mask values are exactly ``{0.0, -inf}`` and every filter only ever *bans*
(the whitelist bans non-members), so composition is order-free — the
vectorized masks are bitwise identical to the serial loops' output, which
the batched-vs-serial parity tests rely on.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Optional, Sequence

import numpy as np

from incubator_predictionio_tpu.data.bimap import BiMap

_NEG_INF = np.float32(-np.inf)

#: Bound on the per-index memoized union results (distinct category filter
#: tuples seen); serving traffic reuses a handful of filters, but the cache
#: must not grow without bound under adversarial query streams.
_UNION_CACHE_MAX = 256


class CategoryIndex:
    """Category → member catalog rows, inverted once from item metadata.

    The CSR-style structure behind vectorized category filtering: for each
    category, the sorted int32 array of catalog rows carrying it. A query's
    ``categories=(...)`` filter becomes the union of a few row arrays (OR
    over rows) instead of a per-item intersection test over the whole
    catalog.
    """

    __slots__ = ("n_rows", "_rows", "_union_cache")

    def __init__(self, id_map: BiMap, categories: Mapping[str, Sequence[str]]):
        self.n_rows = len(id_map)
        by_cat: dict[str, list[int]] = {}
        for iid, idx in id_map.items():
            for c in categories.get(iid, ()):
                by_cat.setdefault(c, []).append(idx)
        self._rows = {
            c: np.asarray(sorted(v), np.int32) for c, v in by_cat.items()
        }
        # memoized unions keyed by the (deduped, sorted) category tuple —
        # coalesced batches overwhelmingly repeat the same filter
        self._union_cache: dict[tuple[str, ...], np.ndarray] = {}

    def rows_with_any(self, cats: Iterable[str]) -> np.ndarray:
        """Sorted unique rows carrying ANY of ``cats`` (the OR over rows)."""
        key = tuple(sorted(set(cats)))
        hit = self._union_cache.get(key)
        if hit is not None:
            return hit
        arrs = [self._rows[c] for c in key if c in self._rows]
        rows = (np.unique(np.concatenate(arrs)) if arrs
                else np.empty(0, np.int32))
        if len(self._union_cache) >= _UNION_CACHE_MAX:
            self._union_cache.clear()
        self._union_cache[key] = rows
        return rows

    def allow_vec(self, cats: Iterable[str]) -> np.ndarray:
        """[n] f32 mask: 0 where the row has any of ``cats``, -inf elsewhere
        (the reference's ``categories`` filter: keep items intersecting)."""
        mask = np.full(self.n_rows, _NEG_INF, np.float32)
        mask[self.rows_with_any(cats)] = 0.0
        return mask

    def ban_vec(self, cats: Iterable[str]) -> np.ndarray:
        """[n] f32 mask: -inf where the row has any of ``cats``
        (``categoryBlackList``)."""
        mask = np.zeros(self.n_rows, np.float32)
        mask[self.rows_with_any(cats)] = _NEG_INF
        return mask


class HasCategoryIndex:
    """Mixin for serving models carrying ``item_map`` + ``categories``:
    one lazy, memoized :class:`CategoryIndex` build shared by every
    template model (eagerly compiled by each model's
    ``prepare_for_serving``, lazily on first direct-``predict`` use)."""

    _cat_index = None  # class default; instances memoize on first access

    def category_index(self) -> CategoryIndex:
        if self._cat_index is None:
            self._cat_index = CategoryIndex(self.item_map, self.categories)
        return self._cat_index


def whitelist_vec(id_map: BiMap, white_list: Sequence[str]) -> np.ndarray:
    """[n] f32 mask: 0 at whitelisted rows, -inf elsewhere (unknown ids are
    dropped, like the reference's flatten)."""
    n = len(id_map)
    allowed = id_map.lookup_array(white_list)
    mask = np.full(n, _NEG_INF, np.float32)
    mask[allowed[allowed >= 0]] = 0.0
    return mask


def ban_rows(mask: np.ndarray, id_map: BiMap,
             ids: Optional[Iterable[str]]) -> np.ndarray:
    """Scatter -inf into ``mask`` at the rows of ``ids`` (in place; unknown
    ids ignored). The vectorized form of the per-item ``.get`` loops."""
    if ids:
        idx = id_map.lookup_array(ids)
        mask[idx[idx >= 0]] = _NEG_INF
    return mask
