"""Two-tower matrix factorization — the MLlib ALS replacement.

The reference recommendation template trains Spark MLlib ALS
(tests/pio_tests/engines/recommendation-engine/src/main/scala/ALSAlgorithm.scala:50-93)
producing a MatrixFactorizationModel. Here: embedding towers trained by
minibatch gradient descent on the mesh (the ALX paper, arxiv 2112.02194,
shards exact ALS the same way — we choose SGD because it lets one jit program
serve explicit *and* implicit feedback and fuses into two MXU matmuls per
step).

TPU mapping:
- user/item embedding tables live sharded over the ``model`` axis (row
  sharding, PartitionSpec("model", None)) — the table is the big tensor here,
  and row sharding keeps gather traffic local-ish while XLA inserts the
  all-gathers it needs;
- the rating minibatch is sharded over ``data``; gradient psum rides ICI;
- per-step compute is two gathers + fused dot-products in bfloat16 on the
  MXU, with float32 accumulation for the loss and the adam state;
- scoring a user against the full catalog is one [k] × [k, n_items] matmul +
  ``lax.top_k`` — the serving path stays on-device end to end.

Static shapes: triples padded to a whole number of global batches with
zero-weight rows.
"""

from __future__ import annotations

import dataclasses
import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from incubator_predictionio_tpu.parallel.mesh import MeshContext


@dataclasses.dataclass(frozen=True)
class TwoTowerConfig:
    rank: int = 32                  # ALS "rank" (ALSAlgorithm.scala params)
    learning_rate: float = 3e-2
    reg: float = 1e-4               # ALS "lambda"
    epochs: int = 20                # ALS "numIterations"
    batch_size: int = 8192          # global batch
    implicit_negatives: int = 0     # >0 → implicit mode with sampled negatives
    seed: int = 0
    # mid-training checkpoint/resume (utils/checkpoint.py); 0 = off
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0       # epochs between checkpoints
    checkpoint_keep: int = 3
    # adam moment STORAGE dtype ("float32" | "bfloat16"): bf16 moments cut
    # the dense-adam HBM traffic from 6 to 4 fp32-equivalent table passes
    # per step (~33% on the bandwidth-bound scaled config); math stays fp32
    # (utils/optim.adam_apply; parity: tests/test_optim_parity.py)
    adam_moments_dtype: str = "float32"
    # model finalize: "host" pulls the trained tables to host numpy (the
    # round-3 path — one full-table transfer, tens of seconds for production
    # tables behind a device tunnel); "device" keeps them resident as jax
    # Arrays (persisted as sharded orbax checkpoints, served without ever
    # touching host); "auto" picks device for single-process runs whose
    # CATALOG exceeds HOST_SERVE_MAX_ELEMENTS — the same criterion the
    # serving path uses, so device residency and device serving agree
    gather: str = "auto"


#: Micro-batch bucket ladder for serving: every request batch is padded up to
#: the next bucket so the jitted scorers see a handful of static shapes
#: instead of one per batch size (the round-2 compile-churn bug). Beyond the
#: largest bucket, batches round up to a multiple of it.
SERVE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

#: Catalogs with ≤ this many table elements (rows × columns) serve from HOST
#: numpy instead of the device: scoring a 3.7k-item catalog is ~100 µs of
#: numpy, while EVERY device call pays a dispatch/result round trip — sub-ms
#: on a local PCIe chip but tens of ms behind a device tunnel. Big catalogs
#: amortize the round trip over real MXU work and stay on device.
HOST_SERVE_MAX_ELEMENTS = 2_000_000

#: Per-row rule masks are DENSE [batch, n_items] f32 — the host build +
#: device transfer scales with batch × catalog, so the row-mask path (and
#: its deploy-time warmup) is limited to batches where that mask stays
#: modest (≤ this many elements, 32 MB f32). Above it, callers fall back to
#: shared-exclude / over-fetch semantics and warmup skips the row-mask
#: executables (which are then never dispatched — the compile-count gauge
#: stays flat either way).
ROW_MASK_MAX_ELEMENTS = 8_000_000


def serve_bucket(b: int) -> int:
    """Smallest bucket ≥ ``b`` (multiples of the top bucket past the ladder)."""
    for s in SERVE_BUCKETS:
        if b <= s:
            return s
    top = SERVE_BUCKETS[-1]
    return ((b + top - 1) // top) * top


@dataclasses.dataclass
class TwoTowerModel:
    """user/item factor tables + biases + global mean.

    Two residency modes:

    - **host** (the reference-shaped path): ``user_emb``/``item_emb``/biases
      are host numpy; pickles into MODELDATA like Kryo blobs do.
    - **device** (``TwoTowerConfig.gather="device"``/big-table auto): the
      fused padded tables stay resident as jax Arrays in ``_tables``
      ({"ue": [nu_p, k+1], "ie": [ni_p, k+1]}, possibly "model"-axis
      sharded); the host fields are ``None`` until :meth:`ensure_host`.
      Persistence goes through sharded orbax checkpoints
      (templates/recommendation.py RecModel.save), never a host gather.
    """

    user_emb: Optional[np.ndarray] = None    # [n_users, k]
    item_emb: Optional[np.ndarray] = None    # [n_items, k]
    user_bias: Optional[np.ndarray] = None   # [n_users]
    item_bias: Optional[np.ndarray] = None   # [n_items]
    mean: float = 0.0
    config: TwoTowerConfig = dataclasses.field(default_factory=TwoTowerConfig)

    _tables = None  # device-resident fused tables (device mode)
    _n_users = 0  # real (unpadded) row counts in device mode
    _n_items = 0
    _device_items = None  # (item_embᵀ bf16, item_bias, zero mask) for serving
    _device_items_q = None  # int8-quantized catalog (pallas retrieval kernel)
    _device_users = None  # (user_emb bf16, user_bias) — gathered inside jit
    _host_items = None  # small-catalog host fast path (item_embᵀ, item_bias)
    _serve_k = 0  # static top-k the serving executables are compiled for
    # two-stage retrieval index (serving/ann.py). Unlike the device handles
    # it IS host numpy and rides default pickling, so a persisted model
    # redeploys without re-clustering the catalog
    _ivf = None
    # sharded serving state (sharding/serve.py): per-shard top-k + merge
    # replaces the single-host scorers when the model-axis layout is a win.
    # Derived at prepare time — never serialized (deploy rebuilds it)
    _sharded = None
    # per-shard IVF partitions (one slim-pickling IVFIndex per shard) and
    # the training shard layout — both host-picklable, both persisted so a
    # sharded redeploy skips the per-shard re-cluster
    _shard_ivf = None
    _shard_spec = None

    @property
    def device_resident(self) -> bool:
        return self._tables is not None

    def ensure_host(self) -> "TwoTowerModel":
        """Materialize the host numpy views (one full-table device→host pull
        — the transfer device mode exists to avoid; only consumers that
        genuinely need host arrays, e.g. cosine-similarity model builds or
        default pickling, should ever land here)."""
        if self.user_emb is not None or self._tables is None:
            return self
        from incubator_predictionio_tpu.sharding import shard_metrics

        shard_metrics.FULL_GATHERS.inc()
        k = self.config.rank
        host = jax.device_get(self._tables)
        self.user_emb = np.ascontiguousarray(host["ue"][: self._n_users, :k])
        self.user_bias = np.ascontiguousarray(host["ue"][: self._n_users, k])
        self.item_emb = np.ascontiguousarray(host["ie"][: self._n_items, :k])
        self.item_bias = np.ascontiguousarray(host["ie"][: self._n_items, k])
        return self

    def __getstate__(self):
        # default pickling (MODELDATA blob) always ships host arrays; device
        # handles and serving buffers never serialize — deploy rebuilds them
        # (the sharded serving state may hold device arrays; its host-only
        # inputs — _shard_ivf, _shard_spec — do persist)
        self.ensure_host()
        return {k: v for k, v in self.__dict__.items()
                if k not in ("_tables", "_device_items", "_device_items_q",
                             "_device_users", "_host_items", "_sharded")}

    def prepare_for_serving(
        self, quantize: bool = False, serve_k: int = 128,
        host_max_elements: Optional[int] = None, build_index: bool = True,
    ) -> "TwoTowerModel":
        """Make serving state resident for the query hot path.

        Catalogs up to :data:`HOST_SERVE_MAX_ELEMENTS` serve from host numpy
        — scoring a few-thousand-item catalog is microseconds of numpy and
        paying a device round trip per query only adds latency. Bigger
        catalogs go device-resident; ``quantize=True`` additionally stores
        the catalog int8 row-quantized and scores through the fused Pallas
        retrieval kernel (ops/retrieval.py) — 4× less HBM for the item table
        and a faster score pass on TPU.

        ``serve_k`` fixes the static top-k the device executables compute:
        queries asking ``num ≤ serve_k`` share ONE executable per batch bucket
        (results sliced host-side), so per-query ``num`` never recompiles.

        When two-stage retrieval is enabled for this catalog
        (``PIO_RETRIEVAL_MODE``, serving/ann.py) this also builds — or
        reuses, when a persisted index's build key still matches — the IVF
        partition the coarse stage probes; the exact buffers above stay
        resident as the fallback and recall oracle. ``build_index=False``
        opts out — for callers (the ecommerce/similarity templates) whose
        serving path never goes through :meth:`TwoTowerMF.recommend_batch`
        and would pay the clustering for nothing."""
        self._prepare_scoring(quantize, serve_k, host_max_elements)
        if build_index:
            self._prepare_index()
        return self

    def _prepare_index(self) -> None:
        """Build/reuse the two-stage IVF partition (serving/ann.py)."""
        from incubator_predictionio_tpu.serving import ann

        if not ann.two_stage_enabled(self.n_items):
            # keep any persisted index around: flipping the mode knob back
            # shouldn't force a re-cluster on the next prepare
            return
        if self._sharded is not None:
            # composed sharded two-stage: each shard clusters its LOCAL
            # rows (shard-at-a-time pulls — the full item table is never
            # materialized on one host); persisted per-shard indexes are
            # reused when their build keys still match
            self._shard_ivf = self._sharded.ensure_ivf(
                self, persisted=self._shard_ivf)
            return
        from incubator_predictionio_tpu.sharding import serve as shard_serve

        shard_ivf = shard_serve.train_time_shard_ivf(
            self, persisted=self._shard_ivf)
        if shard_ivf is not None:
            # train-time build for a model that will SERVE sharded: the
            # per-shard clustering persists with the model, so redeploys
            # skip the re-cluster — and the full table is never gathered
            self._shard_ivf = shard_ivf
            return
        key = ann.build_key(self.n_items)
        if self._ivf is not None and self._ivf.matches(key):
            if not self._ivf.hydrated:
                # persisted slim (clustering only): one O(N) gather rebuilds
                # the member-order rerank tables — the k-means is skipped
                self._ivf.rehydrate(*self._host_item_table())
            return
        self._ivf = ann.build_ivf(*self._host_item_table(), key=key)

    def _host_item_table(self) -> tuple[np.ndarray, np.ndarray]:
        """Host ``(item_emb, item_bias)`` WITHOUT materializing the full
        host views: ``ensure_host`` would also pull the user table and set
        ``user_emb``, flipping a device-gather model off its
        device-to-device serving-prep fast path for good. The index build
        only needs the item side."""
        if self.item_emb is not None:
            return (np.asarray(self.item_emb, np.float32),
                    np.asarray(self.item_bias, np.float32))
        from incubator_predictionio_tpu.sharding import shard_metrics

        shard_metrics.FULL_GATHERS.inc()
        k = self.config.rank
        host_ie = np.asarray(jax.device_get(self._tables["ie"]))
        return (np.ascontiguousarray(host_ie[: self._n_items, :k],
                                     dtype=np.float32),
                np.ascontiguousarray(host_ie[: self._n_items, k],
                                     dtype=np.float32))

    def _prepare_scoring(
        self, quantize: bool = False, serve_k: int = 128,
        host_max_elements: Optional[int] = None,
    ) -> "TwoTowerModel":
        self._serve_k = min(serve_k, self.n_items)
        # re-preparation switches paths cleanly: clear every serving buffer
        # first (a stale _host_items would shadow a requested device path)
        self._host_items = None
        self._device_items = None
        self._device_items_q = None
        self._device_users = None
        self._sharded = None
        host_max = (HOST_SERVE_MAX_ELEMENTS if host_max_elements is None
                    else host_max_elements)
        # sharded serving (sharding/serve.py): per-shard top-k + cross-shard
        # merge straight from the model-axis layout. auto engages when the
        # tables restored sharded (or the simulated HBM budget says one chip
        # can't hold the catalog) AND the catalog is device-scale;
        # PIO_SHARD_SERVE=1 forces it (host models get virtual shards).
        # serving_shards_for is the ONE engage decision (train-time IVF
        # build and restore layout use it too)
        from incubator_predictionio_tpu.sharding import serve as shard_serve

        n_shards = shard_serve.serving_shards_for(
            self, host_max_elements=host_max)
        if n_shards > 1:
            self._build_sharded(n_shards)
            return self
        # host check first: ``quantize`` applies to device-resident catalogs;
        # a catalog small enough for the host path never benefits from it
        if self.n_items * (self.config.rank + 1) <= host_max:
            self.ensure_host()  # no-op unless forced device mode on tiny tables
            self._host_items = (
                np.ascontiguousarray(np.asarray(self.item_emb, np.float32).T),
                np.asarray(self.item_bias, np.float32),
            )
            return self
        if self.device_resident and self.user_emb is None:
            # device→device: slice/cast the resident fused tables — serving
            # state is derived without a single host round trip (the whole
            # point of gather="device")
            k = self.config.rank
            ue, ie = self._tables["ue"], self._tables["ie"]
            self._device_users = (
                ue[: self._n_users, :k].astype(jnp.bfloat16),
                ue[: self._n_users, k].astype(jnp.float32),
            )
            item_emb = ie[: self._n_items, :k]
            item_bias = ie[: self._n_items, k]
            if quantize:
                from incubator_predictionio_tpu.ops.retrieval import (
                    quantize_catalog_device,
                )

                self._device_items_q = tuple(
                    quantize_catalog_device(item_emb, item_bias))
            else:
                self._device_items = (
                    item_emb.T.astype(jnp.bfloat16),
                    item_bias.astype(jnp.float32),
                    jnp.zeros(self._n_items, jnp.float32),
                )
            return self
        self._device_users = (
            jax.device_put(np.asarray(self.user_emb, np.float32).astype(jnp.bfloat16)),
            jax.device_put(np.asarray(self.user_bias, np.float32)),
        )
        if quantize:
            from incubator_predictionio_tpu.ops.retrieval import (
                pad_catalog,
                quantize_rows,
            )

            items_q, scales = quantize_rows(np.asarray(self.item_emb))
            base_mask = np.zeros(self.n_items, np.float32)
            items_q, scales, bias, mask = pad_catalog(
                items_q, scales, np.asarray(self.item_bias, np.float32), base_mask
            )
            self._device_items_q = tuple(
                jax.device_put(v) for v in (items_q, scales, bias, mask)
            )
        else:
            self._device_items = (
                jax.device_put(
                    np.ascontiguousarray(
                        np.asarray(self.item_emb, np.float32).T
                    ).astype(jnp.bfloat16)
                ),
                jax.device_put(np.asarray(self.item_bias, np.float32)),
                jax.device_put(np.zeros(self.n_items, np.float32)),
            )
        return self

    def _build_sharded(self, n_shards: int) -> None:
        """Materialize the per-shard serving state (sharding/serve.py):
        device-resident models derive it device-to-device from the sharded
        tables (the item table never visits the host); host models split
        into virtual shard blocks (the CPU-parity twin)."""
        import jax

        from incubator_predictionio_tpu.sharding.serve import ShardedServing

        serve_k = self._serve_k or min(128, self.n_items)
        if self.device_resident and self.user_emb is None:
            n_shards = min(n_shards, len(jax.devices()))
            self._sharded = ShardedServing.build_device(
                self._tables, self._n_users, self._n_items,
                self.config.rank, self.mean, serve_k, n_shards)
        else:
            self._sharded = ShardedServing.build_host(
                np.asarray(self.item_emb, np.float32),
                np.asarray(self.item_bias, np.float32),
                self.n_users, self.mean, serve_k, n_shards)

    def warmup(self, max_batch: int = 64) -> int:
        """Pre-compile the serving executable for every batch bucket up to
        ``max_batch`` (deploy-time cost, so no live query ever waits on XLA).
        Returns the number of buckets warmed (0 on the host fast path —
        nothing compiles there)."""
        if (self._device_users is None and self._host_items is None
                and self._sharded is None):
            self.prepare_for_serving()
        from incubator_predictionio_tpu.serving import ann

        has_ivf = self._ivf is not None or (
            self._sharded is not None and any(self._sharded.ivf or ()))
        n = 0
        if has_ivf and ann.two_stage_enabled(self.n_items):
            # prime the two-stage path too: on host no XLA is involved (the
            # coarse + rerank stages are numpy), but the first dispatch
            # faults the member-order tables into memory and spins up the
            # BLAS thread pool — deploy-time cost, not the first live
            # query's
            k = min(max(self._serve_k, 1), self.n_items)
            TwoTowerMF.recommend_batch(self, np.zeros(1, np.int32), k)
            quantized = (self._ivf is not None and self._ivf.quantized) or (
                self._sharded is not None
                and any(i is not None and i.quantized
                        for i in self._sharded.ivf or ()))
            if quantized and jax.default_backend() == "tpu":
                # the int8 coarse kernel pads queries to power-of-two
                # buckets (serving/ann._probe_tpu): compile each bucket's
                # `ivf_coarse_int8` executable now so no live batch shape
                # pays it (jitstats names them; the batch-1 prime above
                # already built the ≤8 bucket)
                seen = {8}
                for b in SERVE_BUCKETS:
                    if b > max(1, max_batch):
                        break
                    bp = 1 << max(3, (b - 1).bit_length())
                    if bp in seen:
                        continue
                    seen.add(bp)
                    TwoTowerMF.recommend_batch(
                        self, np.zeros(b, np.int32), k)
                    n += 1
        if self._host_items is not None or (
                self._sharded is not None and self._sharded.device is None):
            # pure-numpy serving paths: nothing compiles
            return 0
        for b in SERVE_BUCKETS:
            if b > max(1, max_batch):
                break
            # _force_exact: with two-stage retrieval active these warmup
            # dispatches would route to the (host-side) pruned path and the
            # exact executables — the two-stage FALLBACK — would compile on
            # the first live query that needs them
            TwoTowerMF.recommend_batch(
                self, np.zeros(b, np.int32), self._serve_k or 1,
                _force_exact=True,
            )
            # the rule-filtered variant ([b, n] row mask) is a distinct
            # executable — warm it too so the first filtered live batch
            # doesn't pay an XLA compile. Only under ROW_MASK_MAX_ELEMENTS:
            # beyond it serving never dispatches the row-mask form (callers
            # fall back to shared-exclude/over-fetch), and warming it would
            # cost a batch×catalog host allocation + transfer per bucket
            if b * self.n_items <= ROW_MASK_MAX_ELEMENTS:
                TwoTowerMF.recommend_batch(
                    self, np.zeros(b, np.int32), self._serve_k or 1,
                    row_mask=np.zeros((b, self.n_items), np.float32),
                    _force_exact=True,
                )
            n += 1
        return n

    @property
    def n_items(self) -> int:
        return self._n_items if self.item_emb is None else self.item_emb.shape[0]

    @property
    def n_users(self) -> int:
        return self._n_users if self.user_emb is None else self.user_emb.shape[0]

    def with_row_updates(
        self,
        user_rows: Optional[dict] = None,
        item_rows: Optional[dict] = None,
    ) -> "TwoTowerModel":
        """A NEW model with the given fused ``[rank+1]`` rows scattered in
        — the streaming delta-apply primitive (docs/streaming.md).

        Build-beside semantics: the receiver is NEVER mutated (it may be
        the live serving model, or the probation-pinned previous one), so
        the tables are copied, rows assigned, and the caller swaps the new
        model in atomically — serving can't observe a half-applied table.

        Two-stage index staleness: item rows that moved are overlaid on
        the IVF index (:meth:`serving.ann.IVFIndex.with_updated_rows`) so
        the pruned path rescopes them with CURRENT values; past
        ``PIO_STREAM_STALE_REBUILD_FRAC`` of the catalog stale, the index
        is re-clustered from the updated table instead.

        Sharded models route each row to its OWNING shard
        (sharding/serve.py) — only that shard's arrays (and its IVF
        overlay) rebuild; a device-resident sharded model never pulls its
        tables to host for a delta."""
        if self._sharded is not None and self.user_emb is None:
            return self._with_row_updates_sharded(user_rows, item_rows)
        self.ensure_host()
        k = self.config.rank
        new = TwoTowerModel(
            user_emb=np.array(self.user_emb, np.float32, copy=True),
            item_emb=np.array(self.item_emb, np.float32, copy=True),
            user_bias=np.array(self.user_bias, np.float32, copy=True),
            item_bias=np.array(self.item_bias, np.float32, copy=True),
            mean=self.mean,
            config=self.config,
        )

        def scatter(emb, bias, rows, n):
            for idx, row in rows.items():
                idx = int(idx)
                if not (0 <= idx < n):
                    raise ValueError(f"delta row index {idx} outside "
                                     f"[0, {n})")
                row = np.asarray(row, np.float32)
                if row.shape != (k + 1,):
                    raise ValueError(
                        f"delta row shape {row.shape} != ({k + 1},)")
                emb[idx] = row[:k]
                bias[idx] = row[k]

        if user_rows:
            scatter(new.user_emb, new.user_bias, user_rows, new.n_users)
        if item_rows:
            scatter(new.item_emb, new.item_bias, item_rows, new.n_items)
        if self._ivf is not None:
            if item_rows:
                new._ivf = self._updated_index(new, item_rows)
            else:
                new._ivf = self._ivf  # shared read-only: nothing moved
        if self._sharded is not None:
            # host-block sharded serving: route the rows to their owning
            # shard's blocks/IVF overlay; untouched shards stay shared.
            # _shard_ivf only follows when serving actually carries per-
            # shard indexes — with two-stage currently off the persisted
            # clustering must survive for a later mode flip
            new._sharded = self._sharded.with_row_updates(
                user_rows or {}, item_rows or {})
            new._shard_ivf = (new._sharded.ivf
                              if new._sharded.ivf is not None
                              else self._shard_ivf)
            new._shard_spec = self._shard_spec
            new._serve_k = self._serve_k
        return new

    def _with_row_updates_sharded(
        self,
        user_rows: Optional[dict] = None,
        item_rows: Optional[dict] = None,
    ) -> "TwoTowerModel":
        """Build-beside delta apply for a device-resident sharded model:
        rows scatter into copies of the sharded tables ON DEVICE (XLA
        routes each row to its owner — batch-sized traffic only) and the
        serving state updates through the owning shard; the receiver keeps
        serving its own arrays untouched."""
        import jax.numpy as jnp

        from incubator_predictionio_tpu.sharding.serve import _set_rows_fn

        new = TwoTowerModel(mean=self.mean, config=self.config)
        new._n_users, new._n_items = self._n_users, self._n_items
        new._serve_k = self._serve_k
        new._shard_spec = self._shard_spec
        new._sharded = self._sharded.with_row_updates(
            user_rows or {}, item_rows or {})
        if self._tables is not None:
            # keep the persistable tables coherent with what serving
            # answers (a later save/pickle must not resurrect old rows).
            # No re-validation here: ShardedServing.with_row_updates above
            # already range/width-checked every row — one checker, one
            # error message
            tables = dict(self._tables)
            for name, rows_dict in (("ue", user_rows), ("ie", item_rows)):
                if not rows_dict:
                    continue
                ids = np.asarray(sorted(int(i) for i in rows_dict), np.int64)
                rows = np.stack([np.asarray(rows_dict[int(i)], np.float32)
                                 for i in ids])
                tables[name] = _set_rows_fn()(
                    tables[name], jnp.asarray(ids, jnp.int32),
                    jnp.asarray(rows))
            new._tables = tables
        if item_rows and new._tables is not None:
            # past the staleness threshold a shard re-clusters from the
            # UPDATED tables (the overlay must not grow without bound)
            new._sharded.rebuild_stale_ivf(new)
        new._shard_ivf = (new._sharded.ivf if new._sharded.ivf is not None
                          else self._shard_ivf)
        if self._ivf is not None:
            # a persisted whole-catalog index survives for a later
            # retrieval/sharding mode flip — with the moved rows overlaid
            # so an in-process flip never serves pre-delta embeddings
            # (the host path's _updated_index semantics, minus its
            # rebuild-past-threshold branch, which needs host towers)
            if item_rows:
                ids = np.asarray(sorted(int(i) for i in item_rows), np.int64)
                rows = np.stack([np.asarray(item_rows[int(i)], np.float32)
                                 for i in ids])
                k = self.config.rank
                new._ivf = self._ivf.with_updated_rows(
                    ids, rows[:, :k], rows[:, k])
            else:
                new._ivf = self._ivf
        return new

    def _updated_index(self, new: "TwoTowerModel", item_rows: dict):
        """Overlay the moved item rows on the shared IVF index, or rebuild
        past the staleness threshold."""
        import os as _os

        from incubator_predictionio_tpu.serving import ann

        ids = np.asarray(sorted(int(i) for i in item_rows), np.int64)
        rows = np.stack([np.asarray(item_rows[int(i)], np.float32)
                         for i in ids])
        k = self.config.rank
        overlaid = self._ivf.with_updated_rows(ids, rows[:, :k], rows[:, k])
        frac = float(_os.environ.get("PIO_STREAM_STALE_REBUILD_FRAC", "0.25"))
        if overlaid.stale_fraction > frac and ann.two_stage_enabled(
                new.n_items):
            return ann.build_ivf(
                np.asarray(new.item_emb, np.float32),
                np.asarray(new.item_bias, np.float32),
                key=ann.build_key(new.n_items))
        return overlaid

    def serving_info(self) -> dict:
        """Which serving path this model runs (status-page observability)."""
        if self._sharded is not None:
            path = ("sharded-device-bf16" if self._sharded.device is not None
                    else "sharded-host-numpy")
        elif self._device_items_q is not None:
            path = "device-int8-pallas"
        elif self._device_items is not None:
            path = "device-bf16"
        elif self._host_items is not None:
            path = "host-numpy"
        else:
            path = "unprepared"
        from incubator_predictionio_tpu.serving import ann

        has_index = self._ivf is not None or (
            self._sharded is not None and any(self._sharded.ivf or ()))
        two_stage = has_index and ann.two_stage_enabled(self.n_items)
        if self._ivf is not None:
            index = self._ivf.stats()
        elif self._sharded is not None and self._sharded.ivf:
            index = [i.stats() if i is not None else None
                     for i in self._sharded.ivf]
        else:
            index = None
        return {"path": path, "serve_k": self._serve_k,
                "catalog_rows": self.n_items,
                "retrieval_mode": "two_stage" if two_stage else "exact",
                "sharding": (self._sharded.info()
                             if self._sharded is not None else None),
                "index": index}

    def shard_info(self) -> dict:
        """Shard layout for ``pio-tpu shards``: the live serving layout
        when sharded serving is active, else the training-layout record
        (or the single-chip plan) plus what the current simulated HBM
        budget implies."""
        from incubator_predictionio_tpu.sharding.table import (
            ShardSpec,
            hbm_budget,
            requires_sharding,
        )

        k = self.config.rank
        if self._sharded is not None:
            info = self._sharded.info()
            info["sharded"] = True
            return info
        spec = self._shard_spec or {
            "ue": ShardSpec("ue", self.n_users, k + 1, 1),
            "ie": ShardSpec("ie", self.n_items, k + 1, 1),
        }
        return {
            "sharded": False,
            "n_shards": spec["ie"].n_shards,
            "items": spec["ie"].to_dict(),
            "users": spec["ue"].to_dict(),
            "hbm_budget": hbm_budget(),
            "requires_sharding": requires_sharding(
                self.n_items, k + 1, self.config.adam_moments_dtype),
        }


class TwoTowerMF:
    def __init__(self, config: TwoTowerConfig = TwoTowerConfig()):
        self.config = config

    def fit(
        self,
        ctx: MeshContext,
        users: np.ndarray,     # [n] int32 user indices
        items: np.ndarray,     # [n] int32 item indices
        ratings: np.ndarray,   # [n] float32
        n_users: int,
        n_items: int,
        rows_are_local: bool = False,
    ) -> TwoTowerModel:
        """``rows_are_local=True``: the given triples are only THIS process's
        entity-disjoint shard (indices already global); batches are assembled
        per process and joined into global arrays via
        ``make_array_from_process_local_data`` — host memory is data/P per
        process instead of a full replica (reference counterpart: RDD
        partition reads, PEvents.scala:38)."""
        import time as _time

        cfg = self.config
        n = len(users)
        if not (len(items) == len(ratings) == n):
            raise ValueError("users/items/ratings must be equal length")

        t_stage = _time.perf_counter()
        if rows_are_local and ctx.process_count > 1:
            ub, ib, rb, wb, mean = self._stage_local(
                ctx, users, items, ratings)
        else:
            mean = float(ratings.mean()) if n else 0.0
            global_batch = ctx.pad_to_batch_multiple(
                min(cfg.batch_size, max(n, 1)))
            n_batches = max(1, (n + global_batch - 1) // global_batch)
            n_pad = n_batches * global_batch
            rng = np.random.default_rng(cfg.seed)
            perm = rng.permutation(n)
            pad_idx = rng.integers(0, max(n, 1), n_pad - n)
            order = np.concatenate([perm, pad_idx])
            w = np.concatenate(
                [np.ones(n, np.float32), np.zeros(n_pad - n, np.float32)])
            order, w = _sort_batches_by_entity(
                order, w, np.asarray(users, np.int32),
                n_batches, global_batch)

            def stage(a, dtype):
                a = np.asarray(a, dtype)[order] if len(a) == n else np.asarray(a, dtype)
                a = a.reshape(n_batches, global_batch)
                return ctx.put(a, None, ctx.data_axis)

            ub = stage(users, np.int32)
            ib = stage(items, np.int32)
            rb = stage(ratings.astype(np.float32) - mean, np.float32)
            wb = ctx.put(w.reshape(n_batches, global_batch), None, ctx.data_axis)

        # phase fence: staging transfers (h2d) must bill to this phase,
        # not to whichever later phase first blocks on the batches
        jax.block_until_ready((ub, ib, rb, wb))
        t_stage = _time.perf_counter() - t_stage
        t_init = _time.perf_counter()
        key = jax.random.key(cfg.seed)
        ku, ki = jax.random.split(key)
        scale = 1.0 / np.sqrt(cfg.rank)
        # biases live as the LAST COLUMN of each table: TPU gathers operate
        # on rows — a separate 1-D bias table means 65k scalar gathers per
        # step, measured ~3× the cost of the whole [B, rank] row gather.
        #
        # The tables materialize through ShardedTable (sharding/table.py):
        # rows padded to the model-axis multiple and row-sharded via
        # NamedSharding, init ON DEVICE with per-shard keys directly into
        # that layout (a 1M×129 table round-tripped through the host costs
        # ~GB of transfer for pure noise), and PIO_SHARD_HBM_BUDGET
        # enforced per shard — the simulated stand-in for a real chip's
        # OOM, so a CPU dryrun can prove the doesn't-fit-one-chip case.
        from incubator_predictionio_tpu.sharding.table import ShardedTable

        ut = ShardedTable.init_train(
            ctx, "ue", n_users, cfg.rank, ku, scale, cfg.adam_moments_dtype)
        it = ShardedTable.init_train(
            ctx, "ie", n_items, cfg.rank, ki, scale, cfg.adam_moments_dtype)
        params = {"ue": ut.array, "ie": it.array}
        # jitted init: multi-process-safe (optimizer state inherits the
        # params' global shardings instead of materializing host-side)
        from incubator_predictionio_tpu.utils.optim import adam_tree_init

        opt_state = adam_tree_init(params, cfg.adam_moments_dtype)

        from incubator_predictionio_tpu.utils.checkpoint import checkpointed_epochs

        # phase fence: on-device table/moment init bills to init
        jax.block_until_ready((params, opt_state))
        t_init = _time.perf_counter() - t_init
        t_train = _time.perf_counter()
        # distributed members checkpoint by owned slice and fence-check at
        # every chunk boundary (DistContext.dist_hooks); a plain ctx has no
        # hooks and trains exactly as before
        dist = getattr(ctx, "dist_hooks", None)
        params, opt_state, loss = checkpointed_epochs(
            cfg.checkpoint_dir, cfg.checkpoint_every, cfg.checkpoint_keep,
            cfg.epochs, params, opt_state, ctx.mesh,
            lambda p, o, n: _train_epochs(
                p, o, ub, ib, rb, wb, cfg.learning_rate, cfg.reg, n
            ),
            factory=None if dist is None else dist.checkpointer_factory,
            on_chunk=None if dist is None else dist.on_chunk,
        )
        if loss is None:
            loss = np.inf
        else:
            loss = float(loss)  # blocks: the train schedule is done here
        t_train = _time.perf_counter() - t_train
        t_gather = _time.perf_counter()
        # auto keys on the CATALOG size — the same criterion
        # prepare_for_serving uses to pick host vs device serving. Keying on
        # user+item would keep a user-heavy/small-catalog model on device
        # only for deploy to take the host serving path and pay the full
        # user-table pull anyway (plus a pointless giant checkpoint)
        # UNPADDED count: prepare_for_serving's host-path check keys on
        # n_items, so keying auto on the padded ni_p would leave catalogs in
        # the padding band device-resident (orbax checkpoint and all) only
        # for deploy to take the host path anyway (round-4 advisor finding)
        item_elems = n_items * (cfg.rank + 1)
        keep_device = cfg.gather == "device" or (
            cfg.gather == "auto" and item_elems > HOST_SERVE_MAX_ELEMENTS)
        if keep_device and ctx.process_count > 1:
            # persistence is primary-only (core_workflow.py) but an orbax
            # save of process-spanning arrays would need every process —
            # multi-process runs keep the collective host gather
            keep_device = False
        if keep_device:
            # device-resident finalize: the trained tables never leave HBM.
            # block_until_ready only drains the train schedule — the
            # full-table device→host transfer (tens of seconds behind a
            # device tunnel for production tables) is gone entirely
            jax.block_until_ready(params)
            model = TwoTowerModel(mean=mean, config=cfg)
            model._tables = {"ue": params["ue"], "ie": params["ie"]}
            model._n_users = n_users
            model._n_items = n_items
            # layout record: what `pio-tpu shards` and sharded serving read
            model._shard_spec = {"ue": ut.spec, "ie": it.spec}
            t_gather = _time.perf_counter() - t_gather
        else:
            # host gather (collective when multi-process); behind a device
            # tunnel this transfer can dwarf the train loop for big tables,
            # so the phases are reported separately on the model
            host = ctx.host_gather(params)
            t_gather = _time.perf_counter() - t_gather
            model = TwoTowerModel(
                user_emb=host["ue"][:n_users, :cfg.rank],
                item_emb=host["ie"][:n_items, :cfg.rank],
                user_bias=host["ue"][:n_users, cfg.rank],
                item_bias=host["ie"][:n_items, cfg.rank],
                mean=mean,
                config=cfg,
            )
        model.final_loss = float(loss)
        model.timings = {
            "stage_sec": round(t_stage, 4),
            "init_sec": round(t_init, 4),
            "train_sec": round(t_train, 4),
            "gather_sec": round(t_gather, 4),
        }
        # continuous performance plane: the same four timers feed the
        # profiler's train.fit phase buckets (h2d staging / device init /
        # fused train loop / host|collective gather) and the analytic-flops
        # MFU gauge (docs/observability.md "Profiling")
        from incubator_predictionio_tpu.obs import profile as _profile

        _profile.record_phases("train.fit", {
            "h2d": t_stage, "init": t_init,
            "compute": t_train, "gather": t_gather,
        })
        n_b, g_batch = int(ub.shape[0]), int(ub.shape[1])
        n_params = (n_users + n_items) * (cfg.rank + 1)
        _profile.record_training_step(
            cfg.epochs * n_b * (12 * cfg.rank * g_batch + 12 * n_params),
            t_train)
        return model

    def _stage_local(self, ctx: MeshContext, users, items, ratings):
        """Per-process batch staging for entity-sharded input rows."""
        cfg = self.config
        n_local = len(users)
        procs = ctx.process_count
        # one metadata exchange: (row count, rating sum) per process
        stats = ctx.allgather_obj(
            (n_local, float(np.asarray(ratings, np.float64).sum())))
        n_global = sum(s[0] for s in stats)
        mean = (sum(s[1] for s in stats) / n_global) if n_global else 0.0
        global_batch = ctx.pad_to_batch_multiple(
            min(cfg.batch_size, max(n_global, 1)))
        if global_batch % procs:
            raise ValueError(
                f"global batch {global_batch} not divisible by "
                f"{procs} processes")
        b_local = global_batch // procs
        n_batches = max(
            1, max((s[0] + b_local - 1) // b_local for s in stats))
        n_pad = n_batches * b_local
        rng = np.random.default_rng(cfg.seed + ctx.process_index)
        if n_local:
            order = np.concatenate([
                rng.permutation(n_local),
                rng.integers(0, n_local, n_pad - n_local),
            ])
        else:
            order = np.zeros(n_pad, np.int64)  # all-padding shard
            users = np.zeros(1, np.int32)
            items = np.zeros(1, np.int32)
            ratings = np.zeros(1, np.float32)
        w = np.concatenate([
            np.ones(n_local, np.float32),
            np.zeros(n_pad - n_local, np.float32),
        ])

        order, w = _sort_batches_by_entity(
            order, w, np.asarray(users, np.int32), n_batches, b_local)

        def stage(a, dtype):
            a = np.asarray(a, dtype)[order].reshape(n_batches, b_local)
            return ctx.put_local_batches(a)

        return (
            stage(users, np.int32),
            stage(items, np.int32),
            stage(np.asarray(ratings, np.float32) - mean, np.float32),
            ctx.put_local_batches(w.reshape(n_batches, b_local)),
            mean,
        )

    # -- scoring ----------------------------------------------------------
    @staticmethod
    def recommend(
        model: TwoTowerModel,
        user_idx: int,
        num: int,
        exclude: Optional[np.ndarray] = None,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Top-``num`` (item indices, scores) for one user.

        ``exclude`` masks item indices (seen items / blacklist) with -inf
        before top-k — the static-shape answer to dynamic filtered candidate
        sets (SURVEY §7 hard part #4)."""
        idx, scores = TwoTowerMF.recommend_batch(
            model, np.asarray([user_idx], np.int32), num, exclude
        )
        return idx[0], scores[0]

    @staticmethod
    def recommend_batch(
        model: TwoTowerModel,
        user_idx: np.ndarray,
        num: int,
        exclude: Optional[np.ndarray] = None,
        row_mask: Optional[np.ndarray] = None,
        _force_exact: bool = False,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized top-k over the full catalog for a batch of users.

        Shape discipline (the serving hot path): the user batch is padded to
        a :data:`SERVE_BUCKETS` bucket and the top-k size is the model's
        static ``serve_k`` whenever ``num`` fits under it — so the whole
        query mix shares a handful of pre-warmed executables. The user-row
        gather happens ON DEVICE (indices in, [bucket, k] out) — no
        full-table host round-trip per call.

        ``exclude`` masks one shared item-index set for the whole batch;
        ``row_mask`` is the rule-filtered form — a ``[b, n_items]`` f32
        additive mask (0 keep / -inf drop) giving EVERY query its own
        filter set in the same single dispatch (ops/retrieval.py carries it
        through the Pallas kernel on the quantized path)."""
        from incubator_predictionio_tpu.utils import jitstats

        num = min(num, model.n_items)  # k cannot exceed the catalog
        if num <= 0:
            # degenerate query — every path (serial, grouped, device)
            # answers empty; never hand a non-positive k to top-k
            return (np.zeros((len(user_idx), 0), np.int64),
                    np.zeros((len(user_idx), 0), np.float32))
        if (model._device_items is None and model._device_items_q is None
                and model._host_items is None and model._sharded is None):
            model.prepare_for_serving()
        if row_mask is not None and row_mask.shape != (len(user_idx), model.n_items):
            raise ValueError(
                f"row_mask shape {row_mask.shape} != "
                f"(batch, n_items) {(len(user_idx), model.n_items)}")
        if model._sharded is not None:
            # sharded layout: per-shard top-k + cross-shard merge
            # (sharding/serve.py); _force_exact skips only the pruned
            # (per-shard IVF) stage — exact answers stay sharded
            return _recommend_batch_sharded(
                model, user_idx, num, exclude, row_mask, _force_exact)
        if model._ivf is not None and not _force_exact:
            from incubator_predictionio_tpu.serving import ann

            if ann.two_stage_enabled(model.n_items):
                res = _recommend_batch_two_stage(
                    model, user_idx, num, exclude, row_mask)
                if res is not None:
                    return res
                # fewer candidates than num survived the probe — the exact
                # path below answers (pio_retrieval_fallback_total counts it)
        if model._host_items is not None:
            return _recommend_batch_host(model, user_idx, num, exclude, row_mask)
        b = len(user_idx)
        bucket = serve_bucket(max(b, 1))
        k = model._serve_k if 0 < num <= model._serve_k else num
        uidx = np.zeros(bucket, np.int32)
        uidx[:b] = np.asarray(user_idx, np.int32)
        ue_tab, ub_tab = model._device_users
        quantized = model._device_items_q is not None
        if quantized:
            items_q, scales, bias, base_mask = model._device_items_q
        else:
            item_t, item_b, base_mask = model._device_items
        mask = base_mask
        if exclude is not None and len(exclude):
            m = np.zeros(base_mask.shape[0], np.float32)
            m[np.asarray(exclude, np.int64)] = -np.inf
            mask = mask + jnp.asarray(m)
        rmask = None
        if row_mask is not None:
            # pad rows to the batch bucket and columns to the (quantized)
            # catalog padding; padded columns are already -inf in base_mask
            n_cols = int(mask.shape[0])
            rm = _row_mask_pad_buffer(bucket, n_cols)
            rm[:b, : row_mask.shape[1]] = row_mask
            rmask = jnp.asarray(rm)
        # the int8 executable gets its own jitstats name so `pio-tpu status`
        # top-compiles attributes quantized-kernel compiles distinctly from
        # the bf16 exact scorer (utils/jitstats.executable_name)
        with jitstats.dispatch_timer((
            "two_tower_topk_int8" if quantized else "two_tower_topk",
            bucket, k, model.n_items, ue_tab.shape[0], rmask is not None,
        )):
            if quantized:
                idx, scores = _topk_quantized(
                    jnp.asarray(uidx), ue_tab, ub_tab,
                    items_q, scales, bias, mask, rmask, model.mean, k,
                )
            else:
                idx, scores = _topk_scores(
                    jnp.asarray(uidx), ue_tab, ub_tab,
                    item_t, item_b, model.mean, mask, rmask, k,
                )
            # ONE batched device→host pull for both results: each separate
            # np.asarray costs a full round trip on remote-attached devices
            idx_h, scores_h = jax.device_get((idx, scores))
        return idx_h[:b, :num], scores_h[:b, :num]


#: Per-thread [bucket, n_cols] row-mask pad buffers: the device dispatch
#: consumes the padded mask synchronously (recommend_batch device_gets its
#: results before returning), so each serving thread can recycle one scratch
#: buffer per shape instead of allocating bucket × N × 4 bytes per dispatch.
#: Thread-local because serving overlaps batches across threads
#: (serving_thread_safe / max_in_flight).
_ROW_MASK_SCRATCH = threading.local()


def _row_mask_pad_buffer(bucket: int, n_cols: int) -> np.ndarray:
    """A zeroed, reusable ``[bucket, n_cols]`` f32 pad buffer."""
    cache = getattr(_ROW_MASK_SCRATCH, "cache", None)
    if cache is None:
        cache = _ROW_MASK_SCRATCH.cache = {}
    buf = cache.get((bucket, n_cols))
    if buf is None:
        if len(cache) >= 16:  # many models/shapes in one process: tests
            cache.clear()
        buf = cache[(bucket, n_cols)] = np.zeros((bucket, n_cols), np.float32)
    else:
        buf.fill(0.0)
    return buf


def _recommend_batch_sharded(
    model: TwoTowerModel,
    user_idx: np.ndarray,
    num: int,
    exclude: Optional[np.ndarray] = None,
    row_mask: Optional[np.ndarray] = None,
    force_exact: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Sharded retrieval (sharding/serve.py): the per-shard IVF prune +
    merge-rerank when two-stage is enabled (falling back to sharded-exact
    when any shard under-covers), else per-shard exact top-k + merge."""
    sh = model._sharded
    if (sh.ivf is not None and any(sh.ivf) and not force_exact):
        from incubator_predictionio_tpu.serving import ann

        if ann.two_stage_enabled(model.n_items):
            q, ub = sh.user_rows(model, user_idx)
            res = sh.search_ivf(q, ub, num, exclude=exclude,
                                row_mask=row_mask)
            if res is not None:
                return res
    return sh.search_exact(model, user_idx, num, exclude=exclude,
                           row_mask=row_mask)


def _recommend_batch_two_stage(
    model: TwoTowerModel,
    user_idx: np.ndarray,
    num: int,
    exclude: Optional[np.ndarray] = None,
    row_mask: Optional[np.ndarray] = None,
) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """Coarse IVF pruning + exact rerank (serving/ann.py): centroid scores
    pick top-nprobe partitions per user, only their members are scored with
    the exact math, and ``exclude``/``row_mask`` land on the rerank scores
    in candidate-index space after the gather. Returns None when the probe
    can't cover ``num`` candidates — the caller's exact path answers."""
    if not model._ivf.hydrated:
        # persisted slim and this model never ran _prepare_index (e.g. a
        # build_index=False prepare): rebuild the rerank tables lazily
        model._ivf.rehydrate(*model._host_item_table())
    model.ensure_host()  # no-op unless the towers are device-resident
    uidx = np.asarray(user_idx, np.int64)
    q = np.asarray(model.user_emb, np.float32)[uidx]
    ub = np.asarray(model.user_bias, np.float32)[uidx]
    return model._ivf.search(
        q, ub, model.mean, num, exclude=exclude, row_mask=row_mask)


def _recommend_batch_host(
    model: TwoTowerModel,
    user_idx: np.ndarray,
    num: int,
    exclude: Optional[np.ndarray] = None,
    row_mask: Optional[np.ndarray] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Small-catalog top-k in host numpy: one [b, k] @ [k, n] GEMM + argpartition.

    Microseconds for catalogs under :data:`HOST_SERVE_MAX_ELEMENTS`; never
    pays a device dispatch round trip (which dominates small-model serving
    latency on remote-attached accelerators)."""
    item_t, item_b = model._host_items
    ue = np.asarray(model.user_emb, np.float32)[user_idx]
    ub = np.asarray(model.user_bias, np.float32)[user_idx]
    scores = ue @ item_t + item_b[None, :] + ub[:, None] + model.mean
    if exclude is not None and len(exclude):
        scores[:, np.asarray(exclude, np.int64)] = -np.inf
    if row_mask is not None:
        scores += row_mask
    k = min(num, scores.shape[1])
    part = np.argpartition(-scores, k - 1, axis=1)[:, :k]
    row = np.arange(scores.shape[0])[:, None]
    ordr = np.argsort(-scores[row, part], axis=1)
    idx = part[row, ordr]
    return idx, scores[row, idx]


def _sort_batches_by_entity(
    order: np.ndarray, w: np.ndarray, entities: np.ndarray,
    n_batches: int, batch: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Sort each batch's rows by entity (user) index, host-side at staging.

    Batch composition — and therefore the math — is unchanged (the loss sums
    over the batch); only the within-batch ORDER changes, which lets the
    device gather/scatter walk the big user table quasi-sequentially
    (measured ~15% off the step time at 1M users). Returns the re-ordered
    (order, w) pair; ``w`` rides along so padding rows keep zero weight."""
    o2 = order.reshape(n_batches, batch)
    keys = entities[o2] if len(entities) else o2
    srt = np.argsort(keys, axis=1, kind="stable")
    return (
        np.take_along_axis(o2, srt, 1).reshape(-1),
        np.take_along_axis(w.reshape(n_batches, batch), srt, 1).reshape(-1),
    )


@partial(jax.jit, static_argnames=("lr", "reg", "n_epochs"), donate_argnums=(0, 1))
def _train_epochs(p, o, ub, ib, rb, wb, lr, reg, n_epochs):
    """``n_epochs`` epochs in one dispatch: lax.scan over epochs of lax.scan
    over staged batches — the whole schedule runs on device with no host
    round-trips (the dominant cost behind a device tunnel). Module-level with
    static (lr, reg, n_epochs) so repeated fits of the same shapes reuse one
    executable. Returns the last epoch's mean loss. Adam runs through
    utils/optim.adam_apply (optax-equivalent math; moment storage dtype —
    fp32 or bf16 — is carried by the state ``o`` itself)."""
    from incubator_predictionio_tpu.utils.optim import adam_apply

    def loss_fn(p, bu, bi, br, bw):
        # one ROW gather per table fetches vector + bias together (bias is
        # the last column — see fit); no 1-D scalar gathers on the hot path.
        # batches are user-sorted at staging, so the user-table gather (and
        # its transpose scatter-add) walks the big table quasi-sequentially
        gu = jnp.take(p["ue"], bu, axis=0, indices_are_sorted=True)
        gi = p["ie"][bi]
        ue = gu[:, :-1].astype(jnp.bfloat16)
        ie = gi[:, :-1].astype(jnp.bfloat16)
        pred = (
            jnp.sum(ue * ie, axis=-1).astype(jnp.float32)
            + gu[:, -1] + gi[:, -1]
        )
        err = (pred - br) ** 2
        denom = jnp.maximum(jnp.sum(bw), 1.0)
        mse = jnp.sum(err * bw) / denom
        l2 = reg * (
            jnp.sum(ue.astype(jnp.float32) ** 2) + jnp.sum(ie.astype(jnp.float32) ** 2)
        ) / denom
        return mse + l2

    def step(carry, batch):
        p, o = carry
        bu, bi, br, bw = batch
        loss, grads = jax.value_and_grad(loss_fn)(p, bu, bi, br, bw)
        p, o = adam_apply(p, grads, o, lr)
        return (p, o), loss

    def epoch(carry, _):
        carry, losses = jax.lax.scan(step, carry, (ub, ib, rb, wb))
        return carry, losses.mean()

    (p, o), epoch_losses = jax.lax.scan(epoch, (p, o), None, length=n_epochs)
    return p, o, epoch_losses[-1]


@partial(jax.jit, static_argnames=("num",))
def _topk_quantized(uidx, ue_tab, ub_tab, items_q, scales, bias, mask,
                    row_mask, mean, num):
    """Quantized catalog scoring: Pallas kernel on TPU, jnp oracle elsewhere.
    User rows are gathered on device from the resident bf16 table.
    ``row_mask`` (None or [b, n]) carries per-query rule filters into the
    kernel itself — masked batches stay one dispatch."""
    from incubator_predictionio_tpu.ops.retrieval import (
        score_catalog_quantized,
        score_catalog_reference,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    scorer = score_catalog_quantized if on_tpu else score_catalog_reference
    scores = scorer(ue_tab[uidx], items_q, scales, bias, mask, row_mask) \
        + ub_tab[uidx][:, None] + mean
    values, indices = jax.lax.top_k(scores, num)
    return indices, values


@partial(jax.jit, static_argnames=("num",))
def _topk_scores(uidx, ue_tab, ub_tab, item_t, item_b, mean, mask, row_mask,
                 num):
    # device gather of the query rows, then [b,k] @ [k,n] on the MXU in
    # bfloat16 with fp32 score accumulation; row_mask (None or [b, n]) adds
    # per-query rule filters without leaving the single dispatch
    scores = (
        jax.lax.dot_general(
            ue_tab[uidx], item_t, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        + item_b[None, :]
        + ub_tab[uidx][:, None]
        + mean
        + mask[None, :]
    )
    if row_mask is not None:
        scores = scores + row_mask
    values, indices = jax.lax.top_k(scores, num)
    return indices, values
