"""JAX model zoo backing the engine templates.

Replaces the reference's delegation to Spark MLlib (NaiveBayes, ALS,
RandomForest) with TPU-first implementations: batched bfloat16 matmuls on the
MXU, data/model-parallel sharding over the mesh, static shapes throughout.
"""
