"""Data-parallel MLP classifier — the NaiveBayes replacement.

The reference classification template trains Spark MLlib NaiveBayes on 3
double features (examples/scala-parallel-classification/.../NaiveBayesAlgorithm.scala:36-60).
Here: a bfloat16 MLP trained with a jit-compiled optax loop.

TPU mapping:
- batch sharded over the mesh ``data`` axis, params replicated — the SPMD
  partitioner inserts the gradient psum over ICI;
- compute in bfloat16 (MXU-native), params + optimizer state in float32;
- static shapes: the dataset is padded to a multiple of (batch × data axis)
  and padding rows carry zero sample-weight;
- the whole epoch loop is a ``lax.scan`` over pre-staged device batches, so
  one compilation covers any epoch count (no per-step dispatch overhead).
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from incubator_predictionio_tpu.parallel.mesh import MeshContext


@dataclasses.dataclass(frozen=True)
class MLPConfig:
    hidden_dims: tuple[int, ...] = (128, 128)
    learning_rate: float = 1e-3
    batch_size: int = 256  # global batch (divided across the data axis)
    epochs: int = 50
    seed: int = 0


def _init_params(key, dims: list[int]) -> list[dict[str, jax.Array]]:
    layers = []
    for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
        key, sub = jax.random.split(key)
        layers.append({
            "w": jax.random.normal(sub, (d_in, d_out), jnp.float32)
            * jnp.sqrt(2.0 / d_in),
            "b": jnp.zeros((d_out,), jnp.float32),
        })
    return layers


def _forward(params, x: jax.Array) -> jax.Array:
    h = x.astype(jnp.bfloat16)
    for layer in params[:-1]:
        h = jnp.maximum(h @ layer["w"].astype(jnp.bfloat16)
                        + layer["b"].astype(jnp.bfloat16), 0.0)
    out = h @ params[-1]["w"].astype(jnp.bfloat16) + params[-1]["b"].astype(jnp.bfloat16)
    return out.astype(jnp.float32)


@functools.lru_cache(maxsize=32)
def _train_epoch_fn(learning_rate: float):
    """Module-level CACHED jitted epoch: repeated fits with the same
    learning rate (and shapes, via the jit cache) reuse one executable —
    a jit nested in ``fit`` recompiles every call (see transformer.py)."""
    tx = optax.adam(learning_rate)

    def loss_fn(p, bx, by, bw):
        logits = _forward(p, bx)
        losses = optax.softmax_cross_entropy_with_integer_labels(logits, by)
        return jnp.sum(losses * bw) / jnp.maximum(jnp.sum(bw), 1.0)

    # batches are jit ARGUMENTS, not closure captures: captured arrays
    # bake in as constants, which fails for multi-process global arrays
    @partial(jax.jit, donate_argnums=(0, 1))
    def train_epoch(p, o, xb, yb, wb):
        def step(carry, batch):
            p, o = carry
            bx, by, bw = batch
            loss, grads = jax.value_and_grad(loss_fn)(p, bx, by, bw)
            updates, o = tx.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return (p, o), loss

        (p, o), losses = jax.lax.scan(step, (p, o), (xb, yb, wb))
        return p, o, losses.mean()

    return train_epoch


@dataclasses.dataclass
class MLPModel:
    """Trained model: params pytree + normalization + label vocabulary."""

    params: list[dict[str, np.ndarray]]
    mean: np.ndarray
    std: np.ndarray
    classes: list  # index -> original label value
    config: MLPConfig

    def prepare_for_serving(self) -> "MLPModel":
        """Make params device-resident once; per-query calls then only move
        the (tiny) feature vector host→device. Deploy-time model residency
        (SURVEY §7 hard part #1) in miniature. The query server calls this
        on any model exposing the method."""
        self.params = jax.device_put(self.params)
        return self

    def serving_info(self) -> dict:
        """Status-page observability (see TwoTowerModel.serving_info)."""
        return {"path": "device-params", "classes": len(self.classes)}


class MLPClassifier:
    def __init__(self, config: MLPConfig = MLPConfig()):
        self.config = config

    # -- training ---------------------------------------------------------
    def fit(
        self,
        ctx: MeshContext,
        x: np.ndarray,
        y: np.ndarray,
        rows_are_local: bool = False,
    ) -> MLPModel:
        """``rows_are_local=True``: (x, y) are only THIS process's
        entity-disjoint shard. Normalization moments and the class
        vocabulary are agreed globally via vocabulary-sized allgathers, so
        every process trains the identical model on 1/P of the rows
        (reference counterpart: RDD partition reads, PEvents.scala:38)."""
        cfg = self.config
        n, d = x.shape
        if rows_are_local and ctx.process_count > 1:
            from incubator_predictionio_tpu.data.sharded import (
                global_sum,
                union_label_set,
            )
            from incubator_predictionio_tpu.parallel.staging import (
                stage_sharded_batches,
            )

            classes = np.asarray(union_label_set(ctx, y.tolist()))
            cls_index = {c: i for i, c in enumerate(classes.tolist())}
            y_idx = np.asarray([cls_index[v] for v in y.tolist()], np.int32)
            # global feature moments from per-shard (n, Σx, Σx²)
            n_g, sx, sxx = global_sum(
                ctx, (n, x.sum(axis=0, dtype=np.float64),
                      (x.astype(np.float64) ** 2).sum(axis=0)))
            mean = (sx / max(n_g, 1)).astype(x.dtype)
            var = np.maximum(sxx / max(n_g, 1) - mean.astype(np.float64) ** 2, 0.0)
            std = (np.sqrt(var) + 1e-8).astype(x.dtype)
            xn = ((x - mean) / std).astype(np.float32)
            (xb, yb), wb, _ = stage_sharded_batches(
                ctx, (xn, y_idx), cfg.batch_size, cfg.seed, n_global=n_g)
        else:
            classes, y_idx = np.unique(y, return_inverse=True)
            mean = x.mean(axis=0)
            std = x.std(axis=0) + 1e-8
            xn = ((x - mean) / std).astype(np.float32)

            # pad to a whole number of global batches (static shapes)
            global_batch = min(cfg.batch_size, ctx.pad_to_batch_multiple(n))
            global_batch = ctx.pad_to_batch_multiple(global_batch)
            n_batches = max(1, (n + global_batch - 1) // global_batch)
            n_pad = n_batches * global_batch
            pad = n_pad - n
            xp = np.concatenate([xn, np.zeros((pad, d), np.float32)])
            yp = np.concatenate([y_idx.astype(np.int32), np.zeros(pad, np.int32)])
            wp = np.concatenate([np.ones(n, np.float32),
                                 np.zeros(pad, np.float32)])

            # stage on device: [n_batches, batch, ...] sharded over data
            # axis; ctx.put (not raw device_put) so replicated-rows training
            # also works on cross-process meshes (e.g. distributed eval of
            # folds read single-process)
            def stage(a):
                a = a.reshape(n_batches, global_batch, *a.shape[1:])
                return ctx.put(a, None, ctx.data_axis)

            xb, yb, wb = stage(xp), stage(yp), stage(wp)
        n_classes = len(classes)

        dims = [d, *cfg.hidden_dims, n_classes]
        params = ctx.replicate(_init_params(jax.random.key(cfg.seed), dims))
        from incubator_predictionio_tpu.utils.optim import jit_adam_init

        # cached jitted init: state inherits the params' shardings and
        # repeated fits (eval sweeps) reuse one executable
        opt_state = jit_adam_init(cfg.learning_rate)(params)
        train_epoch = _train_epoch_fn(cfg.learning_rate)

        loss = np.inf
        for _ in range(cfg.epochs):
            params, opt_state, loss = train_epoch(params, opt_state, xb, yb, wb)
            loss.block_until_ready()  # see two_tower.py: CPU collective-deadlock guard
        final_loss = float(loss)

        host_params = jax.tree.map(np.asarray, params)
        model = MLPModel(host_params, mean, std, classes.tolist(), cfg)
        model.final_loss = final_loss
        return model

    # -- inference --------------------------------------------------------
    @staticmethod
    def logits(model: MLPModel, x: np.ndarray) -> np.ndarray:
        xn = ((x - model.mean) / model.std).astype(np.float32)
        return np.asarray(_jit_forward(model.params, jnp.asarray(xn)))

    @staticmethod
    def predict(model: MLPModel, x: np.ndarray) -> np.ndarray:
        idx = MLPClassifier.logits(model, x).argmax(axis=-1)
        return np.asarray([model.classes[i] for i in idx])


@jax.jit
def _jit_forward(params, x):
    return _forward(params, x)
