"""Negative sampling for implicit-feedback MF.

The reference delegates implicit feedback to MLlib ``ALS.trainImplicit``
(confidence-weighted ALS). Our SGD twin needs explicit negatives; sampling
uniformly produces ~|positives|/|catalog| false negatives, which flattens the
learned structure on small catalogs. ``sample_negatives`` rejection-samples
against the observed (user, item) set.
"""

from __future__ import annotations

import numpy as np


def sample_negatives(
    pos_u: np.ndarray,
    pos_i: np.ndarray,
    n_items: int,
    k: int,
    rng: np.random.Generator,
    max_rounds: int = 8,
) -> tuple[np.ndarray, np.ndarray]:
    """k negatives per positive, avoiding observed pairs (best effort).

    Returns (neg_u, neg_i) of length len(pos_u) * k. After ``max_rounds`` of
    rejection any remaining collisions are kept (dense users on tiny
    catalogs may have no true negatives).
    """
    observed = set((int(u) * n_items + int(i)) for u, i in zip(pos_u, pos_i))
    neg_u = np.repeat(pos_u, k)
    neg_i = rng.integers(0, n_items, len(neg_u)).astype(np.int32)
    keys = neg_u.astype(np.int64) * n_items + neg_i
    bad = np.fromiter((kk in observed for kk in keys), bool, len(keys))
    for _ in range(max_rounds):
        n_bad = int(bad.sum())
        if not n_bad:
            break
        neg_i[bad] = rng.integers(0, n_items, n_bad).astype(np.int32)
        keys = neg_u.astype(np.int64) * n_items + neg_i
        bad = np.fromiter((kk in observed for kk in keys), bool, len(keys))
    return neg_u, neg_i
