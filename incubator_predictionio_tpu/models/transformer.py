"""Sequential-recommendation transformer (SASRec/Transformer4Rec-style).

No reference counterpart exists (the reference's only sequence model is
``e2.engine.MarkovChain``, MarkovChain.scala:25) — this is the new
long-context capability BASELINE.md asks for: a causal transformer over
session item sequences predicting the next item, with sequence/context
parallelism via ring attention (parallel/ring.py) when the mesh has a
``seq`` axis.

TPU mapping:
- tokens [B, L]: B sharded over ``data``, L over ``seq`` (when present);
- attention: blockwise ring attention (ppermute ring over ICI) or local
  per-device causal attention when the mesh has no seq axis;
- matmuls in bf16 with fp32 accumulation; params fp32 replicated (weight
  tying: output logits reuse the item embedding);
- targets/weights precomputed on host — the next-token shift never crosses
  shard boundaries on device.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from incubator_predictionio_tpu.parallel.mesh import MeshContext
from incubator_predictionio_tpu.parallel.ring import (
    causal_attention,
    ring_attention_sharded,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 1024        # items + 1 (0 is padding)
    max_len: int = 64
    d_model: int = 64
    n_heads: int = 2
    n_layers: int = 2
    learning_rate: float = 1e-3
    batch_size: int = 256
    epochs: int = 10
    seed: int = 0
    attention: str = "auto"       # "auto" | "local" | "ring"
    # mixture-of-experts FFN (0 = dense). Switch-style top-1 routing with a
    # static token capacity per expert; expert weights shard over the mesh's
    # ``expert`` axis when present (XLA inserts the dispatch all_to_all)
    n_experts: int = 0
    expert_capacity_factor: float = 1.25
    router_aux_weight: float = 1e-2
    # pipeline parallelism (0 = off): split the layer stack into S stages
    # over the mesh's ``pipe`` axis, GPipe microbatch schedule
    # (parallel/pipeline.py); microbatches default to the stage count
    pipeline_stages: int = 0
    pipeline_microbatches: int = 0
    # rematerialization: recompute each block's activations in the backward
    # pass instead of storing them — trades ~1 extra forward of FLOPs for
    # O(n_layers) less activation HBM, the lever that fits long sequences
    remat: bool = False
    # "bfloat16" stores adam's FIRST moment in bf16 (second stays fp32 for
    # dynamic range) — halves the biggest optimizer-state tensor
    adam_moments_dtype: str = "float32"
    # tensor parallelism (Megatron-style) over the mesh's ``model`` axis:
    # attention heads and the FFN hidden dim shard column-wise, the output
    # projections row-wise — the GSPMD way: annotate the WEIGHTS, let XLA
    # insert the psums. The axis size must divide n_heads and 4*d_model.
    tensor_parallel: bool = False
    # mid-training checkpoint/resume (utils/checkpoint.py); 0 = off
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0     # epochs between checkpoints
    checkpoint_keep: int = 3


def _init_params(key, cfg: TransformerConfig):
    k = iter(jax.random.split(key, 4 + 8 * cfg.n_layers))
    d, dh = cfg.d_model, cfg.d_model * 4
    init = lambda kk, shape, scale: jax.random.normal(kk, shape, jnp.float32) * scale
    params = {
        "item_emb": init(next(k), (cfg.vocab_size, d), 0.02),
        "pos_emb": init(next(k), (cfg.max_len, d), 0.02),
        "ln_f": {"g": jnp.ones(d), "b": jnp.zeros(d)},
        "layers": [],
    }
    for _ in range(cfg.n_layers):
        layer = {
            "ln1": {"g": jnp.ones(d), "b": jnp.zeros(d)},
            "wq": init(next(k), (d, d), d ** -0.5),
            "wk": init(next(k), (d, d), d ** -0.5),
            "wv": init(next(k), (d, d), d ** -0.5),
            "wo": init(next(k), (d, d), d ** -0.5),
            "ln2": {"g": jnp.ones(d), "b": jnp.zeros(d)},
        }
        if cfg.n_experts:
            e = cfg.n_experts
            layer.update({
                "wr": init(next(k), (d, e), d ** -0.5),      # router
                "we1": init(next(k), (e, d, dh), d ** -0.5),
                "be1": jnp.zeros((e, dh)),
                "we2": init(next(k), (e, dh, d), dh ** -0.5),
                "be2": jnp.zeros((e, d)),
            })
        else:
            layer.update({
                "w1": init(next(k), (d, dh), d ** -0.5),
                "b1": jnp.zeros(dh),
                "w2": init(next(k), (dh, d), dh ** -0.5),
                "b2": jnp.zeros(d),
            })
        params["layers"].append(layer)
    return params


def _ln(x, p):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * p["g"] + p["b"]


def _bf16_matmul(x, w):
    return (x.astype(jnp.bfloat16) @ w.astype(jnp.bfloat16)).astype(jnp.float32)


def _moe_ffn(x, layer, cfg: TransformerConfig, mesh, token_mask=None):
    """Switch-style top-1 MoE FFN: x [B, L, D] → (y [B, L, D], aux loss).

    Expert parallelism the XLA way: dispatched token slots [E, C, D] and the
    expert weights [E, …] carry an ``expert``-axis sharding constraint when
    the mesh has one, so the SPMD partitioner inserts the all_to_all on the
    dispatch/combine einsums — no hand-written collective. Static capacity
    C keeps every shape jit-constant; overflow tokens fall through on the
    residual path (their combine weight is zero).

    ``token_mask`` [B, L] (1 = real token) keeps PADDING out of the router:
    pad tokens claim no capacity slots and don't distort the load-balancing
    statistics (batches are padded to mesh multiples at staging)."""
    b, l, d = x.shape
    e = cfg.n_experts
    s = b * l
    capacity = max(1, int(cfg.expert_capacity_factor * s / e))
    xf = x.reshape(s, d)
    logits = _bf16_matmul(xf, layer["wr"])                 # [S, E]
    probs = jax.nn.softmax(logits, axis=-1)
    chosen = jnp.argmax(probs, axis=-1)                    # [S]
    onehot = jax.nn.one_hot(chosen, e, dtype=jnp.float32)  # [S, E]
    if token_mask is not None:
        mask_f = token_mask.reshape(s).astype(jnp.float32)
        onehot = onehot * mask_f[:, None]
    else:
        mask_f = jnp.ones((s,), jnp.float32)
    gate = jnp.sum(probs * onehot, axis=-1)                # [S]
    # position of each token within its expert's capacity slots
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot     # [S, E], 0-based
    keep = (pos < capacity).astype(jnp.float32) * onehot
    dispatch = keep[..., None] * jax.nn.one_hot(
        pos.sum(-1).astype(jnp.int32), capacity,
        dtype=jnp.float32)[:, None, :]  # [S, E, C]
    combine = dispatch * gate[:, None, None]

    def on_experts(a):
        if mesh is not None and "expert" in mesh.shape:
            spec = P("expert", *([None] * (a.ndim - 1)))
            return jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, spec))
        return a

    bf = jnp.bfloat16
    expert_in = on_experts(jnp.einsum(
        "sec,sd->ecd", dispatch.astype(bf), xf.astype(bf)).astype(jnp.float32))
    hidden = jax.nn.gelu(jnp.einsum(
        "ecd,edh->ech", expert_in.astype(bf),
        layer["we1"].astype(bf)).astype(jnp.float32) + layer["be1"][:, None, :])
    out = on_experts(jnp.einsum(
        "ech,ehd->ecd", hidden.astype(bf),
        layer["we2"].astype(bf)).astype(jnp.float32) + layer["be2"][:, None, :])
    y = jnp.einsum("sec,ecd->sd", combine.astype(bf),
                   out.astype(bf)).astype(jnp.float32)
    # load-balancing auxiliary (Switch Transformer eq. 4-6): fraction of
    # REAL tokens routed to each expert × their mean router probability
    n_real = jnp.maximum(mask_f.sum(), 1.0)
    frac = onehot.sum(axis=0) / n_real
    mean_prob = (probs * mask_f[:, None]).sum(axis=0) / n_real
    aux = e * jnp.sum(frac * mean_prob)
    return y.reshape(b, l, d), aux


def _apply_layer(layer, h, cfg: TransformerConfig, mesh=None, use_ring=False,
                 token_mask=None):
    """One transformer block: h [B, L, D] → (h [B, L, D], aux loss)."""
    b, l, d = h.shape
    nh, dh = cfg.n_heads, d // cfg.n_heads
    x = _ln(h, layer["ln1"])
    q = _bf16_matmul(x, layer["wq"]).reshape(b, l, nh, dh)
    k = _bf16_matmul(x, layer["wk"]).reshape(b, l, nh, dh)
    v = _bf16_matmul(x, layer["wv"]).reshape(b, l, nh, dh)
    if use_ring:
        att = ring_attention_sharded(q, k, v, mesh)
    else:
        att = causal_attention(q, k, v)
    h = h + _bf16_matmul(att.reshape(b, l, d), layer["wo"])
    x = _ln(h, layer["ln2"])
    if cfg.n_experts:
        y, aux = _moe_ffn(x, layer, cfg, mesh, token_mask)
        return h + y, aux
    x = jax.nn.gelu(_bf16_matmul(x, layer["w1"]) + layer["b1"])
    return h + _bf16_matmul(x, layer["w2"]) + layer["b2"], jnp.float32(0.0)


def _forward(params, tokens, positions, cfg: TransformerConfig,
             mesh=None, use_ring=False):
    """tokens, positions: [B, L] int32 → (hidden [B, L, D] fp32, aux loss)."""
    h = params["item_emb"][tokens] + params["pos_emb"][positions]
    aux_total = jnp.float32(0.0)
    token_mask = (tokens != 0) if cfg.n_experts else None
    block = _apply_layer
    if cfg.remat:
        # recompute-in-backward per block: activation HBM drops from
        # O(n_layers × B × L × D) to O(B × L × D)
        block = jax.checkpoint(
            _apply_layer, static_argnums=(2, 3, 4))
    for layer in params["layers"]:
        h, aux = block(layer, h, cfg, mesh, use_ring, token_mask)
        aux_total = aux_total + aux
    return _ln(h, params["ln_f"]), aux_total


def _forward_pipelined(params, tokens, positions, cfg: TransformerConfig,
                       mesh, data_axis):
    """Pipelined counterpart of :func:`_forward`: ``params["layers"]`` is the
    STACKED pytree sharded over the ``pipe`` axis; embedding/unembedding stay
    outside the pipeline (replicated, tied to the item table)."""
    from incubator_predictionio_tpu.parallel.pipeline import pipeline_forward

    h0 = params["item_emb"][tokens] + params["pos_emb"][positions]
    m = cfg.pipeline_microbatches or cfg.pipeline_stages

    def body(layer, h):
        out, _aux = _apply_layer(layer, h, cfg)
        return out

    if cfg.remat:
        # remat composes with the pipeline: each stage recomputes its
        # blocks' activations in backward (microbatch-sized, per layer)
        body = jax.checkpoint(body)

    h = pipeline_forward(
        params["layers"], h0, body, mesh, m,
        data_axis=data_axis if data_axis in mesh.shape else None)
    return _ln(h, params["ln_f"]), jnp.float32(0.0)


@functools.lru_cache(maxsize=32)
def _jit_init_fn(cfg: TransformerConfig):
    """One jitted whole-pytree param init per config (see fit for why)."""
    return jax.jit(lambda key: _init_params(key, cfg))


@functools.lru_cache(maxsize=32)
def _train_epochs_fn(cfg: TransformerConfig, mesh, use_ring: bool,
                     use_pipeline: bool = False, data_axis: str = "data"):
    """Module-level CACHED jitted schedule: repeated fits of the same
    (config, mesh, attention) reuse one executable. A jit defined inside
    ``fit`` is a fresh cache per call — every fit would recompile the whole
    scan, which behind a remote-compile tunnel costs ~20s and was the round-2
    sequential 'MFU': the bench was timing XLA, not the TPU."""
    tx = optax.adam(
        cfg.learning_rate,
        # bf16 first moment halves the largest optimizer-state tensor's HBM
        # traffic; the second moment stays fp32 (its dynamic range is what
        # adam's stability rests on). Parity-tested in
        # tests/test_sequential_template.py.
        mu_dtype=jnp.bfloat16 if cfg.adam_moments_dtype == "bfloat16"
        else None,
    )

    def loss_fn(p, bt, bp, by, bw):
        from incubator_predictionio_tpu.ops.xent import weighted_xent_sum

        if use_pipeline:
            h, aux = _forward_pipelined(p, bt, bp, cfg, mesh, data_axis)
        else:
            h, aux = _forward(p, bt, bp, cfg, mesh, use_ring)
        # fused CE: fp32 [B, L, V] logits never materialize; beyond the
        # long-context threshold the logits matrix doesn't materialize in
        # ANY dtype (ops/xent.py — VERDICT r3 weak #4)
        loss_sum = weighted_xent_sum(
            h.reshape(-1, h.shape[-1]), p["item_emb"],
            by.reshape(-1), bw.reshape(-1))
        task = loss_sum / jnp.maximum(jnp.sum(bw), 1.0)
        return task + cfg.router_aux_weight * aux

    # staged batches are jit ARGUMENTS, not closure captures: captured
    # arrays bake in as trace constants, which fails for multi-process
    # global arrays (non-addressable shards)
    @partial(jax.jit, static_argnames=("n_epochs",), donate_argnums=(0, 1))
    def train_epochs(p, o, tb, pb, yb, wb, n_epochs):
        def step(carry, batch):
            p, o = carry
            loss, grads = jax.value_and_grad(loss_fn)(p, *batch)
            updates, o = tx.update(grads, o, p)
            return (optax.apply_updates(p, updates), o), loss

        def epoch(carry, _):
            carry, losses = jax.lax.scan(step, carry, (tb, pb, yb, wb))
            return carry, losses.mean()

        (p, o), epoch_losses = jax.lax.scan(
            epoch, (p, o), None, length=n_epochs
        )
        return p, o, epoch_losses[-1]

    return train_epochs


def _place_params_pipe_sharded(ctx: MeshContext, host_params):
    """Stack the layer list and shard the stack's leading (layer) dim over
    the ``pipe`` axis — each device holds only its stage's weights."""
    from incubator_predictionio_tpu.parallel.pipeline import stack_layers

    placed = {k: jax.tree.map(ctx.put, v)
              for k, v in host_params.items() if k != "layers"}
    placed["layers"] = jax.tree.map(
        lambda a: ctx.put(a, "pipe"), stack_layers(host_params["layers"]))
    return placed


def _unstack_layers(params, n_layers: int):
    """Stacked training layout → the canonical per-layer list (host arrays),
    so serving and persistence see the same model shape as the dense path."""
    out = dict(params)
    stacked = params["layers"]
    out["layers"] = [
        jax.tree.map(lambda a: a[i], stacked) for i in range(n_layers)
    ]
    return out


def _place_params_tensor_sharded(ctx: MeshContext, host_params):
    """Megatron-style weight placement over the ``model`` axis: the QKV and
    FFN-up projections shard on their OUTPUT dim (column parallel: heads /
    hidden features live on one device each), the attention-output and
    FFN-down projections on their INPUT dim (row parallel). XLA's SPMD
    partitioner then keeps every per-head / per-feature matmul local and
    inserts exactly one psum after each row-parallel projection."""
    col = {"wq", "wk", "wv", "w1", "b1"}   # shard last dim
    row = {"wo", "w2"}                     # shard first weight dim
    # (MoE expert tables never reach here — fit rejects tp + n_experts)

    def place_layer(layer):
        out = {}
        for k, v in layer.items():
            if k in col:
                out[k] = ctx.put(v, *([None] * (np.ndim(v) - 1)), "model")
            elif k in row:
                out[k] = ctx.put(v, "model")
            else:
                out[k] = jax.tree.map(ctx.put, v)
        return out

    placed = {k: jax.tree.map(ctx.put, v)
              for k, v in host_params.items() if k != "layers"}
    placed["layers"] = [place_layer(l) for l in host_params["layers"]]
    return placed


def _place_params_expert_sharded(ctx: MeshContext, host_params):
    """Place params with expert weight tables sharded over the ``expert``
    mesh axis (each device holds n_experts/ep of the FFN weights — the
    memory win that makes MoE scale) and everything else replicated."""
    expert_keys = ("we1", "be1", "we2", "be2")
    placed = {
        k: ctx.put(v) if not isinstance(v, (dict, list)) else v
        for k, v in host_params.items() if k != "layers"
    }
    placed["ln_f"] = {k: ctx.put(v) for k, v in host_params["ln_f"].items()}
    placed["layers"] = []
    for layer in host_params["layers"]:
        placed["layers"].append({
            k: (ctx.put(v, "expert") if k in expert_keys
                else jax.tree.map(ctx.put, v))
            for k, v in layer.items()
        })
    return placed


@dataclasses.dataclass
class TransformerModel:
    params: dict
    item_map: object  # BiMap item id ↔ token (token 0 = padding)
    config: TransformerConfig

    def prepare_for_serving(self) -> "TransformerModel":
        self.params = jax.device_put(self.params)
        return self

    def serving_info(self) -> dict:
        """Status-page observability (see TwoTowerModel.serving_info)."""
        return {"path": "device-params",
                "vocab": self.config.vocab_size,
                "max_len": self.config.max_len}


class TransformerRecommender:
    def __init__(self, config: TransformerConfig):
        self.config = config

    def _use_ring(self, ctx: MeshContext) -> bool:
        if self.config.attention == "ring":
            return True
        if self.config.attention == "local":
            return False
        return "seq" in ctx.mesh.shape and ctx.axis_size("seq") > 1

    def fit(
        self,
        ctx: MeshContext,
        sequences: np.ndarray,
        item_map,
        rows_are_local: bool = False,
    ) -> "TransformerModel":
        """sequences: [N, max_len+1] int32 token rows (0-padded *left*), each
        row a session; position t predicts position t+1.

        ``rows_are_local=True``: the rows are only THIS process's session
        shard (sessions are user-entity-sharded, tokens already global);
        batches are joined via per-process input feeding
        (parallel/staging.py) — host memory is data/P per process."""
        cfg = self.config
        use_ring = self._use_ring(ctx)
        use_pipeline = bool(cfg.pipeline_stages) and "pipe" in ctx.mesh.shape
        if cfg.pipeline_stages and not use_pipeline:
            logger.warning(
                "pipeline_stages=%d requested but the mesh has no 'pipe' "
                "axis (mesh axes: %s) — training runs without pipeline "
                "parallelism", cfg.pipeline_stages, tuple(ctx.mesh.shape))
        pipe_m = cfg.pipeline_microbatches or cfg.pipeline_stages
        if use_pipeline:
            if cfg.pipeline_stages != ctx.axis_size("pipe"):
                raise ValueError(
                    f"pipeline_stages={cfg.pipeline_stages} must equal the "
                    f"pipe axis size ({ctx.axis_size('pipe')})")
            if cfg.n_layers % cfg.pipeline_stages:
                raise ValueError(
                    f"n_layers={cfg.n_layers} must divide into "
                    f"{cfg.pipeline_stages} pipeline stages")
            if use_ring or cfg.n_experts:
                raise ValueError(
                    "pipeline parallelism composes with dp (and local "
                    "attention), not with ring attention or MoE")
        tokens = sequences[:, :-1]
        targets = sequences[:, 1:]
        weights = (targets != 0).astype(np.float32) * (tokens != 0).astype(np.float32)
        n, l = tokens.shape
        if l != cfg.max_len:
            raise ValueError(f"sequences must be max_len+1 = {cfg.max_len + 1} wide")
        positions = np.broadcast_to(np.arange(l, dtype=np.int32), (n, l))

        if rows_are_local and ctx.process_count > 1:
            if use_ring:
                # sequence-parallel staging needs every process to hold the
                # full sequence dim; dp×sp with per-process rows would need a
                # 2-level make_global_array — dp-only is the launch topology
                raise ValueError(
                    "rows_are_local training does not compose with ring "
                    "(sequence-parallel) attention; use attention='local'")
            from incubator_predictionio_tpu.parallel.staging import (
                stage_sharded_batches,
            )

            if use_pipeline and cfg.batch_size % (
                    pipe_m * ctx.axis_size(ctx.data_axis)):
                raise ValueError(
                    f"batch_size={cfg.batch_size} must be a multiple of "
                    f"pipeline_microbatches × data axis "
                    f"({pipe_m} × {ctx.axis_size(ctx.data_axis)})")
            (tb, pb, yb, wb), w_pad, _ = stage_sharded_batches(
                ctx,
                (tokens.astype(np.int32),
                 np.ascontiguousarray(positions, np.int32),
                 targets.astype(np.int32),
                 weights.astype(np.float32)),
                cfg.batch_size, cfg.seed,
            )
            # padding rows were resampled from real rows: zero their loss
            # weight via the staging weight column
            wb = wb * w_pad[..., None]
        else:
            global_batch = ctx.pad_to_batch_multiple(min(cfg.batch_size, max(n, 1)))
            if use_pipeline:
                # the GPipe schedule needs batch % (microbatches × data) == 0;
                # round up — extra rows are zero-weight padding
                mult = pipe_m * ctx.axis_size(ctx.data_axis)
                global_batch = -(-global_batch // mult) * mult
            n_batches = max(1, (n + global_batch - 1) // global_batch)
            n_pad = n_batches * global_batch
            pad = n_pad - n

            def stage(a, fill=0):
                a = np.concatenate([a, np.full((pad, *a.shape[1:]), fill, a.dtype)])
                a = a.reshape(n_batches, global_batch, *a.shape[1:])
                seq_axis = "seq" if use_ring else None
                return ctx.put(a, None, ctx.data_axis, seq_axis)

            tb = stage(tokens.astype(np.int32))
            pb = stage(positions.astype(np.int32))
            yb = stage(targets.astype(np.int32))
            wb = stage(weights.astype(np.float32))

        # fused on-device init: ONE dispatch for the whole pytree (per-tensor
        # jax.random calls cost a device round trip each — seconds behind a
        # tunnel); multi-process still inits on host and replicates.
        # cache_cfg normalizes fields the executables don't depend on (seed,
        # checkpointing) so e.g. a different seed reuses the same jit cache
        cache_cfg = dataclasses.replace(
            cfg, seed=0, checkpoint_dir=None, checkpoint_every=0)
        init = _jit_init_fn(cache_cfg)
        expert_parallel = bool(cfg.n_experts) and "expert" in ctx.mesh.shape
        if cfg.n_experts and not expert_parallel:
            # once-per-key warning + machine-readable record (the MULTICHIP
            # dryrun embeds sharding.degrade.degradations() in its JSON
            # instead of tailing one stderr line per fit)
            from incubator_predictionio_tpu.sharding.degrade import (
                record_axis_degradation,
            )

            record_axis_degradation(
                "transformer.moe", "expert", f"n_experts={cfg.n_experts}",
                ctx.mesh.shape, "expert tables stay replicated")
        if expert_parallel and cfg.n_experts % ctx.axis_size("expert"):
            raise ValueError(
                f"n_experts={cfg.n_experts} must divide evenly over the "
                f"expert axis ({ctx.axis_size('expert')} devices)")
        tensor_parallel = cfg.tensor_parallel and "model" in ctx.mesh.shape
        if cfg.tensor_parallel and not tensor_parallel:
            from incubator_predictionio_tpu.sharding.degrade import (
                record_axis_degradation,
            )

            record_axis_degradation(
                "transformer.tp", "model", "tensor_parallel",
                ctx.mesh.shape, "weights stay replicated")
        if tensor_parallel:
            tp = ctx.axis_size("model")
            if cfg.n_heads % tp or (4 * cfg.d_model) % tp:
                raise ValueError(
                    f"tensor parallelism needs n_heads ({cfg.n_heads}) and "
                    f"the FFN hidden dim ({4 * cfg.d_model}) divisible by "
                    f"the model axis ({tp})")
            if use_pipeline or cfg.n_experts:
                # MoE expert tables have a different parallel layout (the
                # expert axis); mixing the placements is unsupported
                raise ValueError(
                    "tensor parallelism composes with dp/sp, not with the "
                    "pipeline or MoE placements")
        if ctx.process_count == 1 and not (
                expert_parallel or use_pipeline or tensor_parallel):
            params = ctx.replicate(init(jax.random.key(cfg.seed)))
        else:
            # one batched device→host pull (per-leaf np.asarray costs one
            # round trip per leaf — see MeshContext.host_gather)
            host_params = jax.device_get(init(jax.random.key(cfg.seed)))
            if expert_parallel:
                params = _place_params_expert_sharded(ctx, host_params)
            elif use_pipeline:
                params = _place_params_pipe_sharded(ctx, host_params)
            elif tensor_parallel:
                params = _place_params_tensor_sharded(ctx, host_params)
            else:
                params = ctx.replicate(host_params)
        from incubator_predictionio_tpu.utils.optim import jit_adam_init

        opt_state = jit_adam_init(
            cfg.learning_rate, cfg.adam_moments_dtype)(params)
        train_epochs = _train_epochs_fn(
            cache_cfg, ctx.mesh, use_ring,
            use_pipeline=use_pipeline, data_axis=ctx.data_axis)

        from incubator_predictionio_tpu.utils.checkpoint import checkpointed_epochs

        import time as _time

        t_train = _time.perf_counter()
        params, opt_state, loss = checkpointed_epochs(
            cfg.checkpoint_dir, cfg.checkpoint_every, cfg.checkpoint_keep,
            cfg.epochs, params, opt_state, ctx.mesh,
            lambda p, o, n: train_epochs(p, o, tb, pb, yb, wb, n),
        )
        final_loss = float(loss) if loss is not None else float("nan")
        t_train = _time.perf_counter() - t_train  # float(loss) blocked above
        t_gather = _time.perf_counter()
        host_trained = ctx.host_gather(params)
        if use_pipeline:
            host_trained = _unstack_layers(host_trained, cfg.n_layers)
        model = TransformerModel(host_trained, item_map, cfg)
        model.final_loss = final_loss
        model.timings = {"train_sec": round(t_train, 4),
                         "gather_sec": round(_time.perf_counter() - t_gather, 4)}
        return model

    # -- inference --------------------------------------------------------
    @staticmethod
    def next_item_scores(model: TransformerModel, history_tokens: np.ndarray) -> np.ndarray:
        """history_tokens: [B, max_len] (left-padded) → [B, vocab] scores."""
        cfg = model.config
        positions = np.broadcast_to(
            np.arange(cfg.max_len, dtype=np.int32), history_tokens.shape
        )
        return np.asarray(_serve_scores(
            model.params, jnp.asarray(history_tokens), jnp.asarray(positions),
            cfg,
        ))


@partial(jax.jit, static_argnames=("cfg",))
def _serve_scores(params, tokens, positions, cfg):
    h, _ = _forward(params, tokens, positions, cfg)  # local attention at serving
    last = h[:, -1, :]  # left-padded → last position holds the newest item
    return _bf16_matmul(last, params["item_emb"].T)
