"""Server plugin SPIs.

Parity targets:
- Engine server plugins (core/.../workflow/EngineServerPlugin.scala:24-41):
  ``outputblocker`` synchronously transforms the prediction JSON on the query
  path; ``outputsniffer`` observes it asynchronously.
- Event server plugins (data/.../api/EventServerPlugin.scala:22):
  ``inputblocker`` can reject/transform incoming event JSON; ``inputsniffer``
  observes it.

Mechanism swap: the reference discovers plugins via java ServiceLoader
(EngineServerPluginContext.scala:57); here plugins register explicitly (import
side effect or programmatic call) — the same replacement the storage registry
makes for class-name reflection.
"""

from __future__ import annotations

import abc
import logging
from typing import Any

logger = logging.getLogger(__name__)


class EngineServerPlugin(abc.ABC):
    """(EngineServerPlugin.scala:24)"""

    OUTPUTBLOCKER = "outputblocker"
    OUTPUTSNIFFER = "outputsniffer"

    name: str = "plugin"
    description: str = ""
    output_type: str = OUTPUTSNIFFER

    def start(self, context: Any) -> None:
        pass

    @abc.abstractmethod
    def process(self, engine_instance: Any, query: dict, prediction: Any,
                context: Any) -> Any:
        """outputblocker: return the (possibly transformed) prediction;
        outputsniffer: return value ignored."""

    def handle_rest(self, path: str, params: dict) -> Any:
        """Backs /plugins/<type>/<name>/* routes."""
        return {}


class EventServerPlugin(abc.ABC):
    """(EventServerPlugin.scala:22)"""

    INPUTBLOCKER = "inputblocker"
    INPUTSNIFFER = "inputsniffer"

    name: str = "plugin"
    description: str = ""
    input_type: str = INPUTSNIFFER

    def start(self, context: Any) -> None:
        pass

    @abc.abstractmethod
    def process(self, event_info: dict, context: Any) -> Any:
        """inputblocker: raise to reject, or return transformed event JSON;
        inputsniffer: return value ignored."""

    def handle_rest(self, path: str, params: dict) -> Any:
        return {}


ENGINE_SERVER_PLUGINS: dict[str, EngineServerPlugin] = {}
EVENT_SERVER_PLUGINS: dict[str, EventServerPlugin] = {}


def register_engine_server_plugin(plugin: EngineServerPlugin) -> None:
    ENGINE_SERVER_PLUGINS[plugin.name] = plugin


def register_event_server_plugin(plugin: EventServerPlugin) -> None:
    EVENT_SERVER_PLUGINS[plugin.name] = plugin


def engine_plugins(output_type: str) -> list[EngineServerPlugin]:
    return [p for p in ENGINE_SERVER_PLUGINS.values() if p.output_type == output_type]


def event_plugins(input_type: str) -> list[EventServerPlugin]:
    return [p for p in EVENT_SERVER_PLUGINS.values() if p.input_type == input_type]


def apply_output_plugins(engine_instance, query: dict, prediction: Any) -> Any:
    """Blockers fold over the prediction; sniffers observe (CreateServer.scala:573-577)."""
    for plugin in engine_plugins(EngineServerPlugin.OUTPUTBLOCKER):
        prediction = plugin.process(engine_instance, query, prediction, None)
    for plugin in engine_plugins(EngineServerPlugin.OUTPUTSNIFFER):
        try:
            plugin.process(engine_instance, query, prediction, None)
        except Exception:  # noqa: BLE001 - sniffers must not break serving
            logger.exception("outputsniffer %s failed", plugin.name)
    return prediction


def apply_input_plugins(event_json: dict) -> dict:
    """Blockers may reject (raise) or transform; sniffers observe
    (EventServer.scala plugin hooks)."""
    for plugin in event_plugins(EventServerPlugin.INPUTBLOCKER):
        result = plugin.process(event_json, None)
        if isinstance(result, dict):
            event_json = result
    for plugin in event_plugins(EventServerPlugin.INPUTSNIFFER):
        try:
            plugin.process(event_json, None)
        except Exception:  # noqa: BLE001
            logger.exception("inputsniffer %s failed", plugin.name)
    return event_json
