"""Shared bootstrap for servers that raise the native HTTP front.

One place owns the dance both the event server and the query server need:
bind the aiohttp runner to an ephemeral loopback BACKEND port, start the
native epoll front on the public (ip, port) with the given hot routes, and
— if the front fails to come up (no native lib, port busy) — tear the
runner down so the caller can rebuild it bound to the public port directly.
This also confines the one unavoidable private-API poke (reading the bound
port off ``site._server``) to a single line.
"""

from __future__ import annotations

import logging
from typing import Optional

from aiohttp import web

logger = logging.getLogger(__name__)


async def start_with_native_front(
    runner: web.AppRunner,
    ip: str,
    port: int,
    handler,
    hot_routes: str,
    label: str,
):
    """Try to boot ``runner`` behind the native front.

    Returns the front handle on success (the runner is serving on an
    internal loopback port). Returns ``None`` on failure — the runner has
    been cleaned up and the caller must create a fresh one for the plain
    path (an AppRunner cannot be re-setup after cleanup)."""
    from incubator_predictionio_tpu import native

    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    backend_port = site._server.sockets[0].getsockname()[1]  # noqa: SLF001
    front = native.http_front_start(ip, port, backend_port, handler,
                                    hot_routes=hot_routes)
    if front is not None:
        logger.info("%s listening on %s:%d (native front; aiohttp backend "
                    "on 127.0.0.1:%d)", label, ip, port, backend_port)
        return front
    await runner.cleanup()
    return None
