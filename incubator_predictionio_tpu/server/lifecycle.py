"""Graceful-drain plumbing shared by the three servers (docs/resilience.md).

SIGTERM on any server must mean "stop taking new work, finish what you
have, flush durable state, exit within a deadline" — never "drop in-flight
requests on the floor". The pieces every server shares live here:

- :class:`DrainState` — the draining flag plus its observable surface
  (``pio_server_draining`` gauge per server, the 503 + ``Retry-After``
  response new work receives, the ``/health`` status flip);
- :func:`install_signal_drain` — SIGTERM/SIGINT → one-shot asyncio event
  on the server's loop (second signal forces immediate exit, the standard
  escalation contract so a wedged drain can't make the process unkillable).

Each server owns its *drain semantics* (what "finish what you have" means:
the event server flushes the spill WAL, the query server waits out the
micro-batcher, the storage server just stops accepting); this module only
standardizes the shell around them.
"""

from __future__ import annotations

import asyncio
import logging
import signal
from typing import Optional

from aiohttp import web

from incubator_predictionio_tpu.obs.metrics import REGISTRY

logger = logging.getLogger(__name__)

_DRAINING = REGISTRY.gauge(
    "pio_server_draining",
    "1 while the server is draining (rejecting new work ahead of a "
    "graceful exit), 0 otherwise", labels=("server",))


class DrainState:
    """One server's draining flag + the shared rejection/health surface."""

    def __init__(self, server_name: str, retry_after_sec: int = 5):
        self.server_name = server_name
        self.retry_after_sec = retry_after_sec
        self._draining = False
        _DRAINING.labels(server=server_name).set(0)

    @property
    def draining(self) -> bool:
        return self._draining

    def begin(self) -> None:
        if not self._draining:
            self._draining = True
            _DRAINING.labels(server=self.server_name).set(1)
            logger.info("%s: draining — new work answers 503",
                        self.server_name)

    def reject_response(self) -> web.Response:
        """The 503 new work gets while draining. ``Retry-After`` points
        clients at the replacement process a rolling restart brings up."""
        return web.json_response(
            {"message": f"{self.server_name} is draining"}, status=503,
            headers={"Retry-After": str(self.retry_after_sec)})

    def health_status(self, degraded: bool) -> str:
        """``/health`` status string: draining wins over degraded/ok so
        load balancers pull the instance before its listener goes away."""
        if self._draining:
            return "draining"
        return "degraded" if degraded else "ok"


def install_signal_drain(loop: asyncio.AbstractEventLoop,
                         stop_event: asyncio.Event,
                         server_name: str) -> None:
    """SIGTERM/SIGINT set ``stop_event`` (the serve_forever loop then runs
    the server's drain); a second signal exits immediately — a drain stuck
    on a dead backend must never make the process unkillable."""
    fired = {"n": 0}

    def on_signal(signum: int) -> None:
        fired["n"] += 1
        if fired["n"] > 1:
            logger.warning("%s: second signal (%s) — exiting immediately",
                           server_name, signal.Signals(signum).name)
            raise SystemExit(1)
        logger.info("%s: received %s — beginning graceful drain",
                    server_name, signal.Signals(signum).name)
        stop_event.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, on_signal, sig)
        except (NotImplementedError, RuntimeError):  # pragma: no cover
            # non-main thread / platforms without loop signal support:
            # fall back to the default handler (immediate exit)
            pass


async def wait_for(predicate, deadline_sec: float,
                   poll_sec: float = 0.02) -> bool:
    """Poll ``predicate()`` until true or the deadline passes. The drain
    loops use this for 'in-flight work finished' conditions that have no
    native awaitable."""
    import time

    deadline = time.monotonic() + deadline_sec
    while time.monotonic() < deadline:
        if predicate():
            return True
        await asyncio.sleep(poll_sec)
    return bool(predicate())


def drained_exit_deadline(default: float = 20.0) -> float:
    """`PIO_DRAIN_DEADLINE` (seconds) — the cap every server's drain honors
    before force-exiting (systemd's TimeoutStopSec counterpart)."""
    import os

    try:
        return float(os.environ.get("PIO_DRAIN_DEADLINE", default))
    except ValueError:
        return default


__all__ = ["DrainState", "install_signal_drain", "wait_for",
           "drained_exit_deadline"]
