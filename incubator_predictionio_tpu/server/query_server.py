"""Engine (query) server — the ``pio deploy`` surface.

Parity target: workflow/CreateServer.scala:106-695. One deployed engine per
server process; routes:

- ``GET /``              — status page (engine info + serving stats, the
                           reference's twirl HTML page becomes JSON/HTML)
- ``POST /queries.json`` — the hot path: bind query → supplement →
                           per-algorithm predict → serve → JSON
- ``POST /reload``       — re-load the latest COMPLETED instance (MasterActor
                           ReloadServer, CreateServer.scala:317-343)
- ``POST /stop``         — graceful shutdown (auth via server access key)
- ``GET /plugins.json``  — engine-server plugin listing

Design notes vs the reference:
- the reference calls algorithms sequentially per query with a "TODO:
  Parallelize" (CreateServer.scala:488); our predict path is a resident
  jit-compiled function per algorithm, and the (tiny) per-query host work is
  done inline — the TPU round-trip dominates, so the fix the reference never
  shipped is batching, which ``batch_predict`` exposes for bulk callers;
- models are made device-resident once at deploy (prepare_for_serving), not
  re-loaded per query;
- the optional feedback loop POSTs a ``predict`` event back to the event
  server asynchronously, with prId generation like CreateServer.scala:508-570.
"""

from __future__ import annotations

import asyncio
import contextvars
import dataclasses
import datetime as _dt
import hashlib
import json
import logging
import os
import time
import uuid
from typing import Any, Optional

from aiohttp import web

from incubator_predictionio_tpu.obs.http import (
    add_observability_routes,
    telemetry_middleware,
)
from incubator_predictionio_tpu.obs import profile as _profile
from incubator_predictionio_tpu.obs import slo as _slo
from incubator_predictionio_tpu.obs.metrics import (
    REGISTRY,
    LatencyReservoir,
)
from incubator_predictionio_tpu.resilience.admission import (
    BROWNOUT,
    REJECT,
    AdmissionConfig,
    AdmissionController,
    ShedExpired,
)
from incubator_predictionio_tpu.resilience.breaker import publish_breaker_metrics
from incubator_predictionio_tpu.streaming.stream_metrics import (
    APPLIED as _STREAM_APPLIED,
    DEDUPED as _STREAM_DEDUPED,
    STALENESS as _STREAM_STALENESS,
)

from incubator_predictionio_tpu.core.controller import (
    Engine,
    EngineParams,
    resolve_engine_factory,
    variant_from_file,
)
from incubator_predictionio_tpu.data.storage.base import EngineInstance
from incubator_predictionio_tpu.data.storage.registry import Storage, get_storage
from incubator_predictionio_tpu.parallel.mesh import MeshContext
from incubator_predictionio_tpu.resilience.breaker import (
    BREAKERS,
    CircuitBreaker,
    CircuitOpenError,
)
from incubator_predictionio_tpu.resilience.clock import SYSTEM_CLOCK, Clock
from incubator_predictionio_tpu.resilience.policy import (
    DeadlineExceeded,
    ServingUnavailable,
    run_with_deadline,
)
from incubator_predictionio_tpu.server.lifecycle import (
    DrainState,
    drained_exit_deadline,
    install_signal_drain,
    wait_for,
)
from incubator_predictionio_tpu.utils import jitstats
from incubator_predictionio_tpu.utils.json_util import bind_query, to_jsonable
from incubator_predictionio_tpu.utils.serialization import deserialize_model

logger = logging.getLogger(__name__)

# -- telemetry (obs/, docs/observability.md) --------------------------------
_DEGRADED = REGISTRY.counter(
    "pio_serving_degraded_total",
    "Queries answered from the degradation path (last-good cache / serving "
    "default) instead of a live prediction")
_G_REQUESTS = REGISTRY.gauge(
    "pio_serving_requests", "Successfully served queries (this process)")
_G_BATCHES = REGISTRY.gauge(
    "pio_serving_batches", "Micro-batches dispatched to the device")
_G_MAX_BATCH = REGISTRY.gauge(
    "pio_serving_max_batch_seen", "Largest micro-batch coalesced so far")
_G_LATENCY_Q = REGISTRY.gauge(
    "pio_serving_latency_seconds",
    "Serving latency split into its terms (exact reservoir quantiles)",
    labels=("stage", "quantile"))
_G_DEV_MEM = REGISTRY.gauge(
    "pio_device_bytes_in_use",
    "Accelerator memory in use (the device_memory_report fold)",
    labels=("device",))
_ROLLBACKS = REGISTRY.counter(
    "pio_deploy_rollbacks_total",
    "Reloads rejected by the smoke-query gate or auto-rolled back during "
    "the post-swap probation window (docs/resilience.md)")
_H_TEMPLATE_BATCH = REGISTRY.histogram(
    "pio_serving_template_batch_size",
    "Live queries per coalesced batch_predict dispatch, per algorithm class "
    "— proves the micro-batcher's coalescing reaches the vectorized "
    "template paths (docs/serving.md)",
    labels=("template",),
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0))

#: per-algorithm wall times of the current dispatch, set by ``predict_batch``
#: and read back from the SAME Context object after ``Context.run`` returns
#: (writes inside ``ctx.run`` persist in ``ctx``) — per-dispatch state with
#: no shared attribute, so overlapping dispatches can never swap timings
_DISPATCH_ALGO_TIMES: contextvars.ContextVar[list] = contextvars.ContextVar(
    "pio_dispatch_algo_times")


@dataclasses.dataclass
class ServerConfig:
    """(CreateServer.scala:106-175 flags)"""

    engine_variant: str = "engine.json"
    ip: str = "0.0.0.0"
    port: int = 8000
    feedback: bool = False
    ssl_cert: Optional[str] = None  # TLS (reference SSLConfiguration.scala:30)
    ssl_key: Optional[str] = None
    event_server_ip: str = "127.0.0.1"
    event_server_port: int = 7070
    access_key: Optional[str] = None  # for feedback events
    server_access_key: Optional[str] = None  # guards /stop and /reload
    max_batch: int = 64  # micro-batch cap for /queries.json (1 = no batching)
    # concurrent dispatches (host prep overlaps device time). None = auto:
    # overlap (2) only when every deployed algorithm declares
    # ``serving_thread_safe``; otherwise strict predict_batch serialization
    # (1) — custom engines with non-thread-safe predict code must never race
    # by default. An explicit int overrides in either direction.
    max_in_flight: Optional[int] = None
    log_url: Optional[str] = None  # remote error-log shipping (CreateServer.scala:423-436)
    log_prefix: str = ""  # prepended to shipped log messages
    # -- graceful degradation (resilience/) -------------------------------
    # total per-query budget: a query still unanswered after this many
    # seconds gets a degraded-but-valid response (last-good cache or the
    # serving layer's default), never a 500. Also propagated to storage
    # calls under the predict path via deadline_scope. None disables.
    query_timeout_sec: Optional[float] = None
    # per-algorithm deadline: an algorithm slower than this counts a
    # breaker failure even when it eventually answers. None disables.
    algo_deadline_sec: Optional[float] = None
    # consecutive failures before an algorithm's breaker opens, and how
    # long it stays open before a half-open probe
    algo_breaker_threshold: int = 3
    algo_breaker_reset_sec: float = 10.0
    # -- crash-safe model lifecycle (docs/resilience.md) ------------------
    # smoke queries the /reload health gate runs against the NEW instance
    # before it may serve: any exception keeps the live instance and
    # answers 409. Payload dicts, exactly as POSTed to /queries.json.
    smoke_queries: tuple = ()
    # seconds after a successful swap during which a serving-breaker trip
    # auto-rolls back to the previous (pinned) instance; 0 disables
    reload_probation_sec: float = 30.0
    # -- overload protection (resilience/admission.py) --------------------
    # bounded admission queue: queries beyond this many waiting requests
    # are rejected at the door with 429 + pressure-derived Retry-After
    # (docs/resilience.md "Overload & admission control")
    admission_max_queue: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_ADMISSION_MAX_QUEUE", "256")))
    # adaptive concurrency limiter: AIMD on observed latency, live-resizes
    # the micro-batcher's dispatch slots within [1, effective max]
    admission_adaptive: bool = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "PIO_ADMISSION_ADAPTIVE", "1") != "0")
    # explicit latency target for the limiter (ms); unset = gradient mode
    # (the target tracks a rolling-minimum latency baseline)
    admission_target_ms: Optional[float] = dataclasses.field(
        default_factory=lambda: (
            float(os.environ["PIO_ADMISSION_TARGET_MS"])
            if os.environ.get("PIO_ADMISSION_TARGET_MS") else None))
    # brownout hysteresis: saturation (predicted wait ≥ enter_frac of the
    # deadline) sustained for enter_sec flips the server to the degraded
    # path; exit needs exit_sec of clear air
    brownout_enter_frac: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_BROWNOUT_ENTER_FRAC", "0.5")))
    brownout_enter_sec: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_BROWNOUT_ENTER_SEC", "1.0")))
    brownout_exit_sec: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_BROWNOUT_EXIT_SEC", "2.0")))
    # -- multi-host shard ownership (docs/sharding.md) --------------------
    # when both are set this process serves only item rows
    # ShardSpec(n_items, shard_count).shard_bounds(shard_id) via
    # POST /shard/queries.json partials, announced through
    # /health.deployment.shardOwner for the fleet router's scatter/gather
    shard_id: Optional[int] = dataclasses.field(
        default_factory=lambda: (
            int(os.environ["PIO_FLEET_SHARD_ID"])
            if os.environ.get("PIO_FLEET_SHARD_ID") else None))
    shard_count: Optional[int] = dataclasses.field(
        default_factory=lambda: (
            int(os.environ["PIO_FLEET_SHARD_COUNT"])
            if os.environ.get("PIO_FLEET_SHARD_COUNT") else None))
    # where the owner's fencing epoch persists (atomic-write discipline);
    # unset = in-memory epoch only (tests, throwaway owners)
    shard_state_dir: Optional[str] = dataclasses.field(
        default_factory=lambda: (
            os.environ.get("PIO_FLEET_SHARD_STATE_DIR") or None))


class DeployedEngine:
    """Holds the live models + stages for one engine instance."""

    def __init__(
        self,
        engine: Engine,
        engine_params: EngineParams,
        instance: EngineInstance,
        models: list[Any],
        max_batch: int = 64,
        warmup: bool = True,
        algo_deadline: Optional[float] = None,
        breaker_threshold: int = 3,
        breaker_reset: float = 10.0,
        clock: Clock = SYSTEM_CLOCK,
    ):
        self.engine = engine
        self.engine_params = engine_params
        self.instance = instance
        algorithms, serving = engine.serving_and_algorithms(engine_params)
        self.algorithms = algorithms
        self.serving = serving
        self.models = [
            self._prepare(a, m) for a, m in zip(algorithms, models)
        ]
        self.query_cls = next(
            (a.query_class() for a in algorithms if a.query_class() is not None), None
        )
        # per-algorithm circuit breakers: a consistently failing (or, with
        # algo_deadline set, consistently slow) algorithm is skipped and the
        # remaining algorithms keep serving — not registered in the global
        # BREAKERS registry because their lifetime is this deployment's
        # (reload/tests build fresh engines; /health composes both views)
        self.algo_deadline = algo_deadline
        self._clock = clock
        self.algo_breakers = [
            CircuitBreaker(f"algorithm:{i}:{type(a).__name__}",
                           failure_threshold=breaker_threshold,
                           reset_timeout=breaker_reset, clock=clock)
            for i, a in enumerate(algorithms)
        ]
        if warmup:
            self.warmup(max_batch)

    @staticmethod
    def _prepare(algorithm, model):
        """Models exposing ``prepare_for_serving()`` become device-resident here."""
        prep = getattr(model, "prepare_for_serving", None)
        return prep() if callable(prep) else model

    def warmup(self, max_batch: int) -> None:
        """Pre-compile every serving batch bucket at deploy time so no live
        query ever pays an XLA compile (the round-2 p50 regression)."""
        for m in self.models:
            w = getattr(m, "warmup", None)
            if callable(w):
                w(max_batch)

    def _record_algo_timing(self, idx: int, took: float) -> None:
        """Success bookkeeping with the per-algorithm deadline: a completed
        call slower than the deadline still counts as a breaker failure —
        an algorithm that keeps blowing its budget should be skipped, not
        waited on."""
        brk = self.algo_breakers[idx]
        if self.algo_deadline is not None and took > self.algo_deadline:
            brk.record_failure()
        else:
            brk.record_success()

    def _record_batch_outcome(self, ai: int, results: dict[int, Any],
                              took: float, single_call: bool) -> None:
        """Breaker verdict for one algorithm's share of a batch: healthy if
        ANY query got a prediction, healthy if every failure is
        query-semantic (bad queries, not a bad algorithm), failing only
        when every query died with an infrastructure-class error."""
        vals = list(results.values())
        if any(not isinstance(v, Exception) for v in vals):
            if single_call:
                self._record_algo_timing(ai, took)
            else:
                self.algo_breakers[ai].record_success()
        elif vals and all(isinstance(v, (TypeError, ValueError, KeyError))
                          for v in vals):
            self.algo_breakers[ai].record_success()
        else:
            self.algo_breakers[ai].record_failure()

    def _live_algorithms(self) -> list[int]:
        live = [i for i in range(len(self.algorithms))
                if self.algo_breakers[i].allow()]
        if not live:
            raise ServingUnavailable(
                "all algorithms have open circuit breakers")
        return live

    def predict(self, payload: dict) -> Any:
        query = bind_query(self.query_cls, payload)
        query = self.serving.supplement(query)
        predictions = []
        live = self._live_algorithms()
        # _live_algorithms admitted a (possibly half-open-probe) slot on
        # EVERY live breaker; if an early algorithm raises, the later ones
        # never get an outcome — hand their slots back or they wedge
        pending = set(live)
        try:
            for i in live:
                t0 = self._clock.monotonic()
                try:
                    predictions.append(
                        self.algorithms[i].predict(self.models[i], query))
                except (TypeError, ValueError, KeyError):
                    # query-semantic rejection (unknown entity, bad shape):
                    # the algorithm is healthy — a run of bad queries must
                    # not trip its breaker and degrade everyone's traffic
                    pending.discard(i)
                    self.algo_breakers[i].record_success()
                    raise
                except Exception:
                    pending.discard(i)
                    self.algo_breakers[i].record_failure()
                    raise
                pending.discard(i)
                self._record_algo_timing(i, self._clock.monotonic() - t0)
        finally:
            for j in pending:
                self.algo_breakers[j].release_probe()
        return self.serving.serve(query, predictions)

    def predict_batch(self, payloads: list[dict]) -> list[Any]:
        """Batched predict: one ``batch_predict`` device dispatch per
        algorithm instead of one per query — the fix for the reference's
        unshipped 'TODO: Parallelize' (CreateServer.scala:488). Returns one
        result OR exception per payload (bad queries don't fail the batch).

        Degradation semantics (resilience/): algorithms whose breaker is
        open are skipped; an algorithm that raises is retried query-by-query
        so a poison query fails alone, and a breaker failure is counted only
        when an algorithm fails every query with an infrastructure-class
        error (backend down, model broken) — all-semantic failures (bad
        queries) leave the breaker alone.
        Queries are served from whichever algorithms survived; a query no
        algorithm could answer carries its first error."""
        out: list[Any] = [None] * len(payloads)
        bound: list[Any] = [None] * len(payloads)
        for i, p in enumerate(payloads):
            try:
                bound[i] = self.serving.supplement(bind_query(self.query_cls, p))
            except (TypeError, ValueError, KeyError) as e:
                out[i] = e
        live = [i for i in range(len(payloads)) if out[i] is None]
        if not live:
            return out
        try:
            algo_live = self._live_algorithms()
        except ServingUnavailable as e:
            for i in live:
                out[i] = e
            return out
        per_algo: dict[int, dict[int, Any]] = {}  # algo idx -> query idx -> pred/exc
        algo_times: list[tuple[str, float]] = []
        for ai in algo_live:
            a, m = self.algorithms[ai], self.models[ai]
            _H_TEMPLATE_BATCH.labels(template=type(a).__name__).observe(
                len(live))
            t0 = self._clock.monotonic()
            healed = False
            try:
                got = dict(a.batch_predict(m, [(i, bound[i]) for i in live]))
                for i in live:
                    if i not in got:
                        # sparse batch result: heal per query (the pre-
                        # resilience code recovered this case through its
                        # KeyError → retry-all path)
                        healed = True
                        try:
                            got[i] = a.predict(m, bound[i])
                        except Exception as e:  # noqa: BLE001
                            got[i] = e
                per_algo[ai] = {i: got[i] for i in live}
            except Exception:  # noqa: BLE001 - isolate the failing query
                # a query may have poisoned the whole batch: retry one by
                # one so only the offender fails
                healed = True
                singles: dict[int, Any] = {}
                for i in live:
                    try:
                        singles[i] = a.predict(m, bound[i])
                    except Exception as e:  # noqa: BLE001
                        singles[i] = e
                per_algo[ai] = singles
            took = self._clock.monotonic() - t0
            algo_times.append((f"algo{ai}.{type(a).__name__}", took))
            self._record_batch_outcome(
                ai, per_algo[ai], took,
                # the per-call deadline is only meaningful when the elapsed
                # time WAS one call: a single-query batch with no heals.
                # Judging it against a coalesced N-query dispatch (or a
                # batch attempt plus N retries) would brand a healthy
                # algorithm slow exactly under peak load
                single_call=(len(live) == 1 and not healed))
        # the per-batch cost is per-dispatch state (a coalesced batch shares
        # one device round trip): publish via the dispatch's own context so
        # overlapping dispatches cannot swap each other's timings
        _DISPATCH_ALGO_TIMES.set(algo_times)
        for i in live:
            preds, first_err = [], None
            for ai in algo_live:
                v = per_algo[ai][i]
                if isinstance(v, Exception):
                    first_err = first_err or v
                else:
                    preds.append(v)
            if not preds:
                out[i] = first_err or ServingUnavailable(
                    "no algorithm produced a prediction")
                continue
            try:
                out[i] = self.serving.serve(bound[i], preds)
            except Exception as e:  # noqa: BLE001
                out[i] = e
        return out


class _Delivered:
    """Marker wrapper the dispatcher resolves futures with: the payload's
    result plus the batch's per-algorithm timings. A distinct type (not a
    tuple) so a prediction that happens to BE a tuple can never be mistaken
    for the envelope; error paths deliver bare exceptions."""

    __slots__ = ("result", "algo_times")

    def __init__(self, result: Any, algo_times: list):
        self.result = result
        self.algo_times = algo_times


class MicroBatcher:
    """Continuous micro-batching for the query hot path.

    Requests enqueue; a single drainer coalesces everything that arrived
    while the previous batch was on the device into ONE ``predict_batch``
    dispatch (capped at ``max_batch``). No artificial wait is added — an idle
    server serves single queries at single-query latency, a loaded server
    amortizes the device round-trip across the whole in-flight window. The
    batch executes in a worker thread so the event loop keeps accepting
    requests mid-dispatch, and up to ``max_in_flight`` batches overlap: the
    next batch's host prep (query binding, padding, bucketing) runs while
    the previous one computes — a burst no longer serializes host work
    behind device work (the round-3 p99 tail, VERDICT r3 #6).

    Tail observability: ``queue_delay`` (submit → batch assembly) and
    ``dispatch`` (assembly → results) reservoirs split the latency into its
    two terms; both are exposed on the status page.

    Overload protection (resilience/admission.py): each request is tagged
    with its deadline at enqueue; batch assembly evicts entries whose
    deadline already expired (their futures resolve :class:`ShedExpired`
    → 504) instead of wasting a device dispatch on work nobody is waiting
    for. Deadline decisions run on the injected clock, so they are
    deterministic under ``FakeClock``.
    """

    def __init__(self, deployed: DeployedEngine, max_batch: int = 64,
                 max_in_flight: int = 2,
                 deadline_sec: Optional[float] = None,
                 clock: Clock = SYSTEM_CLOCK,
                 admission: Optional[AdmissionController] = None):
        self.deployed = deployed
        self.max_batch = max_batch
        self.max_in_flight = max_in_flight
        # per-batch budget, propagated into the worker thread as the
        # ambient deadline so storage calls under predict inherit it
        self.deadline_sec = deadline_sec
        self._clock = clock
        self._admission = admission  # shed bookkeeping only (may be None)
        self.queue: asyncio.Queue = asyncio.Queue()
        self.batches_served = 0
        self.max_batch_seen = 0
        self.shed_expired = 0
        self.queue_delay = LatencyReservoir()
        self.dispatch_sec = LatencyReservoir()
        self._task: Optional[asyncio.Task] = None
        self._sem: Optional[asyncio.Semaphore] = None
        self._inflight: set[asyncio.Task] = set()
        self._stopped = False

    def start(self) -> None:
        if self._stopped:
            raise RuntimeError("server shutting down")
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._drain())

    async def stop(self) -> None:
        """Cancel the drainer and fail everything still queued so callers
        don't hang until aiohttp force-cancels them."""
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        while True:
            try:
                entry = self.queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            fut = entry[1]
            if not fut.done():
                fut.set_result(RuntimeError("server shutting down"))

    async def submit(self, payload: dict) -> Any:
        return (await self.submit_timed(payload))[0]

    async def submit_timed(self, payload: dict) -> tuple[Any, list]:
        """Submit and also return the dispatch's per-algorithm wall times
        (the X-PIO-Server-Timing source) — per-call data, never read off
        shared state, so overlapping dispatches can't swap timings."""
        self.start()
        fut = asyncio.get_running_loop().create_future()
        # deadline tagged at enqueue (docs/resilience.md shedding order):
        # batch assembly evicts this entry with ShedExpired once it passes
        deadline_at = (self._clock.monotonic() + self.deadline_sec
                       if self.deadline_sec is not None else None)
        # carry the submitter's contextvars (trace identity from the
        # telemetry middleware) — the dispatch worker thread re-enters the
        # first request's context so storage calls under predict stay on the
        # caller's trace (coalesced followers share that dispatch span)
        await self.queue.put((payload, fut, time.perf_counter(),
                              contextvars.copy_context(), deadline_at))
        try:
            got = await fut
        except asyncio.CancelledError:
            # the waiter is gone (handler timeout/disconnect): mark the
            # queued entry abandoned so assembly drops it silently instead
            # of counting it as a shed the caller never saw
            fut.cancel()
            raise
        if isinstance(got, _Delivered):
            result, algo_times = got.result, got.algo_times
        else:  # error paths deliver bare exceptions
            result, algo_times = got, []
        if isinstance(result, Exception):
            raise result
        return result, algo_times

    async def resize(self, n: int) -> None:
        """Resize the dispatch-slot semaphore live (reload can swap in an
        engine with a different thread-safety posture; the adaptive
        admission limiter shrinks/grows it under load). Growing releases
        slots immediately; shrinking acquires the excess — waiting out
        in-flight dispatches — so the new bound is real, not advisory."""
        n = max(1, n)
        delta = n - self.max_in_flight
        self.max_in_flight = n
        if self._sem is None or delta == 0:  # drainer not started yet
            return
        if delta > 0:
            for _ in range(delta):
                self._sem.release()
        else:
            for _ in range(-delta):
                await self._sem.acquire()

    #: historical name, kept callable (pre-admission callers and tests)
    set_max_in_flight = resize

    async def _drain(self) -> None:
        loop = asyncio.get_running_loop()
        sem = self._sem = asyncio.Semaphore(self.max_in_flight)
        try:
            while True:
                # slot FIRST, assemble SECOND: requests that arrive while we
                # wait for a free dispatch slot coalesce into this batch
                # (assembling first would both under-fill the batch and
                # strand dequeued futures if stop() cancels at the acquire)
                await sem.acquire()
                try:
                    batch = [await self.queue.get()]
                except asyncio.CancelledError:
                    sem.release()
                    raise
                t_phase = time.perf_counter()
                while len(batch) < self.max_batch:
                    try:
                        batch.append(self.queue.get_nowait())
                    except asyncio.QueueEmpty:
                        break
                now = time.perf_counter()
                for entry in batch:
                    self.queue_delay.record(now - entry[2])
                t_assemble, t_phase = now - t_phase, now
                batch = self._evict_expired(batch)
                t_mask = time.perf_counter() - t_phase
                if not batch:
                    # the whole assembly was dead on arrival: no dispatch,
                    # hand the slot back and keep draining
                    sem.release()
                    continue
                self.batches_served += 1
                self.max_batch_seen = max(self.max_batch_seen, len(batch))
                task = loop.create_task(
                    self._dispatch(loop, batch, t_assemble, t_mask))
                self._inflight.add(task)
                task.add_done_callback(self._inflight.discard)
                task.add_done_callback(lambda _t: sem.release())
        except asyncio.CancelledError:
            # stop() cancelled the drainer; in-flight dispatch tasks must
            # still resolve their futures — cancel and await them
            for task in list(self._inflight):
                task.cancel()
            for task in list(self._inflight):
                try:
                    await task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            raise

    def _evict_expired(self, batch: list) -> list:
        """Deadline-aware shedding at batch-assembly time (the 504-evict
        step of the shedding order): entries whose deadline passed while
        they queued resolve ShedExpired instead of riding the dispatch —
        the caller already timed out, and dead work on the device would
        only inflate every live request's tail."""
        now = self._clock.monotonic()
        live = []
        shed = 0
        for entry in batch:
            # entries are (payload, fut, t_enq, ctx, deadline_at); tests
            # that inject raw 4-tuples simply have no deadline
            if entry[1].done():
                # abandoned (waiter cancelled/answered already): drop
                # without dispatching AND without shed bookkeeping — the
                # caller never saw a 504, and phantom counts would inflate
                # the service-rate estimate the 429 gate trusts
                continue
            deadline_at = entry[4] if len(entry) > 4 else None
            if deadline_at is not None and now >= deadline_at:
                shed += 1
                entry[1].set_result(ShedExpired(
                    "deadline expired before dispatch"))
            else:
                live.append(entry)
        if shed:
            self.shed_expired += shed
            if self._admission is not None:
                self._admission.on_shed_expired(shed)
        return live

    async def _dispatch(self, loop, batch,
                        t_assemble: float = 0.0, t_mask: float = 0.0) -> None:
        t0 = time.perf_counter()
        payloads = [entry[0] for entry in batch]
        # run_in_executor does not copy contextvars — run_with_deadline
        # re-establishes the deadline scope inside the worker thread, and
        # entering the first request's captured context carries its trace
        # identity across the thread hop (each request's context is captured
        # once at submit, so it is never entered twice)
        ctx = batch[0][3]
        try:
            results = await loop.run_in_executor(
                None, ctx.run, run_with_deadline, self.deadline_sec,
                self.deployed.predict_batch, payloads
            )
        except asyncio.CancelledError:
            # cancelled mid-dispatch: these futures are already dequeued, so
            # the queue-drain in stop() can't see them — fail them here or
            # their callers hang forever
            for entry in batch:
                if not entry[1].done():
                    entry[1].set_result(RuntimeError("server shutting down"))
            raise
        except Exception as e:  # noqa: BLE001 - keep serving
            results = [e] * len(batch)
        t_dispatch = time.perf_counter() - t0
        self.dispatch_sec.record(t_dispatch)
        # predict_batch published its per-algorithm times inside ctx; writes
        # made under Context.run persist in the Context object
        algo_times = ctx.get(_DISPATCH_ALGO_TIMES, [])
        t_merge = time.perf_counter()
        for entry, r in zip(batch, results):
            if not entry[1].done():
                entry[1].set_result(_Delivered(r, algo_times))
        # perf-plane phases for this batch's full life: coalesce (assemble),
        # deadline eviction (mask), device round-trip (dispatch), future
        # resolution (merge) — docs/observability.md "Profiling"
        _profile.record_phases("serve.batch", {
            "assemble": t_assemble, "mask": t_mask, "dispatch": t_dispatch,
            "merge": time.perf_counter() - t_merge,
        })


# LatencyReservoir moved to obs/metrics.py (it is a general primitive the
# admission limiter needs too); imported above and re-exported here so
# existing ``from ...query_server import LatencyReservoir`` keeps working.


def load_deployed_engine(
    config: ServerConfig,
    storage: Optional[Storage] = None,
    ctx: Optional[MeshContext] = None,
    warmup: bool = True,
) -> DeployedEngine:
    """variant → engine factory → latest COMPLETED instance → live models
    (createServerActorWithEngine, CreateServer.scala:187-246)."""
    storage = storage or get_storage()
    ctx = ctx or MeshContext.create()
    variant = variant_from_file(config.engine_variant)
    factory_path = variant["engineFactory"]
    engine = resolve_engine_factory(factory_path)()
    engine_params = engine.engine_params_from_variant(variant)
    import os

    instances = storage.get_meta_data_engine_instances()
    instance = instances.get_latest_completed(
        variant.get("id", "default"), variant.get("version", "1"),
        os.path.abspath(config.engine_variant),
    )
    if instance is None:
        raise RuntimeError(
            f"No COMPLETED engine instance for variant {config.engine_variant}; "
            "run train first (reference: CreateServer.scala:199 'Invalid engine instance')"
        )
    blob = storage.get_model_data_models().get(instance.id)
    if blob is None:
        raise RuntimeError(f"model blob missing for instance {instance.id}")
    persisted = deserialize_model(blob.models)
    models = engine.prepare_deploy(ctx, engine_params, persisted, instance.id)
    logger.info("deployed engine instance %s (trained %s)", instance.id,
                instance.start_time)
    return DeployedEngine(engine, engine_params, instance, models,
                          max_batch=config.max_batch, warmup=warmup,
                          algo_deadline=config.algo_deadline_sec,
                          breaker_threshold=config.algo_breaker_threshold,
                          breaker_reset=config.algo_breaker_reset_sec)


def effective_max_in_flight(config: ServerConfig, deployed: DeployedEngine) -> int:
    """Resolve ``ServerConfig.max_in_flight``'s auto (None) mode.

    max_batch=1 means "no batching" and keeps its historical strict
    serialization of user predict code regardless; otherwise overlap is only
    enabled automatically when every deployed algorithm opted in via
    ``serving_thread_safe`` (BaseAlgorithm)."""
    if config.max_batch == 1:
        return 1
    if config.max_in_flight is not None:
        return max(1, config.max_in_flight)
    safe = all(getattr(a, "serving_thread_safe", False)
               for a in deployed.algorithms)
    return 2 if safe else 1


class QueryServer:
    def __init__(
        self,
        config: ServerConfig,
        storage: Optional[Storage] = None,
        ctx: Optional[MeshContext] = None,
        deployed: Optional[DeployedEngine] = None,
        clock: Clock = SYSTEM_CLOCK,
        name: str = "query_server",
    ):
        self.config = config
        self.name = name
        self._clock = clock
        self.storage = storage or get_storage()
        self.ctx = ctx or MeshContext.create()
        # durable span export + sampling (obs/spool.py): applies the
        # PIO_TRACE_* env state; a no-op unless the spool dir is set.
        # Only the process front (the default name) configures the
        # process-wide planes — per-tenant cores hosted by a
        # TenantRegistry (server/tenancy.py) must not re-arm them on
        # every cold load
        if name == "query_server":
            from incubator_predictionio_tpu.obs import spool as trace_spool
            from incubator_predictionio_tpu.obs.plane import (
                configure_perf_plane_from_env,
            )

            trace_spool.configure_export_from_env("query_server")
            # continuous performance plane: procstats + profiler + metrics
            # history + SLO burn-rate engine (obs/plane.py)
            configure_perf_plane_from_env("query_server")
        # an explicit DeployedEngine skips storage loading (tests inject
        # hand-built engines to script failure modes)
        self.deployed = deployed or load_deployed_engine(
            config, self.storage, self.ctx)
        # -- overload protection (resilience/admission.py) ----------------
        # the door policy for sheddable query traffic: bounded queue +
        # deadline feasibility (429), brownout (degraded 200s), and the
        # adaptive concurrency limiter that live-resizes dispatch slots.
        # Health/metrics/reload are separate always-admitted routes.
        self._admission = AdmissionController(
            AdmissionConfig(
                max_queue=config.admission_max_queue,
                deadline_sec=config.query_timeout_sec,
                adaptive=config.admission_adaptive,
                max_inflight=effective_max_in_flight(config, self.deployed),
                target_latency_sec=(
                    config.admission_target_ms / 1e3
                    if config.admission_target_ms is not None else None),
                brownout_enter_frac=config.brownout_enter_frac,
                brownout_enter_sec=config.brownout_enter_sec,
                brownout_exit_sec=config.brownout_exit_sec,
            ), clock=clock, server=name)
        self.batcher = MicroBatcher(
            self.deployed, max_batch=config.max_batch,
            max_in_flight=effective_max_in_flight(config, self.deployed),
            deadline_sec=config.query_timeout_sec,
            clock=clock, admission=self._admission,
        )
        self._resize_tasks: set[asyncio.Task] = set()  # strong refs
        self.request_count = 0
        self.avg_serving_sec = 0.0
        self.last_serving_sec = 0.0
        self.latency = LatencyReservoir()
        # -- graceful degradation state (resilience/) ---------------------
        # server-level breaker over the whole predict path: opens after
        # repeated timeouts/unavailability so a dead engine answers
        # degraded responses instantly instead of waiting out every budget
        self._serving_breaker = CircuitBreaker(
            "serving", failure_threshold=config.algo_breaker_threshold,
            reset_timeout=config.algo_breaker_reset_sec)
        # last-good predictions keyed by canonical query JSON (bounded
        # LRU); guarded by a lock — _degraded_result runs in executor
        # threads while _remember_good mutates on the loop thread
        import threading

        self._last_good: "dict[str, Any]" = {}
        self._last_good_lock = threading.Lock()
        self._LAST_GOOD_MAX = 1024
        self.degraded_count = 0
        # -- crash-safe model lifecycle (docs/resilience.md) --------------
        # the previous DeployedEngine stays pinned through the probation
        # window after a successful /reload so a breaker-trip burst from
        # the new instance can atomically roll back
        self._previous: Optional[DeployedEngine] = None
        self._probation_until: Optional[float] = None
        self._rollback_count = 0
        self._last_reload: dict = {"status": "initial",
                                   "instanceId": self.deployed.instance.id}
        # -- streaming delta state (docs/streaming.md) --------------------
        # which [from_seq, to_seq) ranges of the updater's chain this
        # replica has applied; None until the first delta lands (or after
        # a full /reload resets the base). Snapshotted with the probation
        # pin so a rollback restores the matching chain position.
        self._delta_state: Optional[dict] = None
        self._previous_delta_state: Optional[dict] = None
        # -- multi-host shard ownership (docs/sharding.md) ----------------
        # fenced claim on a contiguous item-row range; None when this
        # process serves the whole catalog (the single-host default)
        self.shard_owner = None
        if config.shard_id is not None and config.shard_count is not None:
            from incubator_predictionio_tpu.server.shard_owner import (
                ShardOwner,
            )

            self.shard_owner = ShardOwner(
                config.shard_id, config.shard_count, config.shard_state_dir)
            self.shard_owner.bind_rows(self._catalog_rows())
        # -- graceful drain (server/lifecycle.py) -------------------------
        self._drain_state = DrainState(name)
        self._start_time = self._clock.monotonic()
        self._runner: Optional[web.AppRunner] = None
        self._stop_event = asyncio.Event()
        self._feedback_tasks: set[asyncio.Task] = set()  # strong refs (GC pitfall)
        # fold this server's signals into /metrics at scrape time (keyed:
        # a re-constructed server replaces its predecessor's collector;
        # per-tenant cores each get their own key so an eviction removes
        # exactly one collector)
        REGISTRY.add_collector(name, self._collect_metrics)

    def _collect_metrics(self) -> None:
        """Exposition-time fold: standalone breakers (per-algorithm +
        serving), the serving reservoirs, and device memory."""
        breakers = {b.name: b.snapshot() for b in self.deployed.algo_breakers}
        breakers["serving"] = self._serving_breaker.snapshot()
        publish_breaker_metrics(breakers)
        _G_REQUESTS.set(self.request_count)
        _G_BATCHES.set(self.batcher.batches_served)
        _G_MAX_BATCH.set(self.batcher.max_batch_seen)
        self._admission.publish(self.batcher.queue.qsize())
        for stage, res in (("total", self.latency),
                           ("queue_delay", self.batcher.queue_delay),
                           ("dispatch", self.batcher.dispatch_sec)):
            for q, v in res.percentiles().items():
                _G_LATENCY_Q.labels(stage=stage, quantile=q).set(v)
        stream = self._streaming_health()
        if stream is not None and stream.get("stalenessSeconds") is not None:
            _STREAM_STALENESS.set(stream["stalenessSeconds"])
        import sys

        if "jax" in sys.modules:  # never the import that drags jax in
            try:
                from incubator_predictionio_tpu.utils.tracing import (
                    device_memory_report,
                )

                for row in device_memory_report():
                    if row["bytes_in_use"] is not None:
                        _G_DEV_MEM.labels(device=row["device"]).set(
                            row["bytes_in_use"])
            except Exception:  # noqa: BLE001 - stats are best-effort
                pass

    # -- routes -----------------------------------------------------------
    def make_app(self) -> web.Application:
        app = web.Application(
            middlewares=[telemetry_middleware("query_server")])
        app.router.add_get("/", self.handle_status)
        app.router.add_get("/health", self.handle_health)
        add_observability_routes(app)
        app.router.add_post("/queries.json", self.handle_query)
        app.router.add_post("/shard/queries.json", self.handle_shard_query)
        app.router.add_post("/shard/promote", self.handle_shard_promote)
        app.router.add_post("/reload", self.handle_reload)
        app.router.add_post("/delta", self.handle_delta)
        app.router.add_post("/rollback", self.handle_rollback)
        app.router.add_post("/stop", self.handle_stop)
        app.router.add_get("/plugins.json", self.handle_plugins)
        return app

    async def handle_health(self, request: web.Request) -> web.Response:
        """Liveness + breaker state: per-algorithm, the serving path, and
        every storage backend registered in the process-wide registry."""
        algo = {
            b.name: b.snapshot()
            for b in self.deployed.algo_breakers
        }
        serving = self._serving_breaker.snapshot()
        backends = BREAKERS.snapshot()
        degraded = any(
            s["state"] != "closed"
            for s in (serving, *algo.values(), *backends.values()))
        return web.json_response({
            "status": self._drain_state.health_status(degraded),
            "draining": self._drain_state.draining,
            # SLO burn-rate verdicts (obs/slo.py; None when no PIO_SLO_CONFIG)
            # — pio-tpu health paints breaching objectives red
            "slo": _slo.health_block(),
            "servingBreaker": serving,
            "algorithmBreakers": algo,
            "backendBreakers": backends,
            "degradedResponses": self.degraded_count,
            # overload surface (docs/resilience.md "Overload & admission
            # control"): queue bound, brownout, limiter, shed tallies
            "admission": self._admission.snapshot(
                self.batcher.queue.qsize()),
            # crash-safe lifecycle surface (docs/resilience.md): which
            # instance serves, whether a previous one is pinned for
            # rollback, and what the last reload did. engineVersion is
            # what the fleet tier keys experiment arms and rollouts on
            # (docs/serving.md "Fleet serving")
            "deployment": {
                "instanceId": self.deployed.instance.id,
                "engineId": self.deployed.instance.engine_id,
                "engineVersion": self.deployed.instance.engine_version,
                "previousInstanceId": (
                    self._previous.instance.id
                    if self._previous is not None else None),
                "probationActive": self._probation_active(),
                "rollbacks": self._rollback_count,
                "lastReload": self._last_reload,
                # streaming update lag: lastDeltaSeq is what the updater's
                # ship-resync keys on; stalenessSeconds is the freshness
                # SLO pio-tpu health and the fleet balancer read
                "streaming": self._streaming_health(),
                # sharded serving (docs/sharding.md): per-model shard count
                # + mode + explicit [lo, hi) row bounds, None for
                # single-host models — what `pio-tpu shards` and fleet
                # tooling read without a full status page
                "sharding": self._sharding_summary(),
                # multi-host shard ownership: the fenced row-range claim
                # the fleet router's scatter/gather routes on
                "shardOwner": (self.shard_owner.announce()
                               if self.shard_owner is not None else None),
            },
        })

    def _sharding_summary(self) -> list:
        from incubator_predictionio_tpu.sharding.table import ShardSpec

        out = []
        for m in self.deployed.models:
            info = m.serving_info() if hasattr(m, "serving_info") else None
            sh = (info or {}).get("sharding")
            if not sh:
                out.append(None)
                continue
            entry = {"nShards": sh["n_shards"], "mode": sh["mode"],
                     "mergeFanin": sh["merge_fanin"]}
            items = sh.get("items") or None
            if items:
                # explicit per-shard [lo, hi) item-row bounds — routers and
                # `pio-tpu shards` need ranges, not just counts
                spec = ShardSpec(items["name"], items["n_rows"],
                                 items["width"], items["n_shards"])
                entry["shardIds"] = list(range(spec.n_shards))
                entry["rows"] = [list(spec.shard_bounds(s))
                                 for s in range(spec.n_shards)]
            out.append(entry)
        return out

    def _catalog_rows(self) -> int:
        """Item-catalog row count of the deployed model — what the shard
        owner's ``[lo, hi)`` bounds derive from."""
        for m in self.deployed.models:
            info = m.serving_info() if hasattr(m, "serving_info") else None
            if info and info.get("catalog_rows"):
                return int(info["catalog_rows"])
        return 0

    async def handle_shard_query(self, request: web.Request) -> web.Response:
        """One shard owner's PARTIAL answer (docs/sharding.md "Multi-host
        shard owners"): block-local top-k candidates over the owned item
        rows only, plus the owner's fenced epoch so the router can discard
        partials from a deposed owner. Only the fleet router should call
        this — clients keep using /queries.json."""
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        so = self.shard_owner
        if so is None or so.bounds() is None:
            return web.json_response(
                {"message": "this server is not a shard owner (deploy with "
                            "--shard-id/--shard-count)"}, status=409)
        lo, hi = so.bounds()
        body = await request.read()
        try:
            payload = json.loads(body.decode("utf-8"))
            if not isinstance(payload, dict):
                raise ValueError("query must be a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            return web.json_response(
                {"message": f"bad query: {e}"}, status=400)
        from incubator_predictionio_tpu.server import shard_owner as so_mod

        deployed = self.deployed  # swap-safe snapshot
        loop = asyncio.get_running_loop()
        try:
            part = await loop.run_in_executor(
                None, so_mod.partial_predict, deployed, payload, lo, hi)
        except (TypeError, ValueError, KeyError) as e:
            # query-semantic rejection, same class split as /queries.json
            return web.json_response(
                {"message": f"bad query: {e}"}, status=400)
        except so_mod.ShardOwnerError as e:
            return web.json_response({"message": str(e)}, status=409)
        return web.json_response({
            "candidates": {"ids": part["ids"], "scores": part["scores"],
                           "items": part["items"]},
            "num": part["num"],
            "shard": {**so.announce(),
                      "instanceId": deployed.instance.id},
        })

    async def handle_shard_promote(self, request: web.Request) -> web.Response:
        """Failover promotion: durably bump this owner's fencing epoch
        (persist-then-announce, the replication/manager.py invariant) so
        its partials supersede the deposed owner's. The caller may pass
        ``{"epoch": N}`` — the highest epoch it has observed for the range
        — to guarantee the promoted owner exceeds it."""
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        if self.shard_owner is None:
            return web.json_response(
                {"message": "this server is not a shard owner"}, status=409)
        try:
            body = json.loads((await request.read()) or b"{}")
        except ValueError:
            body = {}
        requested = body.get("epoch") if isinstance(body, dict) else None
        epoch = self.shard_owner.promote(
            int(requested) if requested is not None else None)
        logger.warning("shard owner %d/%d PROMOTED to epoch %d",
                       self.shard_owner.shard_id,
                       self.shard_owner.shard_count, epoch)
        return web.json_response({
            "status": "promoted", "epoch": epoch,
            "shard": self.shard_owner.announce(),
        })

    async def handle_status(self, request: web.Request) -> web.Response:
        inst = self.deployed.instance
        if "text/html" in request.headers.get("Accept", ""):
            return web.Response(
                text=self._status_html(), content_type="text/html")
        return web.json_response({
            "status": "alive",
            "engineInstance": {
                "id": inst.id,
                "engineId": inst.engine_id,
                "engineVersion": inst.engine_version,
                "startTime": inst.start_time.isoformat(),
            },
            "algorithms": [type(a).__name__ for a in self.deployed.algorithms],
            # which execution path each model serves from (host numpy for
            # small catalogs, device bf16 / int8-pallas for large ones)
            "servingPaths": [
                m.serving_info() if hasattr(m, "serving_info") else None
                for m in self.deployed.models
            ],
            "requestCount": self.request_count,
            "avgServingSec": self.avg_serving_sec,
            "lastServingSec": self.last_serving_sec,
            "servingSecPercentiles": self.latency.percentiles(),
            # tail split (VERDICT r3 #6): time spent WAITING for a batch
            # slot vs time the dispatch itself took
            "queueDelaySecPercentiles": self.batcher.queue_delay.percentiles(),
            "dispatchSecPercentiles": self.batcher.dispatch_sec.percentiles(),
            "batchesServed": self.batcher.batches_served,
            "maxBatchSeen": self.batcher.max_batch_seen,
            # overload tallies (docs/resilience.md): queued-past-deadline
            # evictions and the live dispatch-slot bound
            "shedExpired": self.batcher.shed_expired,
            "maxInFlight": self.batcher.max_in_flight,
            # compile-churn gauge: distinct serving executables built in this
            # process; must stay flat under load once warmup has run
            "jitCompileKeys": jitstats.count(),
            "uptimeSec": self._clock.monotonic() - self._start_time,
        })

    def _status_html(self) -> str:
        """Human status page on ``/`` — the twirl template counterpart
        (core/src/main/twirl/.../workflow/index.scala.html, served by
        CreateServer.scala:437-462). Same sections: engine info, server info,
        per-stage params, algorithms+models, feedback loop. Self-contained
        CSS (no CDN — serving hosts may have no egress)."""
        import html as _html

        inst = self.deployed.instance
        cfg = self.config

        def esc(v) -> str:
            return _html.escape(str(v))

        def table(rows: list[tuple[str, object]]) -> str:
            return "<table>" + "".join(
                f"<tr><th>{esc(k)}</th><td>{esc(v)}</td></tr>"
                for k, v in rows) + "</table>"

        algo_rows = "".join(
            f"<tr><th rowspan=\"3\">{i + 1}</th>"
            f"<th>Class</th><td>{esc(type(a).__name__)}</td></tr>"
            f"<tr><th>Parameters</th><td>{esc(p)}</td></tr>"
            f"<tr><th>Model</th><td>{esc(m)}</td></tr>"
            for i, (a, p, m) in enumerate(zip(
                self.deployed.algorithms,
                json.loads(inst.algorithms_params or "[]")
                + [""] * len(self.deployed.algorithms),
                [type(m).__name__ for m in self.deployed.models]))
        )
        title = (f"{inst.engine_factory} ({inst.engine_variant}) - "
                 f"Engine Server at {cfg.ip}:{cfg.port}")
        return f"""<!DOCTYPE html>
<html lang="en">
<head><title>{esc(title)}</title>
<style>
 body {{ font-family: sans-serif; margin: 2em; }}
 table {{ border-collapse: collapse; margin-bottom: 1.5em; }}
 th, td {{ border: 1px solid #ccc; padding: 4px 10px; text-align: left; }}
 td {{ font-family: Menlo, Monaco, Consolas, monospace; }}
</style></head>
<body>
<h1>Engine Server at {esc(cfg.ip)}:{esc(cfg.port)}</h1>
<p>{esc(inst.engine_factory)} ({esc(inst.engine_variant)})</p>
<h2>Engine Information</h2>
{table([
    ("Training Start Time", inst.start_time),
    ("Training End Time", inst.end_time),
    ("Variant ID", inst.engine_variant),
    ("Instance ID", inst.id),
])}
<h2>Server Information</h2>
{table([
    ("Start Time", _dt.datetime.fromtimestamp(self._start_time)),
    ("Request Count", self.request_count),
    ("Average Serving Time", f"{self.avg_serving_sec:.4f} seconds"),
    ("Last Serving Time", f"{self.last_serving_sec:.4f} seconds"),
    ("Engine Factory Class", inst.engine_factory),
])}
<h2>Data Source</h2>
{table([("Parameters", inst.data_source_params)])}
<h2>Data Preparator</h2>
{table([("Parameters", inst.preparator_params)])}
<h2>Algorithms and Models</h2>
<table><tr><th>#</th><th colspan="2">Information</th></tr>{algo_rows}</table>
<h2>Serving</h2>
{table([("Parameters", inst.serving_params)])}
<h2>Feedback Loop Information</h2>
{table([
    ("Feedback Loop Enabled?", cfg.feedback),
    ("Event Server IP", cfg.event_server_ip),
    ("Event Server Port", cfg.event_server_port),
])}
</body>
</html>"""

    async def handle_query(self, request: web.Request) -> web.Response:
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        status, result, headers = await self._serve_payload(await request.read())
        return web.json_response(result, status=status, headers=headers)

    @staticmethod
    def _server_timing(total_sec: float,
                       algo_times: list[tuple[str, float]]) -> str:
        """``X-PIO-Server-Timing`` value: total µs plus this request's
        dispatch's per-algorithm µs (``<name>;us=<int>`` entries) — clients
        see server-side cost without scraping /metrics."""
        parts = [f"total;us={int(total_sec * 1e6)}"]
        parts.extend(f"{name};us={int(sec * 1e6)}"
                     for name, sec in algo_times)
        return ", ".join(parts)

    def _feed_admission(self, dt: float,
                        observe_latency: bool = True) -> None:
        """Every request that consumed a batcher queue slot counts as drain
        progress — 400 binding rejections, timeout-degraded answers, and
        engine exceptions all drained the queue (and usually a dispatch)
        just like clean 200s, and a service-rate estimate fed only by
        successes under-reads the true drain rate, shedding good traffic
        below capacity on mixed workloads. Brownout answers and abandoned
        entries never enter the queue, so they stay out; assembly-time
        504-evictions are recorded by ``on_shed_expired`` instead. Only
        clean predictions carry ``observe_latency`` — the AIMD limiter's
        gradient baseline must track genuine predict latency, not a fast
        400's — and a changed limit resizes the batcher's slots off the
        hot path."""
        new_limit = self._admission.on_complete(
            dt, observe_latency=observe_latency)
        if new_limit is not None and new_limit != self.batcher.max_in_flight:
            task = asyncio.create_task(self.batcher.resize(new_limit))
            self._resize_tasks.add(task)
            task.add_done_callback(self._resize_tasks.discard)

    async def _serve_payload(
            self, body: bytes) -> tuple[int, Any, Optional[dict]]:
        """The whole query lifecycle from raw body bytes — ONE code path
        shared by the aiohttp route and the native front, so their behavior
        cannot drift. Returns (status, jsonable body, response headers or
        None) — headers carry X-PIO-Server-Timing on predictions and
        Retry-After on overload rejections."""
        t0 = self._clock.monotonic()
        try:
            payload = json.loads(body)
        except json.JSONDecodeError:
            return 400, {"message": "Invalid JSON query"}, None
        loop = asyncio.get_running_loop()
        # -- admission door (resilience/admission.py) ---------------------
        # shedding order (docs/resilience.md): brownout (degraded 200)
        # before 429-reject before the batcher's 504-evict. Health,
        # /metrics, and /reload never pass this door.
        decision, retry_after = self._admission.decide(
            self.batcher.queue.qsize())
        if decision == REJECT:
            return 429, {
                "message": "server overloaded; rejected by admission "
                           "control (docs/resilience.md)",
            }, {"Retry-After": str(retry_after)}
        if decision == BROWNOUT:
            # sustained saturation: answer from the degraded path (last-
            # good cache / serving default) without touching the device
            # queue — valid 200s for everyone beats shedding for some
            return 200, await loop.run_in_executor(
                None, self._degraded_result, payload,
                "brownout (admission control)"), None
        if not self._serving_breaker.allow():
            # the predict path has been failing hard: degrade instantly
            # instead of waiting out another budget (half-open probes are
            # admitted by allow() once the reset window elapses). User code
            # (default_result, plugins) runs in the executor — under outage
            # EVERY request takes this path, and it must not block the loop
            return 200, await loop.run_in_executor(
                None, self._degraded_result, payload,
                "serving breaker open"), None
        try:
            submitted = self.batcher.submit_timed(payload)
            if self.config.query_timeout_sec is not None:
                # the degraded-200 backstop waits a small GRACE past the
                # budget: the batcher's 504-evict (assembly-time shed of
                # queued-expired requests) fires AT the budget, so under
                # overload the orderly shed wins; the backstop only
                # catches a wedged dispatch that produced no assembly at
                # all — firing both at the same instant would make the
                # shed path unreachable and charge the serving breaker
                # (and probation rollback) for pure overload
                budget = self.config.query_timeout_sec
                prediction, algo_times = await asyncio.wait_for(
                    submitted, budget + max(0.05, 0.1 * budget))
            else:
                prediction, algo_times = await submitted
        except asyncio.CancelledError:
            # client disconnected mid-await (aiohttp cancels the handler):
            # no verdict on the engine's health — hand back the admitted
            # half-open probe slot or the breaker wedges half-open forever
            self._serving_breaker.release_probe()
            raise
        except ShedExpired:
            # evicted at batch assembly: the deadline passed while queued.
            # Overload, not an engine verdict — the probe slot goes back
            # untouched and the caller gets a fail-fast 504 with the same
            # pressure-derived hint the 429 path sends
            self._serving_breaker.release_probe()
            return 504, {
                "message": "deadline expired before dispatch; request "
                           "shed (docs/resilience.md)",
            }, {"Retry-After": str(
                self._admission.retry_after(self.batcher.queue.qsize()))}
        except (TypeError, ValueError, KeyError) as e:
            # the engine answered (binding rejected the query): health-wise
            # a success — a half-open probe slot must never leak
            self._serving_breaker.record_success()
            self._feed_admission(self._clock.monotonic() - t0,
                                 observe_latency=False)
            return 400, {"message": f"Invalid query: {e}"}, None
        except (asyncio.TimeoutError, ServingUnavailable, DeadlineExceeded,
                CircuitOpenError) as e:
            # deadline blown or every algorithm/backend breaker open:
            # degraded-but-valid beats a 500 (ISSUE 1 acceptance)
            self._serving_breaker.record_failure()
            # a breaker trip inside a reload's probation window indicts the
            # freshly swapped instance — restore the pinned previous one
            await self._maybe_probation_rollback(repr(e))
            self._ship_remote_log(f"query degraded: {e!r}")
            self._feed_admission(self._clock.monotonic() - t0,
                                 observe_latency=False)
            return 200, await loop.run_in_executor(
                None, self._degraded_result, payload, repr(e)), None
        except Exception as e:  # noqa: BLE001 - ship serving errors remotely
            # a per-query engine exception is the ENGINE answering (with an
            # error) — not a serving outage. One client's poison query must
            # not trip this breaker and degrade everyone; a genuinely
            # broken engine opens the per-algorithm breakers instead, which
            # surfaces here as ServingUnavailable (counted above).
            self._serving_breaker.record_success()
            self._ship_remote_log(f"query failed: {e!r}")
            self._feed_admission(self._clock.monotonic() - t0,
                                 observe_latency=False)
            raise
        self._serving_breaker.record_success()
        dt = self._clock.monotonic() - t0
        self.request_count += 1
        self.last_serving_sec = dt
        self.avg_serving_sec += (dt - self.avg_serving_sec) / self.request_count
        self.latency.record(dt)
        self._feed_admission(dt)
        # camelCase field names: the reference's response shape
        # (CreateServer.scala:494's json4s serialization of e.g. ItemScore)
        result = to_jsonable(prediction, camelize_fields=True)
        from incubator_predictionio_tpu.server.plugins import apply_output_plugins

        result = apply_output_plugins(self.deployed.instance, payload, result)
        # cache POST-plugin: a degraded replay must never leak fields an
        # output plugin (redaction, enrichment) would have removed
        self._remember_good(payload, result)
        if self.config.feedback:
            task = asyncio.create_task(self._send_feedback(payload, result))
            self._feedback_tasks.add(task)
            task.add_done_callback(self._feedback_tasks.discard)
        return 200, result, {
            "X-PIO-Server-Timing": self._server_timing(dt, algo_times)}

    # -- graceful degradation (resilience/) -------------------------------
    @staticmethod
    def _cache_key(payload: dict) -> str:
        try:
            canon = json.dumps(payload, sort_keys=True, default=str)
        except (TypeError, ValueError):
            canon = repr(payload)
        # digest, not the canonical string: 1024 cached entries must not
        # also pin 1024 full query bodies as dict keys
        return hashlib.sha1(canon.encode()).hexdigest()

    def _remember_good(self, payload: dict, result: Any) -> None:
        key = self._cache_key(payload)
        with self._last_good_lock:
            self._last_good.pop(key, None)  # re-insert = move to MRU end
            self._last_good[key] = result
            while len(self._last_good) > self._LAST_GOOD_MAX:
                self._last_good.pop(next(iter(self._last_good)))

    def _degraded_result(self, payload: dict, reason: str) -> Any:
        """Fallback when the engine cannot answer in time: the last good
        prediction for this exact query, else the serving layer's declared
        default (``serving.default_result(query)``), else a minimal valid
        body — always 200, never a 500 (the engine being slow is our
        problem, not the caller's)."""
        with self._last_good_lock:
            # += from concurrent executor threads is a lost-update hazard
            self.degraded_count += 1
            cached = self._last_good.get(self._cache_key(payload))
        _DEGRADED.inc()
        if cached is not None:
            if isinstance(cached, dict):
                return {**cached, "degraded": True}
            return cached
        default_fn = getattr(self.deployed.serving, "default_result", None)
        if callable(default_fn):
            try:
                from incubator_predictionio_tpu.server.plugins import (
                    apply_output_plugins,
                )

                # the documented contract passes the BOUND query (like
                # supplement/serve), not the raw JSON dict
                query = bind_query(self.deployed.query_cls, payload)
                result = to_jsonable(default_fn(query), camelize_fields=True)
                result = apply_output_plugins(
                    self.deployed.instance, payload, result)
                if isinstance(result, dict):
                    return {**result, "degraded": True}
                return result
            except Exception:  # noqa: BLE001 - the default must never throw
                logger.exception("serving default_result failed")
        return {"degraded": True, "message": f"serving degraded: {reason}"}

    @staticmethod
    async def _post_json(url: str, body: dict, what: str) -> None:
        """Fire-and-forget POST; failures are logged, never raised (feedback
        and log shipping must never break serving)."""
        import aiohttp

        try:
            async with aiohttp.ClientSession() as session:
                async with session.post(url, json=body,
                                        timeout=aiohttp.ClientTimeout(total=5)) as resp:
                    if resp.status >= 300:
                        logger.warning("%s rejected: %s", what, resp.status)
        except Exception as e:  # noqa: BLE001
            logger.warning("%s failed: %s", what, e)

    async def _send_feedback(self, query: dict, prediction: Any) -> None:
        """POST a `predict` event to the event server (CreateServer.scala:508-570)."""
        pr_id = prediction.get("prId") if isinstance(prediction, dict) else None
        pr_id = pr_id or uuid.uuid4().hex
        event = {
            "event": "predict",
            "entityType": "pio_pr",
            "entityId": pr_id,
            "properties": {"query": query, "prediction": prediction},
        }
        url = (
            f"http://{self.config.event_server_ip}:{self.config.event_server_port}"
            f"/events.json?accessKey={self.config.access_key or ''}"
        )
        await self._post_json(url, event, "feedback event")

    def _ship_remote_log(self, message: str) -> None:
        """Fire-and-forget POST of a serving error to ``--log-url``
        (reference ``remoteLog``, CreateServer.scala:423-436)."""
        if not self.config.log_url:
            return

        body = {"level": "ERROR",
                "message": f"{self.config.log_prefix}{message}",
                "engineInstanceId": self.deployed.instance.id}
        task = asyncio.create_task(
            self._post_json(self.config.log_url, body, "remote log"))
        self._feedback_tasks.add(task)
        task.add_done_callback(self._feedback_tasks.discard)

    def _authorized(self, request: web.Request) -> bool:
        import hmac

        key = self.config.server_access_key
        if not key:
            return True
        # bytes operands: compare_digest rejects non-ASCII str
        return hmac.compare_digest(
            request.query.get("accessKey", "").encode(), key.encode())

    async def handle_reload(self, request: web.Request) -> web.Response:
        """Versioned hot-swap (docs/resilience.md crash-safe lifecycle):

        1. load + warm the new instance BESIDE the live one (the live
           engine keeps serving throughout — a crash anywhere in here
           leaves it untouched);
        2. run the configured smoke queries against the new instance; any
           failure keeps the live instance and answers 409 (the new
           instance never serves a query);
        3. atomically swap the ``DeployedEngine`` reference and pin the
           previous instance for ``reload_probation_sec`` — a
           serving-breaker trip inside that window auto-rolls back.
        """
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        loop = asyncio.get_running_loop()
        try:
            # executor: loading deserializes blobs and warms compile caches
            # — seconds of work that must not stall live queries
            new = await loop.run_in_executor(
                None, load_deployed_engine, self.config, self.storage,
                self.ctx)
        except RuntimeError as e:
            return web.json_response({"message": str(e)}, status=400)
        failure = await self._smoke_gate(new)
        if failure is not None:
            self._rollback_count += 1
            _ROLLBACKS.inc()
            self._last_reload = {
                "status": "rejected", "instanceId": new.instance.id,
                "reason": failure,
            }
            logger.error("reload: smoke gate rejected instance %s (%s); "
                         "instance %s keeps serving", new.instance.id,
                         failure, self.deployed.instance.id)
            return web.json_response({
                "message": "Reload rejected by smoke-query gate; previous "
                           "instance keeps serving",
                "error": failure,
                "engineInstanceId": self.deployed.instance.id,
            }, status=409)
        old = await self._swap_in(new)
        # a full reload resets the streaming chain: deltas were built for
        # the previous base instance and the updater starts a fresh chain
        # against this one (the snapshot _swap_in took still restores the
        # old chain position if probation rolls this reload back)
        self._delta_state = None
        self._last_reload = {"status": "ok", "instanceId": new.instance.id,
                             "previousInstanceId": old.instance.id}
        return web.json_response({"message": "Reloaded",
                                  "engineInstanceId": new.instance.id})

    async def _swap_in(self, new: DeployedEngine) -> DeployedEngine:
        """Atomic engine swap + probation pin, shared by /reload (full
        model) and /delta (streaming delta deploy): in-flight dispatches
        hold their own reference to the old engine and complete against
        it; everything after the assignment serves the new one. The old
        engine — and the delta-chain position that matched it — is pinned
        for the probation window so a breaker trip rolls BOTH back."""
        loop = asyncio.get_running_loop()
        old = self.deployed
        self.deployed = new
        # The batcher captured the old DeployedEngine at construction;
        # repoint it or the swap would silently keep serving the stale
        # model.
        self.batcher.deployed = new
        # the swapped engine may have a different thread-safety posture —
        # re-resolve the overlap bound (and re-bound the adaptive limiter,
        # which also resets its latency baseline: new engine, new floor)
        # or auto mode's no-race guarantee breaks across the swap
        bound = effective_max_in_flight(self.config, new)
        limit = self._admission.set_max_inflight(bound)
        await self.batcher.resize(limit if limit is not None else bound)
        if self.shard_owner is not None:
            # a swapped-in instance may carry a different catalog size —
            # re-derive the owned [lo, hi) from the same ShardSpec math
            self.shard_owner.bind_rows(self._catalog_rows())
        self._previous = old
        self._previous_delta_state = (
            dict(self._delta_state) if self._delta_state else None)
        self._probation_until = (
            self._clock.monotonic() + self.config.reload_probation_sec
            if self.config.reload_probation_sec > 0 else None)
        if self._probation_until is not None:
            # release the pin proactively when the window ends: without a
            # /health prober nothing else reads _probation_active(), and
            # the old instance's device arrays would stay resident for the
            # process lifetime (doubling memory per reload cycle). The
            # callback is a no-op if a rollback already consumed the pin
            # or an injected test clock says probation is still running.
            loop.call_later(self.config.reload_probation_sec + 0.5,
                            self._probation_active)
        else:
            self._previous = None  # probation disabled: nothing to pin
        return old

    async def _smoke_gate(self, new: DeployedEngine) -> Optional[str]:
        """Run ``config.smoke_queries`` against the not-yet-live instance.
        Returns an error description, or None when the gate passes (no
        queries configured = pass: warmup already exercised the models)."""
        loop = asyncio.get_running_loop()
        for payload in self.config.smoke_queries:
            try:
                await loop.run_in_executor(None, new.predict, dict(payload))
            except Exception as e:  # noqa: BLE001 - any failure gates
                return f"smoke query {payload!r} failed: {e!r}"
        return None

    def _probation_active(self) -> bool:
        if self._previous is None or self._probation_until is None:
            return False
        if self._clock.monotonic() >= self._probation_until:
            # probation survived: release the pinned previous instance so
            # its device arrays can be reclaimed
            self._previous = None
            self._probation_until = None
            return False
        return True

    async def _restore_previous(self, reason: str) -> DeployedEngine:
        """Swap the pinned previous instance back in (probation rollback
        and the fleet orchestrator's POST /rollback share this): atomic
        engine swap, limiter re-bound, serving breaker closed so the
        restored instance serves immediately."""
        prev, self._previous = self._previous, None
        self._probation_until = None
        rolled_from = self.deployed.instance.id
        self.deployed = prev
        self.batcher.deployed = prev
        # the restored engine's tables predate the swapped-in deploy —
        # restore the delta-chain position that matched them, so the
        # updater's ship-resync re-sends exactly what was rolled back
        self._delta_state = self._previous_delta_state
        self._previous_delta_state = None
        bound = effective_max_in_flight(self.config, prev)
        limit = self._admission.set_max_inflight(bound)
        await self.batcher.resize(limit if limit is not None else bound)
        if self.shard_owner is not None:
            self.shard_owner.bind_rows(self._catalog_rows())
        self._serving_breaker.record_success()  # clean slate for the restore
        self._rollback_count += 1
        _ROLLBACKS.inc()
        self._last_reload = {"status": "rolled_back",
                             "instanceId": prev.instance.id,
                             "rolledBackFrom": rolled_from,
                             "reason": reason}
        logger.error("reload: rolled back from instance %s to %s "
                     "(%s)", rolled_from, prev.instance.id, reason)
        return prev

    async def _maybe_probation_rollback(self, reason: str) -> None:
        """Called after a serving-breaker failure: if the breaker tripped
        OPEN inside a reload's probation window, the new instance is
        broken under real traffic — swap the pinned previous instance back
        in and close the breaker so it serves immediately."""
        if self._serving_breaker.state != "open" or not self._probation_active():
            return
        await self._restore_previous(reason)

    def _streaming_health(self) -> Optional[dict]:
        """Delta-chain position + freshness for /health.deployment (None
        until a streaming delta has been applied to this base)."""
        st = self._delta_state
        if not st:
            return None
        staleness = None
        if st.get("maxEventTimeUs"):
            # pio-lint: disable=R2 (maxEventTimeUs is an EPOCH stamp from the event log; staleness vs wall time is the semantic — the monotonic Clock seam cannot express it)
            staleness = max(0.0, time.time() - st["maxEventTimeUs"] / 1e6)
        return {
            "lastDeltaSeq": st["lastDeltaSeq"],
            "chainBase": st["chainBase"],
            "applied": st["applied"],
            "deduped": st["deduped"],
            "stalenessSeconds": staleness,
        }

    async def handle_delta(self, request: web.Request) -> web.Response:
        """Streaming delta deploy (docs/streaming.md): apply a versioned
        embedding-row delta through the SAME discipline as a full /reload
        — build the delta-applied engine BESIDE the live one, run the
        smoke-query gate, swap atomically, pin the previous engine for
        probation (a breaker trip rolls the delta back to last-good).

        Exactly-once enforcement: every delta names its ``[from_seq,
        to_seq)`` event range and the base instance it applies to.
        Out-of-order or wrong-base deltas are rejected 409 (with this
        replica's position, so the updater resyncs the chain); an
        already-applied range answers 200 "duplicate" — the crash-replay
        dedup — and is counted, never re-applied."""
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        from incubator_predictionio_tpu.streaming.delta import decode_delta

        body = await request.read()
        try:
            delta = decode_delta(body)
        except Exception as e:  # noqa: BLE001 - bad/foreign artifact
            return web.json_response(
                {"status": "rejected", "message": f"bad delta: {e}"},
                status=400)
        inst_id = self.deployed.instance.id
        st = self._delta_state
        last = st["lastDeltaSeq"] if st else None
        if delta.base_instance != inst_id:
            return web.json_response({
                "status": "rejected", "reason": "base-mismatch",
                "message": f"delta targets instance {delta.base_instance}, "
                           f"this replica serves {inst_id}",
                "instanceId": inst_id, "lastDeltaSeq": last,
            }, status=409)
        if last is not None and delta.to_seq <= last:
            # already applied (the updater crashed between ship and cursor
            # commit and is replaying): idempotent ack, counted
            self._delta_state["deduped"] += 1
            _STREAM_DEDUPED.inc()
            return web.json_response({
                "status": "duplicate", "lastDeltaSeq": last})
        expected = last if last is not None else delta.chain_base
        if delta.from_seq != expected:
            return web.json_response({
                "status": "rejected", "reason": "out-of-order",
                "message": f"expected from_seq {expected}, got "
                           f"{delta.from_seq} — resync the chain",
                "lastDeltaSeq": last, "instanceId": inst_id,
            }, status=409)
        if not delta.finite():
            return web.json_response({
                "status": "rejected", "reason": "non-finite",
                "message": "delta carries non-finite rows; quarantine the "
                           "stream (docs/streaming.md)",
                "lastDeltaSeq": last,
            }, status=409)
        # shard owners apply only THEIR slice of the chain's item rows —
        # the full chain still ships to every owner (seq bookkeeping must
        # stay contiguous for the range checks above), the restriction
        # happens at apply time so a foreign owner's rows never land here
        apply_delta_obj = delta
        if self.shard_owner is not None:
            bounds = self.shard_owner.bounds()
            if bounds is not None:
                from incubator_predictionio_tpu.streaming.delta import (
                    restrict_to_item_rows,
                )

                apply_delta_obj = restrict_to_item_rows(delta, *bounds)
        loop = asyncio.get_running_loop()

        def build() -> DeployedEngine:
            import signal as _signal

            models = []
            applied = False
            for m in self.deployed.models:
                if hasattr(m, "apply_delta"):
                    m = m.apply_delta(apply_delta_obj)
                    applied = True
                models.append(m)
            if not applied:
                raise LookupError("no deployed model supports streaming "
                                  "deltas (apply_delta)")
            if os.environ.get("PIO_DELTA_FAULT") == "kill:mid_apply":
                # chaos hook: die with the new tables built but NOT
                # swapped — serving must still hold the old engine after
                # restart, with nothing half-applied
                logger.error("PIO_DELTA_FAULT tripping mid_apply — SIGKILL")
                os.kill(os.getpid(), _signal.SIGKILL)
            return DeployedEngine(
                self.deployed.engine, self.deployed.engine_params,
                self.deployed.instance, models,
                max_batch=self.config.max_batch, warmup=False,
                algo_deadline=self.config.algo_deadline_sec,
                breaker_threshold=self.config.algo_breaker_threshold,
                breaker_reset=self.config.algo_breaker_reset_sec,
                clock=self._clock)

        try:
            new = await loop.run_in_executor(None, build)
        except LookupError as e:
            return web.json_response(
                {"status": "rejected", "message": str(e)}, status=409)
        except (ValueError, RuntimeError) as e:
            return web.json_response({
                "status": "rejected", "reason": "apply-failed",
                "message": str(e), "lastDeltaSeq": last,
            }, status=409)
        failure = await self._smoke_gate(new)
        if failure is not None:
            self._rollback_count += 1
            _ROLLBACKS.inc()
            self._last_reload = {
                "status": "delta_rejected", "instanceId": inst_id,
                "deltaRange": [delta.from_seq, delta.to_seq],
                "reason": failure,
            }
            logger.error("delta [%d, %d): smoke gate rejected (%s); "
                         "previous state keeps serving",
                         delta.from_seq, delta.to_seq, failure)
            return web.json_response({
                "status": "rejected", "reason": "smoke-gate",
                "error": failure, "lastDeltaSeq": last,
            }, status=409)
        await self._swap_in(new)
        prev_max_t = st["maxEventTimeUs"] if st else 0
        self._delta_state = {
            "lastDeltaSeq": delta.to_seq,
            "chainBase": delta.chain_base,
            "maxEventTimeUs": max(prev_max_t, delta.max_event_time_us),
            "applied": (st["applied"] if st else 0) + 1,
            "deduped": st["deduped"] if st else 0,
        }
        _STREAM_APPLIED.inc()
        self._last_reload = {
            "status": "delta", "instanceId": inst_id,
            "deltaRange": [delta.from_seq, delta.to_seq],
        }
        return web.json_response({
            "status": "applied",
            "lastDeltaSeq": delta.to_seq,
            "rows": delta.n_rows,
            "engineInstanceId": inst_id,
        })

    async def handle_rollback(self, request: web.Request) -> web.Response:
        """Operator/orchestrator-driven rollback to the pinned previous
        instance — the fleet rollout's halt path (``pio-tpu fleet
        rollout``, docs/serving.md "Fleet serving"): when a LATER replica
        trips its smoke gate or probation, the already-updated replicas
        are restored to last-good through this endpoint while their own
        probation pins still hold. 409 once the pin is gone (probation
        elapsed or rollback already consumed it)."""
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        if not self._probation_active():
            return web.json_response({
                "message": "no pinned previous instance (probation "
                           "inactive); nothing to roll back to",
            }, status=409)
        prev = await self._restore_previous("operator rollback "
                                            "(POST /rollback)")
        return web.json_response({"message": "Rolled back",
                                  "engineInstanceId": prev.instance.id})

    async def handle_stop(self, request: web.Request) -> web.Response:
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        self._stop_event.set()
        return web.json_response({"message": "Shutting down"})

    async def handle_plugins(self, request: web.Request) -> web.Response:
        from incubator_predictionio_tpu.server.plugins import (
            ENGINE_SERVER_PLUGINS,
            EngineServerPlugin,
        )

        def listing(output_type):
            return {
                p.name: {"description": p.description, "class": type(p).__name__}
                for p in ENGINE_SERVER_PLUGINS.values()
                if p.output_type == output_type
            }

        return web.json_response({"plugins": {
            "outputblockers": listing(EngineServerPlugin.OUTPUTBLOCKER),
            "outputsniffers": listing(EngineServerPlugin.OUTPUTSNIFFER),
        }})

    # -- lifecycle --------------------------------------------------------
    async def start(self) -> None:
        import os

        from incubator_predictionio_tpu.obs import procstats
        from incubator_predictionio_tpu.server.event_server import _ssl_context

        # loop-lag gauge rides this server's loop (pio_process_loop_lag_*)
        self._loop_lag = procstats.start_loop_lag("query_server")
        self._runner = web.AppRunner(self.make_app())
        await self._runner.setup()
        # OPT-IN for serving (measured a wash on single-core CPU: the
        # cross-thread completion hops cost what the aiohttp cycle saved —
        # PERF.md round-5; multi-core / TPU hosts may differ, hence the knob)
        if (os.environ.get("PIO_NATIVE_HTTP_SERVING", "0") == "1"
                and os.environ.get("PIO_NATIVE_HTTP", "1") != "0"
                and self.config.ssl_cert is None):
            from incubator_predictionio_tpu.server.front_boot import (
                start_with_native_front,
            )

            self._loop = asyncio.get_running_loop()
            self._front = await start_with_native_front(
                self._runner, self.config.ip, self.config.port,
                self._native_http_handler, "POST /queries.json",
                "engine server")
            if self._front is not None:
                return
            self._runner = web.AppRunner(self.make_app())
            await self._runner.setup()
        site = web.TCPSite(self._runner, self.config.ip, self.config.port,
                           ssl_context=_ssl_context(self.config))
        await site.start()
        logger.info("engine server listening on %s:%d", self.config.ip, self.config.port)

    def _native_http_handler(self, token: int, method: str, path_qs: str,
                             body: bytes):
        """Runs on the native front's epoll thread: schedule the query on
        the event loop (the SAME _serve_payload path aiohttp uses) and
        answer later via the completion token — so micro-batching keeps
        coalescing concurrent queries across connections."""
        from incubator_predictionio_tpu import native

        if self._drain_state.draining:
            # tunnel: the aiohttp handler owns the 503 + Retry-After
            # draining answer — accepting here would re-enter the
            # micro-batcher and keep the drain's queue-empty wait from
            # ever becoming true
            return None
        loop = getattr(self, "_loop", None)
        if loop is None or loop.is_closed():
            return None  # tunnel
        asyncio.run_coroutine_threadsafe(
            self._native_serve(token, body), loop)
        return native.HTTP_PENDING

    async def _native_serve(self, token: int, body: bytes) -> None:
        from incubator_predictionio_tpu import native

        try:
            status, result, headers = await self._serve_payload(body)
            payload = json.dumps(result).encode()
            reason = {200: "OK", 400: "Bad Request",
                      429: "Too Many Requests",
                      504: "Gateway Timeout"}.get(status, "Error")
            extra = "".join(f"{k}: {v}\r\n"
                            for k, v in (headers or {}).items())
            resp = (f"HTTP/1.1 {status} {reason}\r\n"
                    f"Content-Type: application/json; charset=utf-8\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    f"{extra}"
                    f"Connection: keep-alive\r\n\r\n").encode() + payload
        except Exception:  # noqa: BLE001 - aiohttp would 500 here
            logger.exception("native serving handler error")
            body_b = b"500 Internal Server Error"
            resp = (b"HTTP/1.1 500 Internal Server Error\r\n"
                    b"Content-Type: text/plain; charset=utf-8\r\n"
                    b"Content-Length: " + str(len(body_b)).encode() +
                    b"\r\nConnection: close\r\n\r\n" + body_b)
        native.http_front_complete(getattr(self, "_front", None), token, resp)

    async def wait_stopped(self) -> None:
        await self._stop_event.wait()
        await self.drain_and_shutdown()

    async def drain_and_shutdown(
            self, deadline_sec: Optional[float] = None) -> None:
        """Graceful exit (docs/resilience.md): stop accepting queries
        (503 + Retry-After, /health → 'draining'), let every queued and
        in-flight micro-batch complete, then shut down — all within the
        deadline so a wedged dispatch can't hold the process hostage."""
        self._drain_state.begin()
        deadline = (drained_exit_deadline()
                    if deadline_sec is None else deadline_sec)
        drained = await wait_for(
            lambda: (self.batcher.queue.qsize() == 0
                     and not self.batcher._inflight),
            deadline)
        if not drained:
            logger.warning("drain: in-flight queries still running after "
                           "%.1fs — shutting down anyway", deadline)
        await self.shutdown()

    async def shutdown(self) -> None:
        # stop the native front first (no new pending queries), then stop
        # accepting backend connections BEFORE stopping the batcher — a
        # query in the gap would otherwise resurrect the drainer task
        front = getattr(self, "_front", None)
        if front is not None:
            from incubator_predictionio_tpu import native

            native.http_front_stop(front)
            self._front = None
        if self._runner is not None:
            await self._runner.cleanup()
        # a shrink mid-shutdown could be parked on the dispatch semaphore;
        # nothing will ever need the smaller bound again
        for task in list(self._resize_tasks):
            task.cancel()
        lag = getattr(self, "_loop_lag", None)
        if lag is not None:
            lag.cancel()
        await self.batcher.stop()
        # lifecycle flush for the trace spool: the drain's last spans (the
        # 503s it answered, the final dispatches) must reach disk before
        # the process exits
        from incubator_predictionio_tpu.obs import spool as trace_spool

        trace_spool.flush_export()


def serve_forever(config: ServerConfig, storage: Optional[Storage] = None) -> None:
    """Blocking entry used by the CLI `deploy` verb."""

    async def main():
        server = QueryServer(config, storage)
        await server.start()
        # SIGTERM/SIGINT drain exactly like POST /stop: finish in-flight
        # micro-batches, then exit (second signal force-exits)
        install_signal_drain(asyncio.get_running_loop(), server._stop_event,
                             "engine server")
        await server.wait_stopped()

    asyncio.run(main())
