"""Storage Server — the networked storage backend's server half.

The reference's production story is shared networked storage: every host of a
job points at the same PostgreSQL/HBase/Elasticsearch service
(storage/jdbc/.../JDBCLEvents.scala:109-150, storage/hbase/.../HBEventsUtil.scala:76-131,
storage/elasticsearch/.../ESLEvents.scala:41) discovered through the registry
(data/.../storage/Storage.scala:310-336). This framework's equivalent is a
storage *server process* (`pio-tpu storageserver`) that owns one embedded
backend (sqlite / eventlog / memory / localfs) and exposes the full storage
contract — METADATA + EVENTDATA + MODELDATA — over HTTP, with the ``remote``
backend type (data/storage/remote.py) as the client half. A multi-host
``launch`` job sets ``PIO_STORAGE_SOURCES_<N>_TYPE=remote`` and every process
shares one store without a shared filesystem.

Wire protocol (designed for the TPU input path, not per-row ORM chatter):

- ``POST /rpc/{store}/{method}`` — JSON args → JSON result for all CRUD and
  metadata calls. Bytes travel base64 (model blobs), datetimes ISO-8601.
- ``POST /rpc/events/find`` — chunked JSON-lines stream of events, so scans
  never materialize server-side; the client iterator is lazy end to end.
  Accepts ``n_shards``/``shard_index`` so a multi-host job's per-process
  sharded read pulls ONLY its entity shard over the network.
- ``POST /rpc/events/assemble_triples`` — the training bulk read returns the
  five columnar arrays as one binary ``.npz`` body: the event log becomes
  device-ready tensors in a single round trip (the networked analogue of the
  native scanner's columnar fast path).

Auth: optional shared key (``--server-access-key`` / config ``KEY``) checked
on every request via the ``X-PIO-Storage-Key`` header. TLS via the same PEM
cert/key pair as the other servers (common/SSLConfiguration.scala:30).

Storage calls run in a thread executor — the event loop never blocks on
sqlite/fs I/O (same discipline as the Event Server).
"""

from __future__ import annotations

import asyncio
import base64
import contextvars
import dataclasses
import io
import json
import logging
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Optional

import numpy as np
from aiohttp import web

from incubator_predictionio_tpu.obs.http import (
    add_observability_routes,
    telemetry_middleware,
)
from incubator_predictionio_tpu.resilience.admission import InflightGate

from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    Model,
    StorageError,
)
from incubator_predictionio_tpu.data.storage.eventlog_backend import (
    ReadOnlyLogError,
)
from incubator_predictionio_tpu.data.storage.registry import Storage, get_storage
from incubator_predictionio_tpu.replication.manager import (
    ReplicationUnavailable,
)
from incubator_predictionio_tpu.resilience.breaker import BREAKERS
from incubator_predictionio_tpu.server.lifecycle import (
    DrainState,
    drained_exit_deadline,
    install_signal_drain,
)

logger = logging.getLogger(__name__)

#: RPC methods that never mutate — a fenced/follower replica still serves
#: them (bounded-staleness reads). Everything else is a write and must be
#: epoch-fenced off non-primaries. ONE definition shared with the remote
#: client's follower-read routing (wire.py) so the halves cannot drift.
from incubator_predictionio_tpu.data.storage.wire import (  # noqa: E402
    READ_METHODS as _READ_METHODS,
)

#: events-store mutations that append replicated bytes — the ones the
#: quorum-ack / bounded-lag gates cover. (``init`` creates an empty log
#: that ships like any bytes; ``remove`` is an admin op fanned out
#: explicitly via ``propagate_remove`` below — neither carries acked
#: event data to lose.)
_REPLICATED_EVENT_MUTATIONS = frozenset({
    "insert", "insert_batch", "delete",
})


# wire codecs live in data/storage/wire.py (server-independent — the remote
# client imports them without dragging aiohttp in)
from incubator_predictionio_tpu.data.storage.wire import (  # noqa: E402
    _META_CODECS,
    dec_dt,
    dec_engine_instance,
    dec_evaluation_instance,
    dec_opt_filter,
    dec_job,
    enc_dt,
    enc_engine_instance,
    enc_evaluation_instance,
    enc_job,
)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class StorageServerConfig:
    ip: str = "0.0.0.0"
    port: int = 7072
    ssl_cert: Optional[str] = None
    ssl_key: Optional[str] = None
    server_access_key: Optional[str] = None  # shared secret for all calls
    # -- eventlog replication (replication/, docs/replication.md) ---------
    # role of this replica ("primary" serves writes and ships appends;
    # "follower" serves bounded-staleness reads and applies appends) and
    # the OTHER replicas' base URLs. Replication activates when peers are
    # configured or the role is follower; it requires the EVENTDATA
    # backend to be `eventlog`.
    repl_role: str = dataclasses.field(
        default_factory=lambda: os.environ.get("PIO_REPL_ROLE", "primary"))
    repl_peers: tuple = dataclasses.field(
        default_factory=lambda: tuple(
            u.strip() for u in os.environ.get("PIO_REPL_PEERS", "").split(",")
            if u.strip()))
    repl_sync: str = dataclasses.field(
        default_factory=lambda: os.environ.get("PIO_REPL_SYNC", "async"))
    # -- per-client fairness (resilience/admission.py) --------------------
    # concurrent in-flight RPCs allowed per client address; beyond it the
    # client answers 429 and queues behind ITSELF, not behind every other
    # query server sharing this store. 0 disables.
    client_inflight: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_STORAGE_CLIENT_INFLIGHT", "64")))
    # aggregate in-flight cap per source ADDRESS, regardless of the
    # self-reported X-PIO-Client identity: rotating identities must not
    # mint unlimited budget. 0 = auto (8 × client_inflight — wide enough
    # for a NAT'd fleet, bounded all the same).
    remote_inflight: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_STORAGE_REMOTE_INFLIGHT", "0")))


class StorageServer:
    """Serves one backing :class:`Storage` over the RPC surface above."""

    def __init__(self, config: StorageServerConfig,
                 storage: Optional[Storage] = None):
        self.config = config
        self.storage = storage or get_storage()
        # durable span export + sampling (obs/spool.py): applies the
        # PIO_TRACE_* env state; a no-op unless the spool dir is set
        from incubator_predictionio_tpu.obs import spool as trace_spool
        from incubator_predictionio_tpu.obs.plane import (
            configure_perf_plane_from_env,
        )

        trace_spool.configure_export_from_env("storage_server")
        # continuous performance plane (obs/plane.py): procstats +
        # profiler + metrics history + SLO burn-rate engine
        configure_perf_plane_from_env("storage_server")
        self._executor = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="pio-storage")
        self._runner: Optional[web.AppRunner] = None
        # graceful drain (server/lifecycle.py): new RPCs answer 503 while
        # in-flight storage calls finish under the runner's cleanup
        self._drain_state = DrainState("storage_server")
        # per-client in-flight caps (resilience/admission.py): one hot
        # query server cannot occupy every executor thread at once
        self._inflight_gate = InflightGate(config.client_inflight)
        # the per-identity key comes from a self-reported header, so a
        # second gate caps the source address in aggregate — an identity-
        # rotating client stays bounded instead of minting fresh budget
        # per request
        self._remote_gate = InflightGate(
            config.remote_inflight or 8 * config.client_inflight)
        # -- eventlog replication (replication/manager.py) ----------------
        self._repl = None
        if config.repl_peers or config.repl_role == "follower":
            self._repl = self._build_replication()

    def _build_replication(self):
        from incubator_predictionio_tpu.replication.manager import (
            ReplicationConfig,
            ReplicationManager,
        )

        events = self.storage.get_events()
        base_dir = getattr(events, "base_dir", None)
        if base_dir is None:
            raise StorageError(
                "storage replication requires the 'eventlog' EVENTDATA "
                "backend (the append-only log is the replicated "
                f"substrate); got {type(events).__name__}")
        repl = ReplicationManager(
            ReplicationConfig(
                log_dir=base_dir, role=self.config.repl_role,
                peers=tuple(self.config.repl_peers),
                sync=self.config.repl_sync,
                key=self.config.server_access_key),
            on_writable=lambda: events.set_read_only(False),
            on_read_only=lambda: events.set_read_only(True))
        repl.invalidate_read_views = events.reopen
        # a follower (or a node fenced in a previous life) must serve
        # reads through lock-free views so the replicated appends own
        # the writer flocks
        events.set_read_only(not repl.is_primary)
        return repl

    def _client_key(self, request: web.Request) -> str:
        # the client's self-reported process identity (remote.py sends
        # host:pid) beats the peer address: distinct query servers behind
        # one proxy/NAT must not share a single in-flight cap, and two
        # server processes on one host must not either. The address is
        # appended so an adversarial client can't impersonate another's
        # identity to eat its budget from a different machine.
        ident = request.headers.get("X-PIO-Client")
        remote = request.remote or "unknown"
        return f"{ident}@{remote}" if ident else remote

    def _throttle_response(self) -> web.Response:
        return web.json_response(
            {"message": "per-client in-flight RPC cap reached "
                        "(docs/resilience.md)"},
            status=429, headers={"Retry-After": "1"})

    def _admit_rpc(self, request: web.Request) -> Optional[tuple[str, str]]:
        """Acquire BOTH in-flight gates (per-identity, then per-address);
        returns the key pair to hand back to :meth:`_release_rpc`, or
        ``None`` when either cap is reached."""
        key = self._client_key(request)
        rkey = request.remote or "unknown"
        if not self._inflight_gate.acquire(key):
            return None
        if not self._remote_gate.acquire(rkey):
            self._inflight_gate.release(key)
            return None
        return key, rkey

    def _release_rpc(self, keys: tuple[str, str]) -> None:
        key, rkey = keys
        self._inflight_gate.release(key)
        self._remote_gate.release(rkey)

    async def _run(self, fn, *args, **kw):
        # copy_context: run_in_executor drops contextvars, and the request's
        # trace identity (set by the telemetry middleware) must follow the
        # storage call into the worker thread
        ctx = contextvars.copy_context()
        return await asyncio.get_running_loop().run_in_executor(
            self._executor, lambda: ctx.run(fn, *args, **kw))

    # -- app --------------------------------------------------------------
    def make_app(self) -> web.Application:
        app = web.Application(client_max_size=256 * 1024 * 1024,
                              middlewares=[telemetry_middleware("storage_server")])
        app.router.add_get("/", self.handle_status)
        app.router.add_get("/health", self.handle_health)
        add_observability_routes(app)
        app.router.add_post("/rpc/events/find", self.handle_find)
        app.router.add_post("/rpc/events/assemble_triples",
                            self.handle_assemble_triples)
        app.router.add_post("/rpc/{store}/{method}", self.handle_rpc)
        app.router.add_post("/repl/{verb}", self.handle_repl)
        return app

    def _authorized(self, request: web.Request) -> bool:
        import hmac

        key = self.config.server_access_key
        if not key:
            return True
        # bytes operands: compare_digest rejects non-ASCII str
        return hmac.compare_digest(
            request.headers.get("X-PIO-Storage-Key", "").encode(), key.encode())

    async def handle_status(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "alive", "service": "storage"})

    async def handle_health(self, request: web.Request) -> web.Response:
        """Draining flag + the backing store's breaker registry — the same
        shape the other two servers expose, so one probe works fleet-wide.
        Clients see the 'draining' flip and stop routing before the
        listener goes away (their retry policy classifies the 503 as
        transient and fails over)."""
        from incubator_predictionio_tpu.obs import slo as _slo

        backends = BREAKERS.snapshot()
        degraded = any(s["state"] != "closed" for s in backends.values())
        body = {
            "status": self._drain_state.health_status(degraded),
            "draining": self._drain_state.draining,
            # SLO burn-rate verdicts (obs/slo.py; None when no PIO_SLO_CONFIG)
            "slo": _slo.health_block(),
            "backendBreakers": backends,
            # per-client RPC fairness (docs/resilience.md "Overload &
            # admission control")
            "admission": self._inflight_gate.snapshot(),
            # the per-address aggregate backstop behind the self-reported
            # identity key
            "remoteAdmission": self._remote_gate.snapshot(),
        }
        if self._repl is not None:
            # role/epoch/lag surface: clients select the primary from
            # this, `pio-tpu health`/`store status` render it, and the
            # prober turns red on fenced or lag-exceeded replicas
            repl = await self._run(self._repl.health)
            body["replication"] = repl
            if repl.get("fenced") or repl.get("lagExceeded"):
                body["status"] = ("draining" if self._drain_state.draining
                                  else "degraded")
        return web.json_response(body)

    # -- generic JSON RPC --------------------------------------------------
    async def handle_rpc(self, request: web.Request) -> web.Response:
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        store = request.match_info["store"]
        method = request.match_info["method"]
        if (self._repl is not None and method not in _READ_METHODS
                and not self._repl.can_accept_writes()):
            # epoch fencing (docs/replication.md): a demoted/stale
            # primary or a follower must never apply a write — counted,
            # and flagged so the multi-endpoint client re-probes for the
            # real primary instead of retrying here
            self._repl.record_fenced_write()
            return web.json_response(
                {"message": f"write fenced: this replica is "
                            f"{self._repl.role} at epoch "
                            f"{self._repl.epoch}, not the current primary "
                            "(docs/replication.md)"},
                status=409,
                headers={"X-PIO-Fenced": str(self._repl.epoch)})
        keys = self._admit_rpc(request)
        if keys is None:
            return self._throttle_response()
        try:
            try:
                args = await request.json()
            except json.JSONDecodeError:
                return web.json_response({"message": "invalid JSON"},
                                         status=400)
            handler = _RPC.get((store, method))
            if handler is None:
                return web.json_response(
                    {"message": f"unknown rpc {store}.{method}"}, status=404)
            replicate = (self._repl is not None and store == "events"
                         and method in _REPLICATED_EVENT_MUTATIONS)
            replicate_remove = (self._repl is not None
                                and store == "events" and method == "remove")

            def run_handler():
                if replicate_remove:
                    # capture the log's basename BEFORE the local remove
                    # deletes it, then fan the removal out: byte shipping
                    # only moves record data, so a follower's retained
                    # copy would wedge shipping as divergent when the app
                    # is re-initialized smaller
                    events = self.storage.get_events()
                    name = os.path.basename(events.log_path(
                        args["app_id"], args.get("channel_id")))
                    result = handler(self.storage, args)
                    self._repl.propagate_remove(name)
                    return result
                if replicate and self._repl.config.sync != "quorum":
                    # bounded-lag async mode: refuse while the best
                    # follower is beyond the lag bound — the sole-copy
                    # window must not grow without limit
                    self._repl.check_async_bound()
                result = handler(self.storage, args)
                if replicate and self._repl.config.sync == "quorum":
                    # quorum-ack: the write is NOT acknowledged until a
                    # majority of the replica set holds it. Failure is a
                    # 503 (transient) — the event server spills to its
                    # WAL rather than treating an unreplicated write as
                    # durable (the PR 4 ack contract).
                    self._repl.sync_quorum()
                return result

            try:
                result = await self._run(run_handler)
            except ReplicationUnavailable as e:
                # quorum unreachable / lag bound exceeded: transient
                # cluster-wise — clients spill and retry, never a lossy ack
                return web.json_response(
                    {"message": str(e)}, status=503,
                    headers={"Retry-After": "1"})
            except ReadOnlyLogError as e:
                # a write slipped into a role-transition window (or the
                # flock genuinely lives elsewhere): 503, not a semantic
                # 500 — a 500 here would make the event server's drain
                # dead-letter acked events that a retry lands cleanly
                return web.json_response(
                    {"message": str(e)}, status=503,
                    headers={"Retry-After": "1"})
            except StorageError as e:
                return web.json_response({"message": str(e)}, status=500)
            except (TypeError, ValueError, KeyError) as e:
                return web.json_response({"message": repr(e)}, status=400)
            return web.json_response({"result": result})
        finally:
            self._release_rpc(keys)

    # -- replication RPC surface (replication/manager.py) ------------------
    async def handle_repl(self, request: web.Request) -> web.Response:
        """Thin HTTP shim over :meth:`ReplicationManager.handle` — the
        protocol itself (epoch checks, CRC verify, offset contract,
        promote, anti-entropy digests) lives in ONE place and is driven
        identically by these routes and the in-process tests. Served even
        while draining: catch-up replication during a graceful exit is
        exactly what minimizes failover loss."""
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        if self._repl is None:
            return web.json_response(
                {"message": "replication not configured on this storage "
                            "server (--repl-peer / PIO_REPL_PEERS)"},
                status=404)
        verb = request.match_info["verb"]
        try:
            args = await request.json()
        except json.JSONDecodeError:
            args = {}
        status, body = await self._run(self._repl.handle, verb, args)
        headers = ({"X-PIO-Fenced": str(body["fenced"])}
                   if isinstance(body, dict) and "fenced" in body else None)
        return web.json_response(body, status=status, headers=headers)

    # -- streaming find ----------------------------------------------------
    async def handle_find(self, request: web.Request) -> web.StreamResponse:
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        # the gates are held for the WHOLE stream: a scan occupies an
        # executor thread per chunk until it finishes, and that is exactly
        # the resource one client must not monopolize
        keys = self._admit_rpc(request)
        if keys is None:
            return self._throttle_response()
        try:
            return await self._handle_find_gated(request)
        finally:
            self._release_rpc(keys)

    async def _handle_find_gated(self, request: web.Request) -> web.StreamResponse:
        try:
            a = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"message": "invalid JSON"}, status=400)
        events = self.storage.get_events()
        n_shards = a.get("n_shards")

        def make_iter():
            if n_shards is not None:
                return events.find_sharded(
                    a["app_id"], n_shards,
                    channel_id=a.get("channel_id"),
                    start_time=dec_dt(a.get("start_time")),
                    until_time=dec_dt(a.get("until_time")),
                    entity_type=a.get("entity_type"),
                    event_names=a.get("event_names"),
                )[a.get("shard_index", 0)]
            return events.find(
                a["app_id"],
                channel_id=a.get("channel_id"),
                start_time=dec_dt(a.get("start_time")),
                until_time=dec_dt(a.get("until_time")),
                entity_type=a.get("entity_type"),
                entity_id=a.get("entity_id"),
                event_names=a.get("event_names"),
                target_entity_type=dec_opt_filter(a, "target_entity_type"),
                target_entity_id=dec_opt_filter(a, "target_entity_id"),
                limit=a.get("limit"),
                reversed=a.get("reversed", False),
            )

        sentinel = object()

        def pull(it, n=256):
            # a chunk of events per executor hop (not one hop per event)
            out = []
            for _ in range(n):
                e = next(it, sentinel)
                if e is sentinel:
                    break
                out.append(e)
            return out

        # materialize the iterator AND its first chunk before committing to a
        # 200 stream, so backend errors (e.g. uninitialized app) surface as a
        # proper error status instead of a truncated stream
        try:
            it = await self._run(make_iter)
            chunk = await self._run(pull, it)
        except StorageError as e:
            return web.json_response({"message": str(e)}, status=500)
        resp = web.StreamResponse(
            headers={"Content-Type": "application/x-ndjson"})
        await resp.prepare(request)
        while chunk:
            body = "".join(
                json.dumps(e.to_json_dict(), separators=(",", ":")) + "\n"
                for e in chunk
            )
            await resp.write(body.encode())
            chunk = await self._run(pull, it)
        await resp.write_eof()
        return resp

    # -- columnar bulk read ------------------------------------------------
    async def handle_assemble_triples(self, request: web.Request) -> web.Response:
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        if not self._authorized(request):
            return web.json_response({"message": "Unauthorized"}, status=401)
        keys = self._admit_rpc(request)
        if keys is None:
            return self._throttle_response()
        try:
            return await self._handle_assemble_gated(request)
        finally:
            self._release_rpc(keys)

    async def _handle_assemble_gated(
            self, request: web.Request) -> web.Response:
        try:
            a = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"message": "invalid JSON"}, status=400)
        events = self.storage.get_events()

        def run():
            uv, tv, ui, ti, vals = events.assemble_triples(
                a["app_id"],
                channel_id=a.get("channel_id"),
                start_time=dec_dt(a.get("start_time")),
                until_time=dec_dt(a.get("until_time")),
                entity_type=a.get("entity_type"),
                event_names=a.get("event_names"),
                target_entity_type=dec_opt_filter(a, "target_entity_type"),
                value_property=a.get("value_property"),
                default_values=a.get("default_values"),
                missing_value=a.get("missing_value", 0.0),
                dedup=a.get("dedup", False),
                n_shards=a.get("n_shards"),
                shard_index=a.get("shard_index", 0),
            )
            buf = io.BytesIO()
            # vocabularies ship as unicode arrays (ids are strings); indices
            # and values as raw dtypes — one binary body, zero pickling
            np.savez(
                buf,
                entity_vocab=uv.astype(np.str_),
                target_vocab=tv.astype(np.str_),
                entity_idx=ui, target_idx=ti, values=vals,
            )
            return buf.getvalue()

        try:
            body = await self._run(run)
        except StorageError as e:
            return web.json_response({"message": str(e)}, status=500)
        return web.Response(body=body,
                            content_type="application/octet-stream")

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        from incubator_predictionio_tpu.obs import procstats
        from incubator_predictionio_tpu.server.event_server import _ssl_context

        # loop-lag gauge rides this server's loop (pio_process_loop_lag_*)
        self._loop_lag = procstats.start_loop_lag("storage_server")
        if self._repl is not None:
            # announce BEFORE the listener exists: a primary restarted
            # with a stale epoch learns it was deposed (and fences) before
            # the first client write can possibly reach it
            await self._run(self._repl.start)
        self._runner = web.AppRunner(self.make_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.config.ip, self.config.port,
                           ssl_context=_ssl_context(self.config))
        await site.start()
        logger.info("storage server listening on %s:%d (replication: %s)",
                    self.config.ip, self.config.port,
                    f"{self._repl.role}@{self._repl.epoch}"
                    if self._repl is not None else "off")

    async def drain_and_shutdown(
            self, deadline_sec: Optional[float] = None) -> None:
        """SIGTERM path: flip to draining (new RPCs 503), let in-flight
        storage calls finish under the runner's graceful cleanup, exit —
        bounded internally so a wedged RPC yields a logged, orderly exit
        rather than a TimeoutError traceback out of asyncio.run."""
        self._drain_state.begin()
        deadline = (drained_exit_deadline()
                    if deadline_sec is None else deadline_sec)
        try:
            await asyncio.wait_for(self.shutdown(), deadline)
        except asyncio.TimeoutError:
            logger.warning("storage server drain exceeded %.1fs — exiting "
                           "with requests still in flight", deadline)
            self._executor.shutdown(wait=False)

    async def shutdown(self) -> None:
        lag = getattr(self, "_loop_lag", None)
        if lag is not None:
            lag.cancel()
        if self._runner is not None:
            # aiohttp's cleanup waits for handlers already in the router —
            # the in-flight-RPC half of the drain contract
            await self._runner.cleanup()
        if self._repl is not None:
            self._repl.stop()
        self._executor.shutdown(wait=False)
        from incubator_predictionio_tpu.obs import spool as trace_spool

        trace_spool.flush_export()


def serve_forever(config: StorageServerConfig,
                  storage: Optional[Storage] = None) -> None:
    """Blocking entry used by the CLI `storageserver` verb; runs until the
    process is signalled (same graceful-drain lifecycle as the other
    servers — see docs/resilience.md)."""

    async def main():
        server = StorageServer(config, storage)
        await server.start()
        stop = asyncio.Event()
        install_signal_drain(asyncio.get_running_loop(), stop,
                             "storage server")
        await stop.wait()
        await server.drain_and_shutdown()

    asyncio.run(main())


class ThreadedStorageServer:
    """A storage server on a daemon thread with its own event loop — the
    in-process harness tests and single-host multi-process jobs use (the
    parent process serves, `launch` children connect over the socket)."""

    def __init__(self, storage: Storage, config: Optional[StorageServerConfig] = None):
        import threading

        self.config = config or StorageServerConfig(ip="127.0.0.1", port=0)
        self.storage = storage
        self._loop = asyncio.new_event_loop()
        self._server: Optional[StorageServer] = None
        self._boot_error: Optional[BaseException] = None
        self._started = threading.Event()
        self._thread = threading.Thread(
            target=self._run_loop, name="pio-storage-server", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30):
            raise StorageError("storage server thread failed to start in 30s")
        if self._boot_error is not None:
            raise StorageError(
                f"storage server failed to start: {self._boot_error!r}"
            ) from self._boot_error

    @property
    def url(self) -> str:
        return f"http://{self.config.ip}:{self.config.port}"

    def _run_loop(self) -> None:
        asyncio.set_event_loop(self._loop)

        async def boot():
            self._server = StorageServer(self.config, self.storage)
            await self._server.start()
            if self.config.port == 0:
                # ephemeral bind: publish the kernel-chosen port
                self.config.port = self._server._runner.addresses[0][1]

        try:
            self._loop.run_until_complete(boot())
        except BaseException as e:  # noqa: BLE001 - reported to the constructor
            self._boot_error = e
            self._started.set()
            return
        self._started.set()
        self._loop.run_forever()

    def close(self) -> None:
        async def stop():
            await self._server.shutdown()
            self._loop.stop()

        if self._loop.is_running():
            asyncio.run_coroutine_threadsafe(stop(), self._loop)
            self._thread.join(timeout=10)
        self._loop.close()


# ---------------------------------------------------------------------------
# RPC handler table: (store, method) -> fn(storage, args) -> jsonable
# ---------------------------------------------------------------------------

def _events_insert(s: Storage, a: dict):
    return s.get_events().insert(
        Event.from_json_dict(a["event"]), a["app_id"], a.get("channel_id"))


def _events_insert_batch(s: Storage, a: dict):
    evs = [Event.from_json_dict(d) for d in a["events"]]
    return s.get_events().insert_batch(evs, a["app_id"], a.get("channel_id"))


def _events_get(s: Storage, a: dict):
    e = s.get_events().get(a["event_id"], a["app_id"], a.get("channel_id"))
    return None if e is None else e.to_json_dict()


def _events_delete(s: Storage, a: dict):
    return s.get_events().delete(a["event_id"], a["app_id"], a.get("channel_id"))


def _events_init(s: Storage, a: dict):
    return s.get_events().init(a["app_id"], a.get("channel_id"))


def _events_remove(s: Storage, a: dict):
    return s.get_events().remove(a["app_id"], a.get("channel_id"))


def _events_find_by_entities(s: Storage, a: dict):
    """Bulk per-entity read as ONE unary RPC (ROADMAP open item): the
    batched-serving O(1)-reads-per-batch property holds across a split
    query-server/storage-server topology because the backing store's own
    bulk override (single scan / SQL IN / ES terms) runs server-side."""
    res = s.get_events().find_by_entities(
        a["app_id"], a["entity_type"], a["entity_ids"],
        channel_id=a.get("channel_id"),
        start_time=dec_dt(a.get("start_time")),
        until_time=dec_dt(a.get("until_time")),
        event_names=a.get("event_names"),
        target_entity_type=dec_opt_filter(a, "target_entity_type"),
        target_entity_id=dec_opt_filter(a, "target_entity_id"),
        limit_per_entity=a.get("limit_per_entity"),
        reversed=a.get("reversed", False),
    )
    return {eid: [e.to_json_dict() for e in evs] for eid, evs in res.items()}


def _events_aggregate(s: Storage, a: dict):
    agg = s.get_events().aggregate_properties(
        a["app_id"], a["entity_type"],
        channel_id=a.get("channel_id"),
        start_time=dec_dt(a.get("start_time")),
        until_time=dec_dt(a.get("until_time")),
        required=a.get("required"),
        n_shards=a.get("n_shards"),
        shard_index=a.get("shard_index", 0),
    )
    return {
        k: {"fields": v.to_dict(),
            "first_updated": enc_dt(v.first_updated),
            "last_updated": enc_dt(v.last_updated)}
        for k, v in agg.items()
    }


def _meta_handlers(store_name: str, getter, record_cls):
    enc, _dec = _META_CODECS[record_cls]

    def insert(s, a):
        return getter(s).insert(_dec(a["record"]))

    def get(s, a):
        r = getter(s).get(a["id"])
        return None if r is None else enc(r)

    def get_all(s, a):
        return [enc(r) for r in getter(s).get_all()]

    def update(s, a):
        return getter(s).update(_dec(a["record"]))

    def delete(s, a):
        return getter(s).delete(a["id"])

    return {
        (store_name, "insert"): insert,
        (store_name, "get"): get,
        (store_name, "get_all"): get_all,
        (store_name, "update"): update,
        (store_name, "delete"): delete,
    }


_RPC: dict[tuple, Any] = {
    ("events", "insert"): _events_insert,
    ("events", "insert_batch"): _events_insert_batch,
    ("events", "get"): _events_get,
    ("events", "delete"): _events_delete,
    ("events", "init"): _events_init,
    ("events", "remove"): _events_remove,
    ("events", "aggregate_properties"): _events_aggregate,
    ("events", "find_by_entities"): _events_find_by_entities,
    # models (bytes travel base64)
    ("models", "insert"): lambda s, a: s.get_model_data_models().insert(
        Model(a["id"], base64.b64decode(a["blob"]))),
    ("models", "get"): lambda s, a: (
        (lambda m: None if m is None else
         {"id": m.id, "blob": base64.b64encode(m.models).decode()})
        (s.get_model_data_models().get(a["id"]))),
    ("models", "delete"): lambda s, a: s.get_model_data_models().delete(a["id"]),
}

_RPC.update(_meta_handlers("apps", Storage.get_meta_data_apps, App))
_RPC.update(_meta_handlers(
    "access_keys", Storage.get_meta_data_access_keys, AccessKey))
_RPC.update(_meta_handlers("channels", Storage.get_meta_data_channels, Channel))

# apps/access_keys/channels extra finders
_RPC[("apps", "get_by_name")] = lambda s, a: (
    (lambda r: None if r is None else _META_CODECS[App][0](r))
    (s.get_meta_data_apps().get_by_name(a["name"])))
_RPC[("access_keys", "get_by_app_id")] = lambda s, a: [
    _META_CODECS[AccessKey][0](k)
    for k in s.get_meta_data_access_keys().get_by_app_id(a["app_id"])]
_RPC[("channels", "get_by_app_id")] = lambda s, a: [
    _META_CODECS[Channel][0](c)
    for c in s.get_meta_data_channels().get_by_app_id(a["app_id"])]

# engine / evaluation instances (datetimes in records)
_RPC[("engine_instances", "insert")] = lambda s, a: (
    s.get_meta_data_engine_instances().insert(dec_engine_instance(a["record"])))
_RPC[("engine_instances", "get")] = lambda s, a: (
    (lambda r: None if r is None else enc_engine_instance(r))
    (s.get_meta_data_engine_instances().get(a["id"])))
_RPC[("engine_instances", "get_all")] = lambda s, a: [
    enc_engine_instance(r)
    for r in s.get_meta_data_engine_instances().get_all()]
_RPC[("engine_instances", "update")] = lambda s, a: (
    s.get_meta_data_engine_instances().update(dec_engine_instance(a["record"])))
_RPC[("engine_instances", "delete")] = lambda s, a: (
    s.get_meta_data_engine_instances().delete(a["id"]))
_RPC[("evaluation_instances", "insert")] = lambda s, a: (
    s.get_meta_data_evaluation_instances().insert(
        dec_evaluation_instance(a["record"])))
_RPC[("evaluation_instances", "get")] = lambda s, a: (
    (lambda r: None if r is None else enc_evaluation_instance(r))
    (s.get_meta_data_evaluation_instances().get(a["id"])))
_RPC[("evaluation_instances", "get_all")] = lambda s, a: [
    enc_evaluation_instance(r)
    for r in s.get_meta_data_evaluation_instances().get_all()]
_RPC[("evaluation_instances", "update")] = lambda s, a: (
    s.get_meta_data_evaluation_instances().update(
        dec_evaluation_instance(a["record"])))
_RPC[("evaluation_instances", "delete")] = lambda s, a: (
    s.get_meta_data_evaluation_instances().delete(a["id"]))

# jobs (docs/jobs.md): the durable orchestrator queue. ``cas`` is the one
# non-CRUD verb — record + expected version in ONE call, so the server-side
# store's compare-and-swap is the claim-atomicity point for remote workers.
_RPC[("jobs", "insert")] = lambda s, a: (
    s.get_meta_data_jobs().insert(dec_job(a["record"])))
_RPC[("jobs", "get")] = lambda s, a: (
    (lambda r: None if r is None else enc_job(r))
    (s.get_meta_data_jobs().get(a["id"])))
_RPC[("jobs", "get_all")] = lambda s, a: [
    enc_job(r) for r in s.get_meta_data_jobs().get_all()]
_RPC[("jobs", "cas")] = lambda s, a: (
    s.get_meta_data_jobs().cas(dec_job(a["record"]),
                               int(a["expected_version"])))
_RPC[("jobs", "delete")] = lambda s, a: s.get_meta_data_jobs().delete(a["id"])
