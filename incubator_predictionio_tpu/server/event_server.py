"""Event Server — REST ingestion API.

Parity target: data/api/EventServer.scala:54-663, route for route:

- ``GET  /``                    — welcome ``{"status": "alive"}``
- ``POST /events.json``         — create (201 + eventId; creationTime is
                                  forced server-side, EventJson4sSupport.scala:77)
- ``GET  /events.json``         — query with time/entity/event/target-entity
                                  filters (:314-333), ``limit`` default 20
                                  (−1 = all), ``reversed`` (requires both
                                  entityType and entityId, :329-333)
- ``GET/DELETE /events/<id>.json``
- ``POST /batch/events.json``   — ≤ 50 events, per-item statuses (:376-462)
- ``GET  /stats.json``          — opt-in via PIO_EVENTSERVER_STATS=true
- ``POST/GET /webhooks/<name>.json`` and ``.form`` — connector SPI

Auth matches the reference (withAccessKey, EventServer.scala:92-120):
``accessKey`` query param or HTTP Basic username; per-key event whitelist;
optional ``channel`` query param resolved against the app's channels.
"""

from __future__ import annotations

import asyncio
import base64
import collections
import contextvars
import dataclasses
import datetime as _dt
import json
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Sequence

from aiohttp import web

from incubator_predictionio_tpu.obs.http import (
    add_observability_routes,
    telemetry_middleware,
)
from incubator_predictionio_tpu.obs.metrics import REGISTRY
from incubator_predictionio_tpu.resilience.breaker import publish_breaker_metrics

from incubator_predictionio_tpu.data.event import (
    Event,
    EventValidationError,
    time_prefixed_event_id,
    validate_event,
)
from incubator_predictionio_tpu.data.storage.base import AccessKey
from incubator_predictionio_tpu.data.storage.registry import Storage, get_storage
from incubator_predictionio_tpu.data.webhooks import CONNECTORS, ConnectorError
from incubator_predictionio_tpu.resilience.admission import (
    FairnessGate,
    RateEstimator,
    derive_retry_after,
)
from incubator_predictionio_tpu.resilience.breaker import (
    BREAKERS,
    CircuitBreaker,
    CircuitOpenError,
)
from incubator_predictionio_tpu.resilience.clock import SYSTEM_CLOCK, Clock
from incubator_predictionio_tpu.resilience.policy import (
    DeadlineExceeded,
    TransientError,
)
from incubator_predictionio_tpu.resilience.wal import (
    DEAD_LETTER_TOTAL,
    SpillWal,
    WalError,
)
from incubator_predictionio_tpu.server.lifecycle import (
    DrainState,
    drained_exit_deadline,
    install_signal_drain,
)
from incubator_predictionio_tpu.server.stats import Stats

logger = logging.getLogger(__name__)

MAX_BATCH_SIZE = 50  # EventServer.scala:70

#: storage-write failures that mean "backend unhealthy", not "bad event" —
#: these count against the breaker and divert the write to the spill queue.
#: Deliberately NOT all StorageError: a semantic rejection (constraint
#: violation, mapping error) would be re-rejected identically on every
#: drain replay, wedging the queue head — those must surface to the caller.
_TRANSIENT_STORE_ERRORS = (ConnectionError, TimeoutError, OSError,
                           TransientError, CircuitOpenError, DeadlineExceeded)

# -- telemetry (obs/, docs/observability.md) --------------------------------
_SPILL_DEPTH = REGISTRY.gauge(
    "pio_spill_queue_depth",
    "Events waiting in the event server's in-memory spill queue")
_SPILL_MAX = REGISTRY.gauge(
    "pio_spill_queue_max", "Spill queue capacity")
_SPILLED = REGISTRY.counter(
    "pio_spill_events_total",
    "Events diverted to the spill queue because the store was failing")
_EVENTS_HOUR = REGISTRY.gauge(
    "pio_eventserver_requests_current_hour",
    "Current-hour ingestion outcomes per app (the /stats.json fold)",
    labels=("app_id", "status"))


class SpillQueueFull(Exception):
    """The storage breaker is open (or writes are failing) AND the bounded
    in-memory spill queue is at capacity — the only condition under which
    ingestion answers 503 (with Retry-After)."""


def _ssl_context(config) -> "Optional[object]":
    if not getattr(config, "ssl_cert", None):
        return None
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(config.ssl_cert, config.ssl_key)
    return ctx


@dataclasses.dataclass
class EventServerConfig:
    ip: str = "0.0.0.0"
    port: int = 7070
    # TLS termination (reference common/SSLConfiguration.scala:30 — JKS
    # keystore becomes a PEM cert/key pair)
    ssl_cert: Optional[str] = None
    ssl_key: Optional[str] = None
    stats: bool = dataclasses.field(
        default_factory=lambda: os.environ.get("PIO_EVENTSERVER_STATS", "").lower()
        in ("1", "true", "yes")
    )
    # -- write resilience (resilience/, docs/resilience.md) ---------------
    # bounded spill queue: events accepted (201) while the event store is
    # failing, drained when it recovers; 503 + Retry-After only when full
    spill_max: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_EVENTSERVER_SPILL_MAX", "1000")))
    retry_after_sec: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_EVENTSERVER_RETRY_AFTER", "5")))
    breaker_threshold: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_EVENTSERVER_BREAKER_THRESHOLD", "5")))
    breaker_reset_sec: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_EVENTSERVER_BREAKER_RESET", "10")))
    # -- durable spill (resilience/wal.py, docs/resilience.md) ------------
    # directory for the write-ahead log backing the spill queue. Set →
    # every spilled event is fsynced to disk BEFORE its 201, leftover
    # records replay idempotently at startup, and store-rejected batches
    # land in a dead-letter segment. Empty → PR 1's in-memory-only spill
    # (availability without crash durability).
    wal_dir: str = dataclasses.field(
        default_factory=lambda: os.environ.get("PIO_EVENT_WAL_DIR", ""))
    wal_segment_bytes: int = dataclasses.field(
        default_factory=lambda: int(
            os.environ.get("PIO_EVENT_WAL_SEGMENT_BYTES", str(16 << 20))))
    # PIO_EVENT_WAL_FSYNC=0 keeps the log but skips fsync (bench mode /
    # battery-backed storage): a crash may lose the OS write-back window
    wal_fsync: bool = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "PIO_EVENT_WAL_FSYNC", "1") != "0")
    # -- per-client fairness (resilience/admission.py) --------------------
    # token-bucket rate per access key, events/sec; 0 disables. A client
    # over its rate answers 429 + Retry-After alone — everyone else's
    # ingest is untouched. Enabling this trades the native C ingest fast
    # path for policing (the gate needs the parsed request).
    client_rate: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_EVENTSERVER_CLIENT_RATE", "0")))
    # bucket capacity (burst); 0 → 2× the rate
    client_burst: float = dataclasses.field(
        default_factory=lambda: float(
            os.environ.get("PIO_EVENTSERVER_CLIENT_BURST", "0")))


@dataclasses.dataclass
class AuthData:
    """(EventServer.scala AuthData)"""

    app_id: int
    channel_id: Optional[int]
    events: tuple[str, ...]  # whitelist; empty = all allowed


class WhitelistDenied(Exception):
    """Event name not in the access key's whitelist → 403."""


class EventServer:
    def __init__(self, config: EventServerConfig = EventServerConfig(),
                 storage: Optional[Storage] = None,
                 clock: Clock = SYSTEM_CLOCK):
        self.config = config
        self.storage = storage or get_storage()
        self.stats = Stats()
        # durable span export + sampling (obs/spool.py): applies the
        # PIO_TRACE_* env state; a no-op unless the spool dir is set
        from incubator_predictionio_tpu.obs import spool as trace_spool
        from incubator_predictionio_tpu.obs.plane import (
            configure_perf_plane_from_env,
        )

        trace_spool.configure_export_from_env("event_server")
        # continuous performance plane (obs/plane.py): procstats +
        # profiler + metrics history + SLO burn-rate engine
        configure_perf_plane_from_env("event_server")
        # -- overload protection (resilience/admission.py) ----------------
        # per-access-key token buckets: a misbehaving client is throttled
        # alone instead of starving every tenant's ingest; the drain-rate
        # estimator turns spill pressure into honest Retry-After hints
        self._fairness = FairnessGate(
            config.client_rate, config.client_burst, clock=clock,
            server="event_server")
        self._drain_rate = RateEstimator(clock=clock)
        # auth-cache TTLs and the shutdown-flush deadline run on the same
        # injected clock, so FakeClock tests can script expiry timelines
        self._clock = clock
        self._runner: Optional[web.AppRunner] = None
        # Storage calls are synchronous (LEvents contract, storage/base.py);
        # run them here so concurrent ingestion can't stall the accept loop —
        # the async surface the reference gets from Futures
        # (EventServer.scala:261-375). Backends are thread-safe (RLocks;
        # sqlite opens with check_same_thread=False).
        self._executor = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="evstore")
        # ingestion caches (group-commit work, VERDICT r3 #3): access-key
        # lookups hit METADATA per request and events.init per event — both
        # are invariant across the hot path, so amortize them. Auth entries
        # expire after _AUTH_TTL so key/channel changes take effect without
        # a restart (the reference re-reads per request; a short TTL is the
        # documented trade for ~10× the lookup cost).
        self._auth_cache: dict[tuple[Optional[str], Optional[str]],
                               tuple[float, AuthData]] = {}
        self._AUTH_TTL = self._auth_ttl()
        self._init_done: set[tuple[int, Optional[int]]] = set()
        # single-core hosts: the executor hop buys no overlap (the GIL and
        # the core are the same resource) and costs two thread switches per
        # request — run batch ingests inline on the loop there. Multi-core
        # hosts keep the hop so a slow durable write can't stall the accept
        # loop while other cores could be parsing the next request.
        self._inline_batch = (os.cpu_count() or 2) <= 1
        # -- write resilience (resilience/) -------------------------------
        # breaker over the event store's write path: opens after
        # consecutive transient failures; while failing/open, accepted
        # events divert to the bounded spill queue and drain on recovery
        self._store_breaker = CircuitBreaker(
            "eventstore", failure_threshold=config.breaker_threshold,
            reset_timeout=config.breaker_reset_sec)
        # spill entries: (event, app_id, channel_id, wal_seq) — wal_seq is
        # None when the WAL is disabled
        self._spill: collections.deque[
            tuple[Event, int, Optional[int], Optional[int]]] = (
            collections.deque())
        self._spill_lock = threading.Lock()
        self._drain_task: Optional[asyncio.Task] = None
        self._DRAIN_INTERVAL = 0.5
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # -- durable spill (resilience/wal.py) ----------------------------
        # acked-but-unstored events survive kill -9: fsync before the 201,
        # idempotent replay of leftovers here at startup
        self._dead_lettered = 0  # this process's count (health surface)
        self._wal: Optional[SpillWal] = None
        if config.wal_dir:
            self._wal = SpillWal(config.wal_dir,
                                 segment_bytes=config.wal_segment_bytes,
                                 fsync=config.wal_fsync)
            for rec in self._wal.replay():
                self._spill.append((Event.from_json_dict(rec["event"]),
                                    rec["app_id"], rec.get("channel_id"),
                                    rec["seq"]))
            if self._spill:
                logger.warning(
                    "WAL replay: %d acked event(s) from a previous process "
                    "re-queued for drain (first ids: %s)", len(self._spill),
                    [e.event_id for e, _, _, _ in list(self._spill)[:8]])
        # -- graceful drain (server/lifecycle.py) -------------------------
        self._drain_state = DrainState("event_server",
                                       retry_after_sec=config.retry_after_sec)
        # fold this server's signals into /metrics at scrape time (keyed:
        # a re-constructed server replaces its predecessor's collector)
        REGISTRY.add_collector("event_server", self._collect_metrics)

    def _collect_metrics(self) -> None:
        """Exposition-time fold: spill depth, the standalone event-store
        breaker, and (when enabled) the hourly Stats counters."""
        # lockless read: len(deque) is GIL-atomic, and taking _spill_lock
        # here would queue the scrape behind ingest threads' fsyncs
        depth = len(self._spill)
        _SPILL_DEPTH.set(depth)
        _SPILL_MAX.set(self.config.spill_max)
        publish_breaker_metrics({"eventstore": self._store_breaker.snapshot()})
        # clear-then-set: when the hour rolls, current_totals() drops apps —
        # label sets absent from the new snapshot must not keep serving the
        # old hour's counts (the metrics-layer twin of the stats.py fix)
        _EVENTS_HOUR.clear()
        if self.config.stats:
            for app_id, statuses in self.stats.current_totals().items():
                for status, n in statuses.items():
                    _EVENTS_HOUR.labels(app_id=str(app_id),
                                        status=status).set(n)

    def _retry_after_hint(self) -> int:
        """Pressure-derived ``Retry-After`` for 503s: WAL-backed spill
        depth ÷ the recent drain throughput (resilience/admission.py),
        falling back to the static ``retry_after_sec`` when the drainer
        has produced no rate signal yet — a client told '5' while 900
        events drain at 50/s would just come back to another 503."""
        return derive_retry_after(len(self._spill), self._drain_rate.rate(),
                                  self.config.retry_after_sec)

    def _throttle_response(self, retry_after: int,
                           app_id: Optional[int] = None) -> web.Response:
        # overload rejections must be visible in /stats.json like the 503
        # spill path — a hot app's event count dropping with no 429 tally
        # would read as lost traffic, not rate enforcement
        if self.config.stats and app_id is not None:
            self.stats.update(app_id, 429, "<throttled>", "<throttled>")
        return web.json_response(
            {"message": "client rate limit exceeded; retry later "
                        "(docs/resilience.md)"},
            status=429, headers={"Retry-After": str(retry_after)})

    @staticmethod
    def _auth_ttl() -> float:
        """Auth-cache TTL (seconds). A cached success means a revoked key /
        deleted channel / tightened whitelist is honored for up to TTL after
        the change — a staleness window the reference's per-request lookup
        doesn't have. PIO_EVENTSERVER_AUTH_TTL overrides; 0 disables caching
        (restores exact reference semantics at ~10× the lookup cost).
        Read per server instance; a malformed value is a warning, not a
        crash of every importer."""
        raw = os.environ.get("PIO_EVENTSERVER_AUTH_TTL", "5.0")
        try:
            return float(raw)
        except ValueError:
            logger.warning(
                "invalid PIO_EVENTSERVER_AUTH_TTL=%r; using 5.0s", raw)
            return 5.0

    async def _run(self, fn, *args):
        """Run a blocking storage call off the event loop. The caller's
        contextvars (trace identity from the telemetry middleware, ambient
        deadline) are copied into the worker thread — run_in_executor alone
        would drop them."""
        ctx = contextvars.copy_context()
        return await asyncio.get_running_loop().run_in_executor(
            self._executor, lambda: ctx.run(fn, *args))

    # -- auth (EventServer.scala:92-120) ----------------------------------
    @staticmethod
    def _extract_key(request: web.Request) -> Optional[str]:
        """accessKey query param or HTTP Basic username — ONE definition:
        the cache key below must always match the identity _authenticate
        resolves."""
        key = request.query.get("accessKey")
        if not key:
            auth = request.headers.get("Authorization", "")
            if auth.startswith("Basic "):
                try:
                    decoded = base64.b64decode(auth[6:]).decode()
                    key = decoded.split(":", 1)[0]
                except Exception:
                    key = None
        return key

    async def _authenticate_cached(self, request: web.Request) -> AuthData:
        """Auth with a short-TTL cache over (accessKey, channel) — the
        metadata lookups are per-request invariant on the ingest hot path."""
        if self._loop is None:
            # embedded runs (aiohttp test server) never call start(); the
            # spill drainer still needs a loop to schedule onto
            self._loop = asyncio.get_running_loop()
        key = self._extract_key(request)
        channel = request.query.get("channel")
        if self._AUTH_TTL <= 0:  # caching disabled: per-request lookup
            return await self._run(self._authenticate, request)
        now = self._clock.monotonic()
        hit = self._auth_cache.get((key, channel))
        if hit is not None and hit[0] > now:
            return hit[1]
        try:
            data = await self._run(self._authenticate, request)
        except web.HTTPException:
            # a rejection must never serve from (or leave) a cached success
            self._auth_cache.pop((key, channel), None)
            raise
        if len(self._auth_cache) > 1024:  # unbounded-growth guard
            self._auth_cache.clear()
        self._auth_cache[(key, channel)] = (now + self._AUTH_TTL, data)
        return data

    def _authenticate(self, request: web.Request) -> AuthData:
        return self._authenticate_parts(
            self._extract_key(request), request.query.get("channel"))

    def _authenticate_parts(self, key: Optional[str],
                            channel_name: Optional[str]) -> AuthData:
        """(key, channel) → AuthData or web.HTTPUnauthorized — the request-
        free core, shared with the native HTTP front's sync handler."""
        if not key:
            raise web.HTTPUnauthorized(
                text=json.dumps({"message": "Missing accessKey."}),
                content_type="application/json",
            )
        access_key: Optional[AccessKey] = (
            self.storage.get_meta_data_access_keys().get(key)
        )
        if access_key is None:
            raise web.HTTPUnauthorized(
                text=json.dumps({"message": "Invalid accessKey."}),
                content_type="application/json",
            )
        channel_id = None
        if channel_name:
            channels = self.storage.get_meta_data_channels().get_by_app_id(
                access_key.app_id
            )
            match = next((c for c in channels if c.name == channel_name), None)
            if match is None:
                raise web.HTTPUnauthorized(
                    text=json.dumps({"message": "Invalid channel."}),
                    content_type="application/json",
                )
            channel_id = match.id
        return AuthData(access_key.app_id, channel_id, access_key.events)

    def _check_whitelist(self, auth: AuthData, event_name: str) -> None:
        # 403 for non-whitelisted events (EventServer.scala:293, :431)
        if auth.events and event_name not in auth.events:
            raise WhitelistDenied(f"{event_name} events are not allowed")

    # -- ingestion --------------------------------------------------------
    def _prepare_event(self, payload: dict, auth: AuthData,
                       receipt: Optional[_dt.datetime] = None) -> Event:
        """Parse/validate one payload into a storable Event (no insert)."""
        from incubator_predictionio_tpu.server.plugins import (
            EVENT_SERVER_PLUGINS,
            apply_input_plugins,
        )

        if EVENT_SERVER_PLUGINS:  # defensive copy only if a plugin may mutate
            payload = apply_input_plugins(dict(payload))
        # server assigns receipt time; client-supplied creationTime is ignored
        # (EventJson4sSupport.scala:77-78)
        event = Event.from_json_dict(
            payload,
            creation_time=receipt or _dt.datetime.now(_dt.timezone.utc))
        validate_event(event)
        self._check_whitelist(auth, event.event)
        return event

    def _ensure_init(self, auth: AuthData) -> None:
        """events.init once per (app, channel) per process — per-event init
        costs several storage round trips for an idempotent no-op."""
        key = (auth.app_id, auth.channel_id)
        if key not in self._init_done:
            self.storage.get_events().init(auth.app_id, auth.channel_id)
            self._init_done.add(key)

    def _insert_healing(self, op, auth: AuthData):
        """Run a storage write; if it fails because the table/log vanished
        (another process ran data-delete), drop the init cache, re-init and
        retry once — the per-event init this cache replaced was self-healing,
        so the cached path must be too."""
        try:
            return op()
        except Exception as err:
            if "no such table" not in str(err) and "not initialized" not in \
                    str(err) and "UndefinedTable" not in type(err).__name__:
                raise
            self._init_done.discard((auth.app_id, auth.channel_id))
            self._ensure_init(auth)
            return op()

    # -- breaker-guarded writes + spill queue (resilience/) ---------------
    def _store_events(self, events: Sequence[Event], auth: AuthData) -> list[str]:
        """The ONE write path to the event store: gated by the breaker,
        transient failures spill to the bounded in-memory queue (the write
        is still acknowledged 201 — its id is pre-assigned so the drain
        replay is idempotent), and only a full queue raises
        :class:`SpillQueueFull` (→ 503 + Retry-After).

        Ids are pre-assigned BEFORE the first attempt: a write whose
        response was lost may have committed, and a spill-then-drain replay
        under fresh ids would silently double-store those events — with the
        id fixed up front, the replay overwrites itself on every backend
        (INSERT OR REPLACE / explicit-id index)."""
        events = [e if e.event_id else
                  e.with_id(time_prefixed_event_id(e.creation_time))
                  for e in events]
        if not self._store_breaker.allow():
            return self._spill_events(events, auth)
        try:
            self._ensure_init(auth)
            ids = self._insert_healing(
                lambda: self.storage.get_events().insert_batch(
                    list(events), auth.app_id, auth.channel_id), auth)
        except _TRANSIENT_STORE_ERRORS as e:
            self._store_breaker.record_failure()
            logger.warning("event store write failed (%s); spilling %d "
                           "event(s)", e, len(events))
            return self._spill_events(events, auth)
        except Exception:
            # non-transient = the store answered (bad data, programming
            # error): health-wise a success, and a half-open probe slot
            # must not leak
            self._store_breaker.record_success()
            raise
        self._store_breaker.record_success()
        return ids

    def _spill_events(self, events: Sequence[Event],
                      auth: AuthData) -> list[str]:
        with self._spill_lock:
            if len(self._spill) + len(events) > self.config.spill_max:
                raise SpillQueueFull(
                    f"spill queue at capacity ({self.config.spill_max})")
            ids = []
            stamped = []
            for e in events:
                # ids were pre-assigned by _store_events (time-prefixed
                # 32-hex, btree-right-edge friendly for the burst replay);
                # direct callers may still hand in id-less events
                eid = e.event_id or time_prefixed_event_id(e.creation_time)
                stamped.append(e.with_id(eid))
                ids.append(eid)
            seqs: list[Optional[int]] = [None] * len(stamped)
            if self._wal is not None:
                # durability BEFORE the ack: one group-commit append+fsync
                # for the whole batch — only after it returns may these
                # events be 201-acked (docs/resilience.md ack contract)
                try:
                    last = self._wal.append([
                        {"event": e.to_json_dict(), "app_id": auth.app_id,
                         "channel_id": auth.channel_id} for e in stamped])
                except WalError as err:
                    # can't make the ack durable (disk full / unwritable):
                    # refuse like a full queue rather than silently demote
                    # the durability contract
                    raise SpillQueueFull(f"spill WAL unwritable: {err}") \
                        from err
                seqs = list(range(last - len(stamped) + 1, last + 1))
            for e, seq in zip(stamped, seqs):
                self._spill.append((e, auth.app_id, auth.channel_id, seq))
        _SPILLED.inc(len(ids))
        self._kick_drain()
        return ids

    def _kick_drain(self) -> None:
        """Ensure the drain task is running (callable from executor
        threads — the task itself must start on the loop)."""
        loop = self._loop
        if loop is None or loop.is_closed():
            return
        loop.call_soon_threadsafe(self._ensure_drain_task)

    def _ensure_drain_task(self) -> None:
        if self._drain_task is None or self._drain_task.done():
            self._drain_task = asyncio.get_running_loop().create_task(
                self._drain_spill())

    async def _drain_spill(self) -> None:
        while self._spill:
            try:
                progressed = await self._run(self._drain_spill_once)
            except Exception:  # noqa: BLE001 - the drainer must survive
                # _drain_spill_once already dropped the offending batch (a
                # store-rejected batch can never succeed on replay); log is
                # there — keep draining the rest after a beat
                progressed = False
            if not progressed:
                await asyncio.sleep(self._DRAIN_INTERVAL)

    def _drain_spill_once(self) -> bool:
        """Flush one head-of-queue batch (same app/channel run, ≤ 50).
        Returns True on progress; a failed probe re-opens the breaker and
        the caller backs off. Sync — tests drive recovery deterministically
        by calling this directly."""
        with self._spill_lock:
            if not self._spill:
                return True
            _, app_id, channel_id, _ = self._spill[0]
            batch = []
            batch_seqs: list[Optional[int]] = []
            for e, a, c, s in self._spill:
                if (a, c) != (app_id, channel_id) or len(batch) >= MAX_BATCH_SIZE:
                    break
                batch.append(e)
                batch_seqs.append(s)
        if not self._store_breaker.allow():
            return False
        auth = AuthData(app_id, channel_id, ())
        try:
            self._ensure_init(auth)
        except Exception as e:  # noqa: BLE001
            # init failing says NOTHING about these events (permission,
            # schema drift): never drop on an init error — back off and
            # keep the batch, whatever the failure class
            self._store_breaker.record_failure()
            logger.warning("spill drain: store init failed (%s); %d "
                           "event(s) still queued", e, len(self._spill))
            return False
        try:
            self._insert_healing(
                lambda: self.storage.get_events().insert_batch(
                    batch, app_id, channel_id), auth)
        except _TRANSIENT_STORE_ERRORS as e:
            self._store_breaker.record_failure()
            logger.warning("spill drain probe failed (%s); %d event(s) "
                           "still queued", e, len(self._spill))
            return False
        except Exception:
            # the store ANSWERED and rejected THIS batch (semantic error):
            # replaying it forever would wedge the whole queue behind it —
            # divert it to the dead-letter segment, loudly, instead of the
            # silent drop PR 1 shipped (these events were 201-acked; with
            # the WAL they stay recoverable via `pio-tpu wal`)
            self._store_breaker.record_success()
            with self._spill_lock:
                for _ in range(len(batch)):
                    self._spill.popleft()
                # SpillWal is not thread-safe: every mutation happens under
                # _spill_lock (append already does) — dead_letter outside it
                # could race an ingest append's rotation/bookkeeping
                if self._wal is not None:
                    self._wal.dead_letter([
                        {"seq": s, "event": e.to_json_dict(),
                         "app_id": app_id, "channel_id": channel_id}
                        for e, s in zip(batch, batch_seqs)])
            self._dead_lettered += len(batch)
            if self._wal is None:
                DEAD_LETTER_TOTAL.inc(len(batch))
            logger.exception(
                "spill drain: store rejected %d event(s) non-transiently; "
                "dead-lettered to unwedge the queue (ids: %s, wal: %s)",
                len(batch), [e.event_id for e in batch][:8],
                self.config.wal_dir or "<disabled>")
            raise
        self._store_breaker.record_success()
        # drained events are the Retry-After hint's rate signal: clients
        # told to come back see depth ÷ THIS throughput, not a constant
        self._drain_rate.record(len(batch))
        with self._spill_lock:
            # only this drainer pops; ingest threads only append — the head
            # run we snapshotted is still the head
            for _ in range(len(batch)):
                self._spill.popleft()
            # commit under the SAME lock append holds: a commit racing an
            # append could snapshot a stale per-segment max and delete a
            # segment holding a newer fsynced (201-acked) frame
            if self._wal is not None:
                committed = [s for s in batch_seqs if s is not None]
                if committed:
                    self._wal.commit(max(committed))
        logger.info("spill drain: flushed %d event(s), %d remaining",
                    len(batch), len(self._spill))
        return True

    def _ingest_one(self, payload: dict, auth: AuthData) -> str:
        event = self._prepare_event(payload, auth)
        return self._store_events([event], auth)[0]

    async def _try_native_ingest(self, raw: bytes, single: bool,
                                 max_items: int, auth: AuthData):
        """C ingest fast path (VERDICT r4 next #4): raw body → native
        parse→validate→encode→append when the storage backend supports it
        (eventlog) and no input plugins are registered. Returns per-item
        response dicts, or None when the Python path must run (its results
        are identical — the C core declines anything it can't match
        byte-for-byte)."""
        from incubator_predictionio_tpu.server.plugins import EVENT_SERVER_PLUGINS

        if EVENT_SERVER_PLUGINS:
            return None
        store = self.storage.get_events()
        fn = getattr(store, "ingest_raw", None)
        if fn is None:
            return None

        def op():
            # _ensure_init inside the hop: the first touch of a large log
            # parses the whole file — that must not block the accept loop
            self._ensure_init(auth)
            return self._insert_healing(
                lambda: fn(raw, single, max_items, auth.events,
                           auth.app_id, auth.channel_id),
                auth,
            )

        return op() if self._inline_batch else await self._run(op)

    async def handle_create(self, request: web.Request) -> web.Response:
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        auth = await self._authenticate_cached(request)
        throttle = self._fairness.admit(self._extract_key(request) or "")
        if throttle is not None:
            return self._throttle_response(throttle, auth.app_id)
        raw = await request.read()
        if not self.config.stats:  # stats needs the parsed payload fields
            fast = await self._try_native_ingest(raw, True, -1, auth)
            if fast is not None:
                r = fast[0]
                if r["status"] == 201:
                    return web.json_response({"eventId": r["eventId"]}, status=201)
                return web.json_response({"message": r["message"]},
                                         status=r["status"])
        payload = None
        headers = None
        try:
            payload = await request.json()
            if not isinstance(payload, dict):
                raise EventValidationError("event JSON must be an object")
            event_id = await self._run(self._ingest_one, payload, auth)
            status, body = 201, {"eventId": event_id}
        except (EventValidationError, json.JSONDecodeError) as e:
            status, body = 400, {"message": str(e)}
        except WhitelistDenied as e:
            status, body = 403, {"message": str(e)}
        except SpillQueueFull as e:
            status, body, headers = 503, {"message": str(e)}, \
                {"Retry-After": str(self._retry_after_hint())}
        if self.config.stats:
            self.stats.update(
                auth.app_id, status,
                payload.get("event", "<invalid>") if isinstance(payload, dict) else "<invalid>",
                payload.get("entityType", "<invalid>") if isinstance(payload, dict) else "<invalid>",
            )
        return web.json_response(body, status=status, headers=headers)

    def _ingest_batch(self, payload: list, auth: AuthData) -> list[dict]:
        """One executor hop AND one storage write for the whole batch.

        Per-item validation statuses are preserved (EventServer.scala:430-433:
        a denied/malformed item doesn't fail its neighbors); the accepted
        items then land via ONE ``insert_batch`` — one transaction/commit in
        sqlite, one append+flush in the event log — instead of a per-event
        insert+fsync (the round-3 ingestion wall)."""
        results: list[dict] = []
        accepted: list[tuple[int, Event]] = []  # (result slot, event)
        for item in payload:
            try:
                if not isinstance(item, dict):
                    raise EventValidationError("event JSON must be an object")
                # receipt creationTime stamped PER ITEM, matching
                # EventJson4sSupport.scala:77-78 (each event at its own
                # processing time — consumers sorting/deduping on
                # creationTime must not see batch-wide ties)
                accepted.append(
                    (len(results), self._prepare_event(item, auth, None)))
                results.append({"status": 201})  # eventId filled below
            except EventValidationError as e:
                results.append({"status": 400, "message": str(e)})
            except WhitelistDenied as e:
                # per-item 403, batch continues (EventServer.scala:430-433)
                results.append({"status": 403, "message": str(e)})
        if accepted:
            try:
                ids = self._store_events([e for _, e in accepted], auth)
            except SpillQueueFull as e:
                # per-item statuses were already decided for the 400/403
                # items — carry them on the exception so stats bookkeeping
                # doesn't flatten the whole batch to 503
                for slot, _ in accepted:
                    results[slot] = {"status": 503}
                e.results = results
                raise
            for (slot, _), event_id in zip(accepted, ids):
                results[slot]["eventId"] = event_id
        return results

    async def handle_batch(self, request: web.Request) -> web.Response:
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        auth = await self._authenticate_cached(request)
        raw = await request.read()
        # stats needs the parsed payload fields (ADVICE r5: the fast path
        # must not make batched events invisible to /stats.json); fairness
        # needs the parsed item count — both gate the raw-bytes fast path
        if not self.config.stats and not self._fairness.enabled:
            fast = await self._try_native_ingest(raw, False, MAX_BATCH_SIZE, auth)
            if fast is not None:
                return web.json_response(fast, status=200)
        try:
            payload = json.loads(raw)
        except json.JSONDecodeError as e:
            return web.json_response({"message": str(e)}, status=400)
        if not isinstance(payload, list):
            return web.json_response({"message": "request body must be a JSON array"},
                                     status=400)
        if len(payload) > MAX_BATCH_SIZE:
            # EventServer.scala:390: whole batch rejected
            return web.json_response(
                {"message": f"Batch request must have less than or equal to "
                            f"{MAX_BATCH_SIZE} events"},
                status=400,
            )
        # fairness charges the batch its event count — 50-event batches
        # must not cost the same as single posts or the bucket is a sieve
        throttle = self._fairness.admit(
            self._extract_key(request) or "", float(max(1, len(payload))))
        if throttle is not None:
            return self._throttle_response(throttle, auth.app_id)
        try:
            if self._inline_batch:
                results = self._ingest_batch(payload, auth)
            else:
                results = await self._run(self._ingest_batch, payload, auth)
        except SpillQueueFull as e:
            if self.config.stats:
                # overload rejections must be visible in /stats.json, same
                # as handle_create's 503 bookkeeping — with the validated
                # items' own 400/403 statuses preserved
                self._update_batch_stats(
                    auth, payload,
                    getattr(e, "results", None)
                    or [{"status": 503}] * len(payload))
            return web.json_response(
                {"message": str(e)}, status=503,
                headers={"Retry-After": str(self._retry_after_hint())})
        if self.config.stats:
            # per accepted/denied item, like the reference's per-batch-event
            # Bookkeeping updates (EventServer.scala:421-423)
            self._update_batch_stats(auth, payload, results)
        return web.json_response(results, status=200)

    def _update_batch_stats(self, auth: AuthData, payload: list,
                            results: list[dict]) -> None:
        for item, r in zip(payload, results):
            is_dict = isinstance(item, dict)
            self.stats.update(
                auth.app_id, r["status"],
                item.get("event", "<invalid>") if is_dict else "<invalid>",
                item.get("entityType", "<invalid>") if is_dict else "<invalid>",
            )

    # -- reads ------------------------------------------------------------
    async def handle_get_event(self, request: web.Request) -> web.Response:
        auth = await self._authenticate_cached(request)
        event = await self._run(
            self.storage.get_events().get,
            request.match_info["event_id"], auth.app_id, auth.channel_id,
        )
        if event is None:
            return web.json_response({"message": "Not Found"}, status=404)
        return web.json_response(event.to_json_dict())

    async def handle_delete_event(self, request: web.Request) -> web.Response:
        auth = await self._authenticate_cached(request)
        found = await self._run(
            self.storage.get_events().delete,
            request.match_info["event_id"], auth.app_id, auth.channel_id,
        )
        if found:
            return web.json_response({"message": "Found"})
        return web.json_response({"message": "Not Found"}, status=404)

    async def handle_find(self, request: web.Request) -> web.Response:
        auth = await self._authenticate_cached(request)
        q = request.query

        def parse_time(name: str) -> Optional[_dt.datetime]:
            v = q.get(name)
            if not v:
                return None
            try:
                return _dt.datetime.fromisoformat(v.replace("Z", "+00:00"))
            except ValueError:
                raise web.HTTPBadRequest(
                    text=json.dumps({"message": f"Invalid {name}: {v}"}),
                    content_type="application/json",
                )

        try:
            limit = int(q.get("limit", 20))
        except ValueError:
            return web.json_response(
                {"message": f"Invalid limit: {q.get('limit')}"}, status=400
            )
        event_names = q.getall("event") if "event" in q else None
        from incubator_predictionio_tpu.data.storage.base import UNSET, StorageError

        start_time, until_time = parse_time("startTime"), parse_time("untilTime")
        is_reversed = q.get("reversed", "false").lower() == "true"
        # EventServer.scala:329-333 — reversed requires both entity params.
        if is_reversed and not (q.get("entityType") and q.get("entityId")):
            return web.json_response(
                {
                    "message": "the parameter reversed can only be used with "
                    "both entityType and entityId specified."
                },
                status=400,
            )
        target_entity_type = (
            q["targetEntityType"] if "targetEntityType" in q else UNSET
        )
        target_entity_id = q["targetEntityId"] if "targetEntityId" in q else UNSET

        def do_find() -> list[dict]:
            found = self.storage.get_events().find(
                auth.app_id,
                auth.channel_id,
                start_time=start_time,
                until_time=until_time,
                entity_type=q.get("entityType"),
                entity_id=q.get("entityId"),
                event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id,
                limit=None if limit == -1 else limit,
                reversed=is_reversed,
            )
            return [e.to_json_dict() for e in found]

        try:
            events = await self._run(do_find)
        except StorageError as e:  # uninitialized app/channel table
            return web.json_response({"message": str(e)}, status=404)
        if not events:
            return web.json_response({"message": "Not Found"}, status=404)
        return web.json_response(events)

    # -- misc -------------------------------------------------------------
    async def handle_root(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "alive"})

    async def handle_health(self, request: web.Request) -> web.Response:
        """Breaker + spill-queue + durability state (resilience/):
        'draining' during a graceful exit, 'degraded' while the event store
        is being routed around, 'ok' otherwise — always 200 (the server
        itself is alive either way)."""
        store = self._store_breaker.snapshot()
        backends = BREAKERS.snapshot()
        # lockless: _spill_lock is held across WAL fsyncs by ingest
        # threads — /health runs ON the event loop and must never queue
        # behind a disk flush (len(deque) is GIL-atomic)
        depth = len(self._spill)
        degraded = depth > 0 or any(
            s["state"] != "closed" for s in (store, *backends.values()))
        from incubator_predictionio_tpu.obs import slo as _slo

        return web.json_response({
            "status": self._drain_state.health_status(degraded),
            "draining": self._drain_state.draining,
            # SLO burn-rate verdicts (obs/slo.py; None when no PIO_SLO_CONFIG)
            "slo": _slo.health_block(),
            "eventStoreBreaker": store,
            "backendBreakers": backends,
            "spillQueueDepth": depth,
            "spillQueueMax": self.config.spill_max,
            # overload surface (docs/resilience.md "Overload & admission
            # control"): what a 503'd client would currently be told, and
            # the per-client fairness tallies
            "admission": {
                "retryAfterHint": self._retry_after_hint(),
                "drainRatePerSec": round(self._drain_rate.rate(), 3),
                "fairness": self._fairness.snapshot(),
            },
            "spillWal": {
                "enabled": self._wal is not None,
                "dir": self.config.wal_dir or None,
                "committedSeq": (self._wal.committed
                                 if self._wal is not None else None),
            },
            # 201-acked events the store rejected non-transiently — they
            # sit in the WAL dead-letter segment (`pio-tpu wal <dir>`)
            # instead of vanishing into a log line. With a WAL, report the
            # PERSISTED count: it survives restarts, so monitoring keeps
            # firing until an operator actually empties the segment
            "deadLettered": (self._wal.dead_letter_count
                             if self._wal is not None
                             else self._dead_lettered),
        })

    async def handle_stats(self, request: web.Request) -> web.Response:
        auth = await self._authenticate_cached(request)
        if not self.config.stats:
            return web.json_response(
                {"message": "To see stats, launch Event Server with stats enabled "
                            "(PIO_EVENTSERVER_STATS=true)"},
                status=404,
            )
        payload = self.stats.get(auth.app_id)
        # per-access-key fairness forensics (docs/tenancy.md): the bucket
        # fill + throttle tallies that NAME the noisy tenant before the
        # aggregate 429 counter alone would tell you one exists
        payload["fairness"] = {
            "enabled": self._fairness.enabled,
            "throttled": self._fairness.throttled_count,
            "perClient": self._fairness.per_client(),
        }
        return web.json_response(payload)

    # -- webhooks (EventServer.scala:491-599) -----------------------------
    async def handle_webhook(self, request: web.Request) -> web.Response:
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        auth = await self._authenticate_cached(request)
        throttle = self._fairness.admit(self._extract_key(request) or "")
        if throttle is not None:
            return self._throttle_response(throttle, auth.app_id)
        name = request.match_info["name"]
        form = request.match_info.get("ext") == "form"
        connector = CONNECTORS.get((name, "form" if form else "json"))
        if connector is None:
            return web.json_response({"message": f"webhook {name} not supported"},
                                     status=404)
        try:
            if form:
                data = dict(await request.post())
                event_json = connector.to_event_json(data)
            else:
                event_json = connector.to_event_json(await request.json())
            event_id = await self._run(self._ingest_one, event_json, auth)
            return web.json_response({"eventId": event_id}, status=201)
        except (ConnectorError, EventValidationError, json.JSONDecodeError) as e:
            return web.json_response({"message": str(e)}, status=400)
        except WhitelistDenied as e:
            return web.json_response({"message": str(e)}, status=403)
        except SpillQueueFull as e:
            return web.json_response(
                {"message": str(e)}, status=503,
                headers={"Retry-After": str(self._retry_after_hint())})

    async def handle_webhook_get(self, request: web.Request) -> web.Response:
        await self._authenticate_cached(request)
        name = request.match_info["name"]
        form = request.match_info.get("ext") == "form"
        if CONNECTORS.get((name, "form" if form else "json")) is None:
            return web.json_response({"message": f"webhook {name} not supported"},
                                     status=404)
        return web.json_response({"message": f"webhook {name} connected"})

    # -- app --------------------------------------------------------------
    def make_app(self) -> web.Application:
        app = web.Application(
            middlewares=[telemetry_middleware("event_server")])
        r = app.router
        r.add_get("/", self.handle_root)
        r.add_get("/health", self.handle_health)
        add_observability_routes(app)
        r.add_post("/events.json", self.handle_create)
        r.add_get("/events.json", self.handle_find)
        r.add_get("/events/{event_id}.json", self.handle_get_event)
        r.add_delete("/events/{event_id}.json", self.handle_delete_event)
        r.add_post("/batch/events.json", self.handle_batch)
        r.add_get("/stats.json", self.handle_stats)
        r.add_post("/webhooks/{name}.{ext:json|form}", self.handle_webhook)
        r.add_get("/webhooks/{name}.{ext:json|form}", self.handle_webhook_get)
        return app

    async def start(self) -> None:
        from incubator_predictionio_tpu.obs import procstats

        # loop-lag gauge rides this server's loop (pio_process_loop_lag_*)
        self._loop_lag = procstats.start_loop_lag("event_server")
        # the spill drainer schedules onto this loop from executor threads
        self._loop = asyncio.get_running_loop()
        if self._spill:
            # WAL replay re-queued acked events from a previous process:
            # start landing them as soon as a loop exists
            self._ensure_drain_task()
        # no per-request access log: formatting a log line per request costs
        # more than parsing the request at ingestion rates
        self._runner = web.AppRunner(self.make_app(), access_log=None)
        await self._runner.setup()
        use_front = (os.environ.get("PIO_NATIVE_HTTP", "1") != "0"
                     and self.config.ssl_cert is None
                     and self._native_front_possible())
        if use_front:
            # aiohttp becomes the loopback BACKEND; the native epoll front
            # owns the public port, answers the hot ingest routes through
            # _native_http_handler, and tunnels every other connection here
            from incubator_predictionio_tpu.server.front_boot import (
                start_with_native_front,
            )

            self._front = await start_with_native_front(
                self._runner, self.config.ip, self.config.port,
                self._native_http_handler,
                "POST /events.json,POST /batch/events.json,GET /",
                "event server")
            if self._front is not None:
                return
            # front failed (no native lib, port busy...): plain path
            self._runner = web.AppRunner(self.make_app(), access_log=None)
            await self._runner.setup()
        site = web.TCPSite(self._runner, self.config.ip, self.config.port,
                           ssl_context=_ssl_context(self.config))
        await site.start()
        logger.info("event server listening on %s:%d", self.config.ip, self.config.port)

    def _native_front_possible(self) -> bool:
        """The front only pays off when the hot routes can complete without
        aiohttp: a storage backend with a C ingest sink and no input
        plugins. (Everything else would tunnel anyway.)"""
        from incubator_predictionio_tpu import native
        from incubator_predictionio_tpu.server.plugins import EVENT_SERVER_PLUGINS

        if EVENT_SERVER_PLUGINS or native.get_lib() is None:
            return False
        if self._fairness.enabled:
            # per-client fairness needs every ingest to pass the token
            # bucket — the C front would answer hot routes un-policed
            return False
        return getattr(self.storage.get_events(), "ingest_raw", None) is not None

    def _native_http_handler(self, _token: int, method: str, path_qs: str,
                             body: bytes) -> Optional[bytes]:
        """Sync handler for the native front's hot routes. Returns the FULL
        HTTP response bytes, or ``None`` to make the front tunnel this exact
        request to aiohttp (the FALLBACK discipline: only answer what the
        fast path fully handles — auth via query param, C-sink storage)."""
        import urllib.parse

        def resp(status: int, reason: str, payload) -> bytes:
            body_b = json.dumps(payload).encode()
            return (f"HTTP/1.1 {status} {reason}\r\n"
                    f"Content-Type: application/json; charset=utf-8\r\n"
                    f"Content-Length: {len(body_b)}\r\n"
                    f"Connection: keep-alive\r\n\r\n").encode() + body_b

        try:
            if self._drain_state.draining:
                # tunnel: the aiohttp handlers own the 503 + Retry-After
                # draining answer, so both fronts reject identically
                return None
            path, _, qs = path_qs.partition("?")
            if method == "GET" and path == "/":
                return resp(200, "OK", {"status": "alive"})
            q = urllib.parse.parse_qs(qs)
            key = (q.get("accessKey") or [None])[0]
            channel = (q.get("channel") or [None])[0]
            if not key:
                return None  # Basic-auth header path: aiohttp owns it
            if self.config.stats:
                # stats needs the parsed payload fields — tunnel BOTH ingest
                # routes to aiohttp, which counts per item (ADVICE r5: the
                # batch route must not bypass /stats.json bookkeeping)
                return None
            try:
                auth = self._authenticate_cached_sync(key, channel)
            except web.HTTPException as e:
                return resp(e.status, e.reason, json.loads(e.text))
            single = path == "/events.json"
            store = self.storage.get_events()
            self._ensure_init(auth)
            fast = self._insert_healing(
                lambda: store.ingest_raw(
                    body, single, MAX_BATCH_SIZE, auth.events,
                    auth.app_id, auth.channel_id),
                auth)
            if fast is None:
                return None  # C sink declined: aiohttp reproduces exactly
            if single:
                r = fast[0]
                if r["status"] == 201:
                    return resp(201, "Created", {"eventId": r["eventId"]})
                reason = "Bad Request" if r["status"] == 400 else "Forbidden"
                return resp(r["status"], reason, {"message": r["message"]})
            return resp(200, "OK", fast)
        except Exception:  # noqa: BLE001 - never kill the epoll loop
            logger.exception("native front handler error; tunneling")
            return None

    def _authenticate_cached_sync(self, key: Optional[str],
                                  channel: Optional[str]) -> AuthData:
        """Sync twin of _authenticate_cached for the native front's thread
        (dict ops are GIL-atomic; the TTL semantics are identical)."""
        if self._AUTH_TTL <= 0:
            return self._authenticate_parts(key, channel)
        now = self._clock.monotonic()
        hit = self._auth_cache.get((key, channel))
        if hit is not None and hit[0] > now:
            return hit[1]
        try:
            data = self._authenticate_parts(key, channel)
        except web.HTTPException:
            self._auth_cache.pop((key, channel), None)
            raise
        if len(self._auth_cache) > 1024:
            self._auth_cache.clear()
        self._auth_cache[(key, channel)] = (now + self._AUTH_TTL, data)
        return data

    async def drain_and_shutdown(
            self, deadline_sec: Optional[float] = None) -> None:
        """The SIGTERM path (docs/resilience.md drain semantics): stop
        accepting ingest (503 + Retry-After, /health → 'draining'), give
        in-flight requests a moment to finish, flush the spill queue, and
        exit within the deadline. aiohttp's runner cleanup below waits for
        handlers that already entered the router."""
        self._drain_state.begin()
        await self.shutdown(
            flush_deadline_sec=(drained_exit_deadline()
                                if deadline_sec is None else deadline_sec))

    async def shutdown(self, flush_deadline_sec: float = 5.0) -> None:
        lag = getattr(self, "_loop_lag", None)
        if lag is not None:
            lag.cancel()
        front = getattr(self, "_front", None)
        if front is not None:
            from incubator_predictionio_tpu import native

            native.http_front_stop(front)
            self._front = None
        if self._drain_task is not None:
            self._drain_task.cancel()
            try:
                await self._drain_task
            except asyncio.CancelledError:
                pass
            self._drain_task = None
        # final best-effort flush: every queued event was 201-acked — if
        # the store is reachable, land them before exiting. Bounded by the
        # deadline, but a no-progress beat RETRIES rather than giving up:
        # the breaker may be waiting out its reset window on a store that
        # already recovered (the SIGTERM-during-recovery drain case)
        flush_deadline = self._clock.monotonic() + flush_deadline_sec
        while self._spill and self._clock.monotonic() < flush_deadline:
            try:
                if not await self._run(self._drain_spill_once):
                    await asyncio.sleep(0.1)
            except Exception:  # noqa: BLE001 - poison batch already logged
                continue
        if self._spill:
            if self._wal is not None:
                # NOT dropped: the WAL holds them past the cursor and the
                # next process replays them (the whole point of this PR)
                logger.warning(
                    "shutdown: %d acknowledged spilled event(s) remain in "
                    "the WAL (%s) — they will replay at next startup "
                    "(first ids: %s)", len(self._spill), self.config.wal_dir,
                    [e.event_id for e, _, _, _ in list(self._spill)[:8]])
            else:
                logger.error(
                    "shutdown: DROPPING %d acknowledged spilled event(s) — "
                    "the event store never recovered and no WAL is "
                    "configured (PIO_EVENT_WAL_DIR; first ids: %s)",
                    len(self._spill), [e.event_id for e, _, _, _ in
                                       list(self._spill)[:8]])
        if self._runner is not None:
            await self._runner.cleanup()
        if self._wal is not None:
            self._wal.close()
        self._executor.shutdown(wait=False)
        from incubator_predictionio_tpu.obs import spool as trace_spool

        trace_spool.flush_export()


def serve_forever(config: EventServerConfig = EventServerConfig(),
                  storage: Optional[Storage] = None) -> None:
    import asyncio

    async def main():
        server = EventServer(config, storage)
        await server.start()
        # SIGTERM/SIGINT → graceful drain: 503 new ingest, flush the spill
        # WAL, exit within PIO_DRAIN_DEADLINE (second signal force-exits)
        stop = asyncio.Event()
        install_signal_drain(asyncio.get_running_loop(), stop, "event server")
        await stop.wait()
        await server.drain_and_shutdown()

    asyncio.run(main())
