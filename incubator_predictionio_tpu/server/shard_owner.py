"""Shard-owner identity for multi-host serving (docs/sharding.md).

A query server deployed with ``--shard-id I --shard-count N`` claims the
contiguous item-row range ``ShardSpec.shard_bounds(I)`` of the deployed
catalog and answers ``POST /shard/queries.json`` with per-shard top-k
*partials* instead of full answers. The fleet router discovers the claim
via ``/health.deployment.shardOwner`` and scatter/gathers over the owners
(fleet/topology.py); ``merge_topk`` over the partials reproduces the
single-process answer bitwise (the PR 10 tie discipline).

Fencing follows replication/manager.py: the owner's epoch is persisted
with the atomic-write discipline BEFORE it is ever announced, and a
promoted standby always announces a strictly higher epoch — so a deposed
owner that comes back from a SIGKILL with stale rows is recognizably
stale (the router discards partials carrying an epoch below the highest
it has seen for that range) and can never contribute wrong rows to a
merged answer.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Optional

from incubator_predictionio_tpu.sharding.table import ShardSpec
from incubator_predictionio_tpu.utils.fs import atomic_write_bytes
from incubator_predictionio_tpu.utils.json_util import bind_query

_STATE_FILE = "shard-owner.json"


class ShardOwnerError(RuntimeError):
    """Misconfigured or unusable shard-owner state."""


class ShardOwner:
    """One process's fenced claim on a contiguous item-row range.

    The claim is (shard_id, shard_count, epoch); the concrete ``[lo, hi)``
    row bounds additionally need the deployed catalog size, bound via
    :meth:`bind_rows` at deploy/swap time so a hot-swap to a grown catalog
    re-derives the range from the same ShardSpec arithmetic serving uses.
    """

    def __init__(self, shard_id: int, shard_count: int,
                 state_dir: Optional[str] = None):
        if shard_count < 1:
            raise ShardOwnerError(
                f"shard count must be >= 1, got {shard_count}")
        if not (0 <= shard_id < shard_count):
            raise ShardOwnerError(
                f"shard id {shard_id} outside [0, {shard_count})")
        self.shard_id = int(shard_id)
        self.shard_count = int(shard_count)
        self.state_dir = state_dir
        self.epoch = 1
        self._n_rows: Optional[int] = None
        self._lock = threading.Lock()
        if state_dir:
            self._load_or_init()

    # -- fencing token persistence (manager.py discipline) -----------------
    def _state_path(self) -> str:
        assert self.state_dir is not None
        return os.path.join(self.state_dir, _STATE_FILE)

    def _load_or_init(self) -> None:
        try:
            with open(self._state_path(), encoding="utf-8") as f:
                st = json.load(f)
        except FileNotFoundError:
            self._persist()
            return
        except (ValueError, OSError) as e:
            # NEVER guess an epoch from a corrupt fencing token: a deposed
            # owner re-initialized to epoch 1 could serve stale rows into
            # merged answers. Same refusal as replication/manager.py.
            raise ShardOwnerError(
                f"corrupt shard-owner state at {self._state_path()}: {e}; "
                "refusing to start with a guessed epoch") from e
        if (int(st.get("shardId", -1)) != self.shard_id
                or int(st.get("shardCount", -1)) != self.shard_count):
            raise ShardOwnerError(
                f"shard-owner state at {self._state_path()} claims shard "
                f"{st.get('shardId')}/{st.get('shardCount')} but this "
                f"process was deployed as {self.shard_id}/{self.shard_count}"
                " — point --shard-state-dir at the right directory")
        self.epoch = int(st.get("epoch", 1))

    def _persist(self) -> None:
        if not self.state_dir:
            return
        os.makedirs(self.state_dir, exist_ok=True)
        atomic_write_bytes(
            self._state_path(),
            json.dumps({"shardId": self.shard_id,
                        "shardCount": self.shard_count,
                        "epoch": self.epoch}).encode(),
            durable=True)

    # -- geometry ----------------------------------------------------------
    def bind_rows(self, n_rows: int) -> None:
        """(Re)bind the catalog size the bounds derive from."""
        self._n_rows = int(n_rows)

    def spec(self) -> Optional[ShardSpec]:
        if self._n_rows is None:
            return None
        return ShardSpec("item_owner", self._n_rows, 1, self.shard_count)

    def bounds(self) -> Optional[tuple[int, int]]:
        """Owned ``[lo, hi)`` item rows, or None before a model is bound."""
        spec = self.spec()
        if spec is None:
            return None
        return spec.shard_bounds(self.shard_id)

    # -- fenced promotion --------------------------------------------------
    def promote(self, requested_epoch: Optional[int] = None) -> int:
        """Bump (and durably persist) the epoch, then return it.

        The persist happens BEFORE the caller can announce the new epoch
        anywhere — the fencing invariant. A router-driven failover passes
        the highest epoch it has observed for the range; the result is
        STRICTLY greater than both that and the owner's current epoch, so
        a standby promoted over a deposed owner never ties with it (a tie
        would let the deposed owner's stale partials back into merges)."""
        with self._lock:
            self.epoch = max(self.epoch, int(requested_epoch or 0)) + 1
            self._persist()
            return self.epoch

    def announce(self) -> dict[str, Any]:
        """The ``/health.deployment.shardOwner`` block the router routes on."""
        out: dict[str, Any] = {
            "shardId": self.shard_id,
            "shardCount": self.shard_count,
            "epoch": self.epoch,
        }
        b = self.bounds()
        if b is not None:
            out["rows"] = [b[0], b[1]]
            out["nRows"] = self._n_rows
        return out


def partial_predict(deployed, payload: dict, lo: int, hi: int,
                    num_override: Optional[int] = None) -> dict[str, Any]:
    """Answer one query against item rows ``[lo, hi)`` only.

    Binds + supplements exactly like the full path, then delegates to the
    first algorithm exposing ``predict_shard`` (templates/recommendation.py).
    Returns the wire partial: shard-local top-k candidate ids (GLOBAL row
    indices), their f32 scores, and resolved item names, ordered by the
    block-local argpartition→argsort chain so the router-side
    ``merge_topk`` sees exactly what single-process ``_search_host``
    would have produced for this block."""
    query = bind_query(deployed.query_cls, payload)
    query = deployed.serving.supplement(query)
    for algo, model in zip(deployed.algorithms, deployed.models):
        fn = getattr(algo, "predict_shard", None)
        if callable(fn):
            return fn(model, query, lo, hi, num_override=num_override)
    raise ShardOwnerError(
        "no deployed algorithm supports shard-partial serving "
        "(predict_shard)")
