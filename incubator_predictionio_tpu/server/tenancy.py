"""Multi-tenant serving plane (docs/tenancy.md).

One query-server process hosts N deployed engines — PredictionIO is
multi-app by design (apps/access-keys/channels), and a TPU host only pays
for itself when one fleet safely packs many medium tenants. Three pieces:

- ``TenantSpec``/``load_tenant_specs`` — the declarative tenant table
  (``PIO_TENANTS``: inline JSON or a file path): engine variant, quota,
  pinning, and an optional resident-bytes hint per tenant.
- ``TenantRegistry`` — lazy load/evict of per-tenant ``QueryServer``
  cores under a host/HBM byte budget (``PIO_TENANT_HBM_BUDGET``,
  generalizing the ``PIO_SHARD_HBM_BUDGET`` accounting in
  sharding/table.py into a packing problem): LRU eviction with pins,
  single-flight cold loads in the executor so one tenant's cold start
  never blocks another tenant's hot path, and per-tenant ``TokenBucket``
  quotas at the front door.
- ``MultiTenantQueryServer`` — the HTTP front: routes on the engine id
  (``/engines/{id}/...`` path or the ``X-PIO-Engine`` header), delegates
  the full query lifecycle to the tenant's core (`_serve_payload` — the
  SAME code path single-tenant serving uses, so behavior cannot drift),
  and scopes ``/reload``/``/delta``/``/rollback``/probation per tenant.

Isolation model: every core owns its own ``AdmissionController`` (server
label = ``query_server:<tenant>``), micro-batcher, breakers, last-good
cache, and probation pin — brownout/429/504 decisions never cross tenant
boundaries. The ``tenant`` metric label is bounded by ``PIO_TENANT_MAX``
registered tenants, enforced at registry construction.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import math
import os
from dataclasses import dataclass, field
from typing import Any, Optional

from aiohttp import web

from incubator_predictionio_tpu.obs import slo as _slo
from incubator_predictionio_tpu.obs.http import (
    add_observability_routes,
    telemetry_middleware,
)
from incubator_predictionio_tpu.obs.metrics import (
    DEFAULT_LATENCY_BUCKETS,
    REGISTRY,
)
from incubator_predictionio_tpu.resilience.admission import TokenBucket
from incubator_predictionio_tpu.resilience.clock import SYSTEM_CLOCK, Clock
from incubator_predictionio_tpu.server.lifecycle import (
    DrainState,
    drained_exit_deadline,
    install_signal_drain,
)
from incubator_predictionio_tpu.server.query_server import (
    QueryServer,
    ServerConfig,
    load_deployed_engine,
)
from incubator_predictionio_tpu.sharding.table import parse_bytes

logger = logging.getLogger(__name__)

# -- telemetry (docs/observability.md) --------------------------------------
# tenant label cardinality is bounded: values come only from the registered
# tenant table, whose size PIO_TENANT_MAX caps at registry construction
_T_REQUESTS = REGISTRY.counter(
    "pio_tenant_requests_total",
    "Per-tenant query answers by HTTP status (the tenant cost meter)",
    labels=("service", "tenant", "status"))
_T_LATENCY = REGISTRY.histogram(
    "pio_tenant_request_seconds",
    "Per-tenant end-to-end query latency (front-door to answer)",
    labels=("service", "tenant"), buckets=DEFAULT_LATENCY_BUCKETS)
_T_THROTTLED = REGISTRY.counter(
    "pio_tenant_quota_throttled_total",
    "Queries rejected (429) by the per-tenant quota bucket",
    labels=("tenant",))
_T_EVICTIONS = REGISTRY.counter(
    "pio_tenant_evictions_total",
    "Tenant cores evicted by the LRU packer to fit another under the "
    "byte budget",
    labels=("tenant",))
_T_COLD = REGISTRY.counter(
    "pio_tenant_cold_loads_total",
    "Tenant cold loads (first touch or reload after eviction)",
    labels=("tenant",))
_T_RESIDENT = REGISTRY.gauge(
    "pio_tenant_resident_bytes",
    "Bytes the tenant's resident models account against the budget "
    "(0 when evicted)",
    labels=("tenant",))
_T_QUOTA_FILL = REGISTRY.gauge(
    "pio_tenant_quota_fill",
    "Per-tenant quota bucket fill fraction (negative = paying off debt)",
    labels=("tenant",))
_T_BUDGET = REGISTRY.gauge(
    "pio_tenant_budget_bytes",
    "Configured tenant packing budget (0 = unlimited)")


class TenancyError(RuntimeError):
    """Invalid tenant table (duplicates, over PIO_TENANT_MAX, bad spec)."""


class TenantBudgetError(RuntimeError):
    """The requested tenant cannot be made resident: every loaded tenant
    is pinned or busy and the budget has no room. Transient — answered
    as 503 + Retry-After, never an engine error."""


def tenant_budget() -> Optional[int]:
    """``PIO_TENANT_HBM_BUDGET`` in bytes (suffixes as parse_bytes);
    None/unset/0 disables packing enforcement."""
    raw = os.environ.get("PIO_TENANT_HBM_BUDGET", "").strip()
    if not raw:
        return None
    n = parse_bytes(raw)
    return n if n > 0 else None


def max_tenants() -> int:
    """``PIO_TENANT_MAX`` — the hard cap on registered tenants, which is
    also the `tenant` metric-label cardinality bound."""
    return int(os.environ.get("PIO_TENANT_MAX", "64"))


@dataclass
class TenantSpec:
    """One row of the tenant table."""

    tenant: str
    engine_variant: str
    quota_qps: float = 0.0    # 0 → PIO_TENANT_QUOTA_QPS default (0 = off)
    quota_burst: float = 0.0  # 0 → max(1, 2×qps)
    pinned: bool = False      # never evicted by the packer
    resident_bytes: int = 0   # 0 → measured from the loaded models

    @classmethod
    def from_dict(cls, d: dict) -> "TenantSpec":
        if not isinstance(d, dict):
            raise TenancyError(f"tenant spec must be an object, got {d!r}")
        tenant = d.get("tenant") or d.get("id")
        variant = d.get("engineVariant") or d.get("variant")
        if not tenant or not isinstance(tenant, str):
            raise TenancyError(f"tenant spec needs a string 'tenant': {d!r}")
        if not variant or not isinstance(variant, str):
            raise TenancyError(
                f"tenant {tenant!r} needs an 'engineVariant' path")
        return cls(
            tenant=tenant,
            engine_variant=variant,
            quota_qps=float(d.get("quotaQps", 0.0)),
            quota_burst=float(d.get("quotaBurst", 0.0)),
            pinned=bool(d.get("pinned", False)),
            resident_bytes=int(d.get("residentBytes", 0)),
        )


def load_tenant_specs(source: str) -> list[TenantSpec]:
    """Parse the tenant table from inline JSON (starts with ``[``) or a
    file path — the ``PIO_TENANTS`` / ``--tenants`` value."""
    text = source.strip()
    if not text.startswith("["):
        with open(text, "r", encoding="utf-8") as f:
            text = f.read()
    try:
        rows = json.loads(text)
    except json.JSONDecodeError as e:
        raise TenancyError(f"tenant table is not valid JSON: {e}") from e
    if not isinstance(rows, list) or not rows:
        raise TenancyError("tenant table must be a non-empty JSON array")
    specs = [TenantSpec.from_dict(r) for r in rows]
    seen: set[str] = set()
    for s in specs:
        if s.tenant in seen:
            raise TenancyError(f"duplicate tenant id {s.tenant!r}")
        seen.add(s.tenant)
    return specs


def estimate_resident_bytes(deployed: Any) -> int:
    """Bytes the deployed engine's models pin on the host/device — the
    packing currency. Walks model attributes for array-like ``nbytes``
    (depth-limited: model objects hold flat param dicts/lists of
    ndarrays, not deep graphs). The spec's ``residentBytes`` hint
    overrides this when set (tests and exotic models)."""

    def walk(obj: Any, depth: int) -> int:
        nb = getattr(obj, "nbytes", None)
        if isinstance(nb, (int, float)) and not isinstance(obj, (bool,)):
            return int(nb)
        if depth <= 0:
            return 0
        if isinstance(obj, dict):
            return sum(walk(v, depth - 1) for v in obj.values())
        if isinstance(obj, (list, tuple)):
            return sum(walk(v, depth - 1) for v in obj)
        d = getattr(obj, "__dict__", None)
        if isinstance(d, dict):
            return sum(walk(v, depth - 1) for v in d.values())
        return 0

    return sum(walk(m, 3) for m in getattr(deployed, "models", []))


@dataclass
class TenantState:
    spec: TenantSpec
    bucket: Optional[TokenBucket]
    core: Optional[QueryServer] = None
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)
    last_used: float = 0.0
    cold_loads: int = 0
    evictions: int = 0
    resident_bytes: int = 0
    requests: int = 0
    throttled: int = 0


class TenantRegistry:
    """Lazy per-tenant serving cores under a byte budget.

    The packer: before a cold load, evict least-recently-used unpinned
    residents until the expected bytes fit; after the load, reconcile
    with the MEASURED bytes (first touch of a tenant without a hint can
    transiently overshoot — the reconcile pass restores the invariant).
    Cold loads run in the executor under a per-tenant single-flight
    lock: concurrent queries for the SAME cold tenant wait on one load;
    other tenants' hot paths never wait at all.
    """

    def __init__(
        self,
        specs: list[TenantSpec],
        config: ServerConfig,
        storage=None,
        ctx=None,
        clock: Clock = SYSTEM_CLOCK,
        budget_bytes: Optional[int] = None,
        limit: Optional[int] = None,
    ):
        cap = limit if limit is not None else max_tenants()
        if len(specs) > cap:
            raise TenancyError(
                f"{len(specs)} tenants exceed PIO_TENANT_MAX={cap} — the "
                "tenant label cardinality bound")
        self.config = config
        self.storage = storage
        self.ctx = ctx
        self._clock = clock
        self.budget_bytes = (tenant_budget()
                             if budget_bytes is None else budget_bytes)
        _T_BUDGET.set(self.budget_bytes or 0)
        default_qps = float(os.environ.get("PIO_TENANT_QUOTA_QPS", "0"))
        default_burst = float(os.environ.get("PIO_TENANT_QUOTA_BURST", "0"))
        self._states: dict[str, TenantState] = {}
        for spec in specs:
            qps = spec.quota_qps if spec.quota_qps > 0 else default_qps
            burst = spec.quota_burst if spec.quota_burst > 0 else default_burst
            bucket = None
            if qps > 0:
                bucket = TokenBucket(
                    qps, burst if burst > 0 else max(1.0, 2.0 * qps), clock)
            self._states[spec.tenant] = TenantState(spec=spec, bucket=bucket)

    # -- lookups ----------------------------------------------------------
    @property
    def tenants(self) -> list[str]:
        return list(self._states)

    def state(self, tenant: str) -> Optional[TenantState]:
        return self._states.get(tenant)

    def resident_total(self) -> int:
        return sum(s.resident_bytes for s in self._states.values()
                   if s.core is not None)

    # -- quota door -------------------------------------------------------
    def admit(self, tenant: str) -> Optional[int]:
        """None when within quota; otherwise Retry-After seconds for the
        429. Tenant-scoped by construction — one bucket per tenant."""
        st = self._states[tenant]
        if st.bucket is None or st.bucket.try_acquire(1.0):
            return None
        st.throttled += 1
        _T_THROTTLED.labels(tenant=tenant).inc()
        return max(1, math.ceil(st.bucket.retry_after(1.0)))

    # -- packing ----------------------------------------------------------
    async def core_for(self, tenant: str) -> QueryServer:
        """The tenant's live core, cold-loading (and evicting) as needed.
        Raises KeyError for unknown tenants, TenantBudgetError when the
        packer cannot make room."""
        st = self._states[tenant]
        st.last_used = self._clock.monotonic()
        core = st.core
        if core is not None:
            return core
        async with st.lock:  # single-flight: one cold load per tenant
            if st.core is not None:
                return st.core
            expected = st.spec.resident_bytes or st.resident_bytes
            await self._make_room(tenant, expected)
            cfg = dataclasses.replace(
                self.config, engine_variant=st.spec.engine_variant)
            loop = asyncio.get_running_loop()
            # the expensive part (deserialize + per-tenant warmup) runs in
            # the executor — the loop keeps serving OTHER tenants' queries
            deployed = await loop.run_in_executor(
                None, load_deployed_engine, cfg, self.storage, self.ctx)
            measured = st.spec.resident_bytes or estimate_resident_bytes(
                deployed)
            st.core = QueryServer(
                cfg, storage=self.storage, ctx=self.ctx, deployed=deployed,
                clock=self._clock, name=f"query_server:{tenant}")
            st.resident_bytes = measured
            st.cold_loads += 1
            st.last_used = self._clock.monotonic()
            _T_COLD.labels(tenant=tenant).inc()
            _T_RESIDENT.labels(tenant=tenant).set(measured)
            logger.info("tenant %s: cold load #%d (%d bytes resident)",
                        tenant, st.cold_loads, measured)
        # first touch without a hint could not pre-budget exactly —
        # reconcile against the measured bytes (never evicts `tenant`)
        await self._make_room(tenant, 0)
        return st.core

    async def _make_room(self, protect: str, incoming: int) -> None:
        if self.budget_bytes is None:
            return
        while self.resident_total() + incoming > self.budget_bytes:
            victim = self._pick_victim(protect)
            if victim is None:
                if incoming == 0 or self.resident_total() == 0:
                    # Nothing left to evict. incoming == 0 is the post-load
                    # reconcile: the overshoot is the protected tenant's own
                    # (or pinned) bytes, so accept it — the next cold load
                    # evicts it like any LRU resident. resident_total() == 0
                    # is the pre-load case where the single incoming tenant
                    # is bigger than the whole budget — admit it alone
                    # rather than deadlock (documented packer escape hatch).
                    return
                raise TenantBudgetError(
                    f"no room for tenant {protect!r}: "
                    f"{self.resident_total() + incoming} bytes needed, "
                    f"budget {self.budget_bytes}, all residents pinned")
            await self._evict(victim)

    def _pick_victim(self, protect: str) -> Optional[TenantState]:
        """LRU among unpinned residents; idle cores (empty queue, nothing
        in flight) are preferred so an eviction never fails queued work."""
        candidates = [
            s for s in self._states.values()
            if s.core is not None and not s.spec.pinned
            and s.spec.tenant != protect
        ]
        if not candidates:
            return None

        def busy(s: TenantState) -> bool:
            b = s.core.batcher
            return b.queue.qsize() > 0 or bool(b._inflight)

        candidates.sort(key=lambda s: (busy(s), s.last_used))
        return candidates[0]

    async def _evict(self, st: TenantState) -> None:
        tenant = st.spec.tenant
        core, st.core = st.core, None
        st.evictions += 1
        # st.resident_bytes is kept as the last-known size — the packer
        # pre-budgets a re-load with it so a round trip can't overshoot
        _T_EVICTIONS.labels(tenant=tenant).inc()
        _T_RESIDENT.labels(tenant=tenant).set(0)
        logger.info("tenant %s: evicted (LRU, budget pressure)", tenant)
        # stop the batcher (fails anything still queued fast — the packer
        # prefers idle victims, so normally there is nothing) and drop the
        # core's scrape collector so /metrics reflects the eviction
        await core.batcher.stop()
        REGISTRY.remove_collector(core.name)

    async def evict_all(self) -> None:
        for st in self._states.values():
            if st.core is not None:
                await self._evict(st)

    # -- surfaces ---------------------------------------------------------
    def publish(self) -> None:
        """Exposition-time gauges (the front's collector calls this)."""
        _T_BUDGET.set(self.budget_bytes or 0)
        for tenant, st in self._states.items():
            if st.bucket is not None:
                _T_QUOTA_FILL.labels(tenant=tenant).set(
                    round(st.bucket.fill(), 4))
            _T_RESIDENT.labels(tenant=tenant).set(
                st.resident_bytes if st.core is not None else 0)

    def snapshot(self) -> dict:
        now = self._clock.monotonic()
        tenants = {}
        for tenant, st in self._states.items():
            row: dict[str, Any] = {
                "resident": st.core is not None,
                "pinned": st.spec.pinned,
                "residentBytes": (st.resident_bytes
                                  if st.core is not None else 0),
                "coldLoads": st.cold_loads,
                "evictions": st.evictions,
                "requests": st.requests,
                "throttled": st.throttled,
                "lastUsedAgeSec": (round(now - st.last_used, 3)
                                   if st.last_used else None),
                "quota": None,
            }
            if st.bucket is not None:
                row["quota"] = {
                    "qps": st.bucket.rate,
                    "burst": st.bucket.burst,
                    "fill": round(st.bucket.fill(), 4),
                }
            if st.core is not None:
                row["instanceId"] = st.core.deployed.instance.id
                row["engineVersion"] = (
                    st.core.deployed.instance.engine_version)
                row["probationActive"] = st.core._probation_active()
                row["admission"] = st.core._admission.snapshot(
                    st.core.batcher.queue.qsize())
            tenants[tenant] = row
        return {
            "budgetBytes": self.budget_bytes or 0,
            "residentBytes": self.resident_total(),
            "tenantCount": len(self._states),
            "residentCount": sum(1 for s in self._states.values()
                                 if s.core is not None),
            "tenants": tenants,
        }


class MultiTenantQueryServer:
    """The multi-tenant HTTP front (`pio-tpu deploy --tenants ...`).

    Routing: ``POST /engines/{id}/queries.json`` (and the admin verbs
    under the same prefix), or bare ``/queries.json`` with the
    ``X-PIO-Engine`` header; with exactly one registered tenant the bare
    path defaults to it, so a one-tenant table behaves like the classic
    single-engine server."""

    def __init__(self, registry: TenantRegistry, config: ServerConfig,
                 clock: Clock = SYSTEM_CLOCK):
        self.registry = registry
        self.config = config
        self._clock = clock
        # process-wide planes are armed ONCE here — per-tenant cores skip
        # them (query_server.py gates on the front's collector name)
        from incubator_predictionio_tpu.obs import spool as trace_spool
        from incubator_predictionio_tpu.obs.plane import (
            configure_perf_plane_from_env,
        )

        trace_spool.configure_export_from_env("query_server")
        configure_perf_plane_from_env("query_server")
        self._drain_state = DrainState("query_server")
        self._start_time = clock.monotonic()
        self._runner: Optional[web.AppRunner] = None
        self._stop_event = asyncio.Event()
        REGISTRY.add_collector("query_server", self.registry.publish)

    # -- routing ----------------------------------------------------------
    def _resolve_tenant(self, request: web.Request) -> Optional[str]:
        tenant = (request.match_info.get("tenant")
                  or request.headers.get("X-PIO-Engine"))
        if tenant is None and len(self.registry.tenants) == 1:
            tenant = self.registry.tenants[0]
        return tenant

    @staticmethod
    def _unknown(tenant: Optional[str]) -> web.Response:
        if tenant is None:
            return web.json_response(
                {"message": "multi-tenant server: name the engine via "
                            "/engines/{id}/... or the X-PIO-Engine header"},
                status=400)
        return web.json_response(
            {"message": f"unknown engine {tenant!r} (docs/tenancy.md)"},
            status=404)

    def make_app(self) -> web.Application:
        app = web.Application(
            middlewares=[telemetry_middleware("query_server")])
        app.router.add_get("/", self.handle_status)
        app.router.add_get("/health", self.handle_health)
        app.router.add_get("/tenants.json", self.handle_tenants)
        add_observability_routes(app)
        app.router.add_post("/queries.json", self.handle_query)
        app.router.add_post(
            "/engines/{tenant}/queries.json", self.handle_query)
        app.router.add_post("/engines/{tenant}/reload", self.handle_admin)
        app.router.add_post("/engines/{tenant}/delta", self.handle_admin)
        app.router.add_post("/engines/{tenant}/rollback", self.handle_admin)
        app.router.add_post("/reload", self.handle_admin)
        app.router.add_post("/delta", self.handle_admin)
        app.router.add_post("/rollback", self.handle_admin)
        app.router.add_post("/stop", self.handle_stop)
        return app

    # -- handlers ---------------------------------------------------------
    async def handle_query(self, request: web.Request) -> web.Response:
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        tenant = self._resolve_tenant(request)
        st = self.registry.state(tenant) if tenant else None
        if st is None:
            return self._unknown(tenant)
        t0 = self._clock.monotonic()
        retry_after = self.registry.admit(tenant)
        if retry_after is not None:
            _T_REQUESTS.labels(service="query_server", tenant=tenant,
                               status="429").inc()
            return web.json_response(
                {"message": f"tenant {tenant!r} over quota "
                            "(docs/tenancy.md)"},
                status=429,
                headers={"Retry-After": str(retry_after),
                         "X-PIO-Tenant": tenant})
        try:
            core = await self.registry.core_for(tenant)
        except TenantBudgetError as e:
            _T_REQUESTS.labels(service="query_server", tenant=tenant,
                               status="503").inc()
            return web.json_response(
                {"message": str(e)}, status=503,
                headers={"Retry-After": "1", "X-PIO-Tenant": tenant})
        except RuntimeError as e:
            _T_REQUESTS.labels(service="query_server", tenant=tenant,
                               status="500").inc()
            return web.json_response({"message": str(e)}, status=500)
        status, result, headers = await core._serve_payload(
            await request.read())
        headers = dict(headers or {})
        headers["X-PIO-Tenant"] = tenant
        st.requests += 1
        _T_REQUESTS.labels(service="query_server", tenant=tenant,
                           status=str(status)).inc()
        _T_LATENCY.labels(service="query_server", tenant=tenant).observe(
            self._clock.monotonic() - t0)
        return web.json_response(result, status=status, headers=headers)

    async def handle_admin(self, request: web.Request) -> web.Response:
        """Tenant-scoped /reload, /delta, /rollback: resolve the tenant,
        make its core resident, delegate — probation pins, smoke gates,
        and delta chains live inside the core, so one tenant's failed
        reload can never touch another tenant's pinned instance."""
        if self._drain_state.draining:
            return self._drain_state.reject_response()
        tenant = self._resolve_tenant(request)
        if tenant is None or self.registry.state(tenant) is None:
            return self._unknown(tenant)
        verb = request.path.rsplit("/", 1)[-1]
        try:
            core = await self.registry.core_for(tenant)
        except TenantBudgetError as e:
            return web.json_response(
                {"message": str(e)}, status=503,
                headers={"Retry-After": "1"})
        handler = {"reload": core.handle_reload,
                   "delta": core.handle_delta,
                   "rollback": core.handle_rollback}[verb]
        return await handler(request)

    async def handle_tenants(self, request: web.Request) -> web.Response:
        return web.json_response(self.registry.snapshot())

    async def handle_status(self, request: web.Request) -> web.Response:
        return web.json_response({
            "mode": "multi-tenant",
            "tenants": self.registry.tenants,
            "uptimeSec": round(
                self._clock.monotonic() - self._start_time, 3),
        })

    async def handle_health(self, request: web.Request) -> web.Response:
        """Aggregate liveness + the per-tenant packing state. The
        ``deployment.engines``/``deployment.resident`` sets are what the
        fleet balancer folds for (tenant, load) routing."""
        snap = self.registry.snapshot()
        degraded = False
        for row in snap["tenants"].values():
            adm = row.get("admission") or {}
            if adm.get("brownoutActive"):
                degraded = True
        resident = [t for t, row in snap["tenants"].items()
                    if row["resident"]]
        return web.json_response({
            "status": self._drain_state.health_status(degraded),
            "draining": self._drain_state.draining,
            "slo": _slo.health_block(),
            "tenancy": snap,
            "deployment": {
                "multiTenant": True,
                "engines": self.registry.tenants,
                "resident": resident,
                # single-instance fields stay None-shaped so existing
                # fleet folds keep working against multi-tenant replicas
                "instanceId": None,
                "engineVersion": None,
                "streaming": None,
                "shardOwner": None,
            },
        })

    async def handle_stop(self, request: web.Request) -> web.Response:
        import hmac

        key = self.config.server_access_key
        if key and not hmac.compare_digest(
                request.query.get("accessKey", "").encode(), key.encode()):
            return web.json_response({"message": "Unauthorized"}, status=401)
        self._stop_event.set()
        return web.json_response({"message": "Shutting down"})

    # -- lifecycle --------------------------------------------------------
    async def start(self) -> None:
        from incubator_predictionio_tpu.obs import procstats
        from incubator_predictionio_tpu.server.event_server import (
            _ssl_context,
        )

        self._loop_lag = procstats.start_loop_lag("query_server")
        self._runner = web.AppRunner(self.make_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.config.ip, self.config.port,
                           ssl_context=_ssl_context(self.config))
        await site.start()
        logger.info("multi-tenant engine server listening on %s:%d "
                    "(%d tenants, budget %s bytes)",
                    self.config.ip, self.config.port,
                    len(self.registry.tenants),
                    self.registry.budget_bytes or "∞")

    async def wait_stopped(self) -> None:
        await self._stop_event.wait()
        await self.drain_and_shutdown()

    async def drain_and_shutdown(
            self, deadline_sec: Optional[float] = None) -> None:
        self._drain_state.begin()
        deadline = (drained_exit_deadline()
                    if deadline_sec is None else deadline_sec)
        # give every resident core its drain window concurrently
        cores = [st.core for st in self.registry._states.values()
                 if st.core is not None]
        if cores:
            from incubator_predictionio_tpu.server.lifecycle import wait_for

            await wait_for(
                lambda: all(c.batcher.queue.qsize() == 0
                            and not c.batcher._inflight for c in cores),
                deadline)
        await self.shutdown()

    async def shutdown(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        lag = getattr(self, "_loop_lag", None)
        if lag is not None:
            lag.cancel()
        await self.registry.evict_all()
        from incubator_predictionio_tpu.obs import spool as trace_spool

        trace_spool.flush_export()


def serve_forever_tenants(config: ServerConfig, specs: list[TenantSpec],
                          storage=None) -> None:
    """Blocking entry for the CLI `deploy --tenants` path."""

    async def main():
        registry = TenantRegistry(specs, config, storage=storage)
        server = MultiTenantQueryServer(registry, config)
        await server.start()
        install_signal_drain(asyncio.get_running_loop(), server._stop_event,
                             "multi-tenant engine server")
        await server.wait_stopped()

    asyncio.run(main())
