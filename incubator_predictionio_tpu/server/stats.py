"""Rolling event-server statistics (reference data/api/Stats.scala:51-82,
StatsActor.scala:36-77): per-app counters bucketed by hour, keeping the
current and previous hour, served at /stats.json when enabled."""

from __future__ import annotations

import datetime as _dt
import threading
from collections import Counter
from typing import Callable, Optional


def _hour_floor(t: _dt.datetime) -> _dt.datetime:
    return t.replace(minute=0, second=0, microsecond=0)


def _utcnow() -> _dt.datetime:
    return _dt.datetime.now(_dt.timezone.utc)


class Stats:
    def __init__(self,
                 clock: Optional[Callable[[], _dt.datetime]] = None) -> None:
        # injectable wall-clock (returns an aware datetime) so the roll
        # logic is testable without wall time
        self._clock = clock or _utcnow
        self._lock = threading.Lock()
        self._hour: Optional[_dt.datetime] = None
        self._prev: dict[int, dict[str, Counter]] = {}
        self._cur: dict[int, dict[str, Counter]] = {}

    def _roll(self, now: _dt.datetime) -> None:
        hour = _hour_floor(now)
        if self._hour is None:
            self._hour = hour
        elif hour > self._hour:
            # "previousHour" must mean exactly that: after a gap of two or
            # more hours the stale _cur is hours old, not the previous hour —
            # promoting it would report ancient counts as fresh
            if hour - self._hour <= _dt.timedelta(hours=1):
                self._prev = self._cur
            else:
                self._prev = {}
            self._cur = {}
            self._hour = hour

    def update(
        self,
        app_id: int,
        status: int,
        event_name: str,
        entity_type: str,
        now: Optional[_dt.datetime] = None,
    ) -> None:
        now = now or self._clock()
        with self._lock:
            self._roll(now)
            app = self._cur.setdefault(
                app_id,
                {"status": Counter(), "event": Counter(), "entityType": Counter()},
            )
            app["status"][str(status)] += 1
            app["event"][event_name] += 1
            app["entityType"][entity_type] += 1

    def get(self, app_id: int) -> dict:
        with self._lock:
            self._roll(self._clock())
            out = {}
            for label, data in (("previousHour", self._prev), ("currentHour", self._cur)):
                app = data.get(app_id, {})
                out[label] = {
                    "status": dict(app.get("status", {})),
                    "event": dict(app.get("event", {})),
                    "entityType": dict(app.get("entityType", {})),
                }
            out["startTime"] = self._hour.isoformat() if self._hour else None
            return out

    def current_totals(self) -> dict[int, dict[str, int]]:
        """Current-hour per-app status counts — the /metrics fold (the full
        per-event/entity breakdown stays on /stats.json; metrics labels must
        stay low-cardinality)."""
        with self._lock:
            self._roll(self._clock())
            return {app_id: dict(data["status"])
                    for app_id, data in self._cur.items()}
