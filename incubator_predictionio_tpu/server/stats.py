"""Rolling event-server statistics (reference data/api/Stats.scala:51-82,
StatsActor.scala:36-77): per-app counters bucketed by hour, keeping the
current and previous hour, served at /stats.json when enabled."""

from __future__ import annotations

import datetime as _dt
import threading
from collections import Counter
from typing import Optional


def _hour_floor(t: _dt.datetime) -> _dt.datetime:
    return t.replace(minute=0, second=0, microsecond=0)


class Stats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hour: Optional[_dt.datetime] = None
        self._prev: dict[int, dict[str, Counter]] = {}
        self._cur: dict[int, dict[str, Counter]] = {}

    def _roll(self, now: _dt.datetime) -> None:
        hour = _hour_floor(now)
        if self._hour is None:
            self._hour = hour
        elif hour > self._hour:
            self._prev = self._cur
            self._cur = {}
            self._hour = hour

    def update(
        self,
        app_id: int,
        status: int,
        event_name: str,
        entity_type: str,
        now: Optional[_dt.datetime] = None,
    ) -> None:
        now = now or _dt.datetime.now(_dt.timezone.utc)
        with self._lock:
            self._roll(now)
            app = self._cur.setdefault(
                app_id,
                {"status": Counter(), "event": Counter(), "entityType": Counter()},
            )
            app["status"][str(status)] += 1
            app["event"][event_name] += 1
            app["entityType"][entity_type] += 1

    def get(self, app_id: int) -> dict:
        with self._lock:
            self._roll(_dt.datetime.now(_dt.timezone.utc))
            out = {}
            for label, data in (("previousHour", self._prev), ("currentHour", self._cur)):
                app = data.get(app_id, {})
                out[label] = {
                    "status": dict(app.get("status", {})),
                    "event": dict(app.get("event", {})),
                    "entityType": dict(app.get("entityType", {})),
                }
            out["startTime"] = self._hour.isoformat() if self._hour else None
            return out
