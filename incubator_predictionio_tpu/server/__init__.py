"""HTTP servers: event ingestion (Event Server) and query serving (Engine
Server). A query server deployed with ``--shard-id/--shard-count`` also
acts as a multi-host shard owner (:mod:`.shard_owner`): it announces its
``[lo, hi)`` item-row claim + fencing epoch on ``/health`` and serves
``/shard/queries.json`` partials for the fleet router's scatter/gather
(docs/sharding.md "Multi-host shard owners")."""
