"""HTTP servers: event ingestion (Event Server) and query serving (Engine Server)."""
