"""Sequential recommender template — next-item prediction over session events.

New capability relative to the reference (whose only sequence model is
``e2.engine.MarkovChain``): a Transformer4Rec-style causal transformer
(models/transformer.py) trained on per-user item sequences, with optional
ring-attention sequence parallelism on meshes with a ``seq`` axis. The DASE
wiring mirrors the other templates: events in, engine params from variant
JSON, /queries.json out.

Query: ``{"recent_items": [...], "num": N}`` scores the next item after an
explicit session, or ``{"user": U, "num": N}`` reads the user's recent
view/buy events live from the event store (LEventStore, like the ecommerce
template's serving-time reads).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Sequence

import numpy as np

from incubator_predictionio_tpu.core import (
    Engine,
    EngineFactory,
    EngineParamsGenerator,
    Evaluation,
    FirstServing,
    IdentityPreparator,
    MetricEvaluator,
    OptionAverageMetric,
    PAlgorithm,
    Params,
    PDataSource,
    SanityCheck,
)
from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.data.store import LEventStore, PEventStore
from incubator_predictionio_tpu.models.transformer import (
    TransformerConfig,
    TransformerModel,
    TransformerRecommender,
)
from incubator_predictionio_tpu.parallel.mesh import MeshContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    user: Optional[str] = None
    recent_items: Optional[tuple[str, ...]] = None
    num: int = 10


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...] = ()


@dataclasses.dataclass(frozen=True)
class ActualResult:
    """Held-out next item of one session (eval ground truth)."""

    next_item: str


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "sequential"
    max_len: int = 32
    events: tuple[str, ...] = ("view", "buy")
    eval_k: Optional[int] = None  # k-fold next-item eval when set
    eval_num: int = 10            # top-N asked per eval query


@dataclasses.dataclass
class TrainingData(SanityCheck):
    sequences: np.ndarray  # [n, max_len+1] int32 tokens, 0-padded left
    item_map: BiMap        # item id → token (1-based; 0 = padding)
    # multi-process sharded read: sequences are THIS process's user shard
    # only (sessions never cross shards; item_map/tokens are global)
    rows_are_local: bool = False
    n_rows_global: Optional[int] = None

    def sanity_check(self) -> None:
        total = (self.n_rows_global if self.n_rows_global is not None
                 else len(self.sequences))
        if total == 0:
            raise ValueError("no sessions found")


def encode_session(items: Sequence[str], item_map: BiMap, width: int) -> np.ndarray:
    """Left-pad a session's tokens to ``width`` (newest item last)."""
    tokens = [item_map[i] for i in items if i in item_map][-width:]
    out = np.zeros(width, np.int32)
    if tokens:
        out[-len(tokens):] = tokens
    return out


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)
        self._store = PEventStore()

    def _collect_sessions(self, ctx: MeshContext) -> tuple[dict[str, list[str]], bool]:
        """user → ordered item list, for this process's user shard
        (sessions are per-user; users are entity-sharded, so a session
        never splits across processes)."""
        p = self.params
        procs, pid = ctx.process_count, ctx.process_index
        sharded = procs > 1
        sessions: dict[str, list[str]] = {}
        if sharded:
            events = self._store.find_sharded(
                p.app_name, procs, entity_type="user",
                event_names=tuple(p.events))[pid]
        else:
            events = self._store.find(
                p.app_name, entity_type="user", event_names=tuple(p.events),
                target_entity_type="item",
            )
        for e in events:  # find() is event-time ordered
            if e.target_entity_type != "item":
                continue
            sessions.setdefault(e.entity_id, []).append(e.target_entity_id)
        return sessions, sharded

    def _build_fold(self, ctx: MeshContext, sessions_list: list[list[str]],
                    sharded: bool) -> TrainingData:
        """Token space + encoded rows from the given sessions (global vocab
        union when sharded; token 0 reserved for padding)."""
        base = BiMap.string_int(
            [i for items in sessions_list for i in items])
        n_rows_global = None
        if sharded:
            from incubator_predictionio_tpu.data.sharded import union_vocab

            # global token space: first-seen union over shards in process
            # order (one vocab-sized allgather)
            vocab, _ = union_vocab(ctx, list(base))
            base = BiMap({v: i for i, v in enumerate(vocab.tolist())})
        item_map = BiMap({k: v + 1 for k, v in base.items()})
        width = self.params.max_len + 1
        rows = [
            encode_session(items, item_map, width)
            for items in sessions_list
            if len(items) >= 2
        ]
        if sharded:
            from incubator_predictionio_tpu.data.sharded import global_row_count

            n_rows_global = global_row_count(ctx, len(rows))
            logger.info("sharded read: %d of %d rows (shard %d/%d)",
                        len(rows), n_rows_global, ctx.process_index,
                        ctx.process_count)
        return TrainingData(
            sequences=np.stack(rows) if rows else np.zeros((0, width), np.int32),
            item_map=item_map,
            rows_are_local=sharded,
            n_rows_global=n_rows_global,
        )

    def read_training(self, ctx: MeshContext) -> TrainingData:
        sessions, sharded = self._collect_sessions(ctx)
        return self._build_fold(ctx, list(sessions.values()), sharded)

    def read_eval(self, ctx: MeshContext):
        """k-fold next-item evaluation: sessions split by a stable user
        hash; a held-out session becomes (Query(recentItems=prefix),
        ActualResult(last item)). Fold vocabularies come from the fold's
        TRAIN sessions only, so unseen items stay genuinely unknown (the
        recommendation template's per-fold BiMap discipline)."""
        import zlib

        k = self.params.eval_k
        if not k:
            return []
        p = self.params
        sessions, sharded = self._collect_sessions(ctx)
        # fold assignment computed ONCE per user (recommendation.py's
        # fold_of discipline), not re-hashed per fold
        fold_of = {
            user: zlib.crc32(f"{p.app_name}|{user}".encode()) % k
            for user in sessions
        }
        folds = []
        for fold in range(k):
            train_sessions, held = [], []
            for user, items in sessions.items():
                if fold_of[user] == fold:
                    held.append(items)
                else:
                    train_sessions.append(items)
            td = self._build_fold(ctx, train_sessions, sharded)
            local_qa = [
                (Query(recent_items=tuple(items[:-1]), num=p.eval_num),
                 ActualResult(items[-1]))
                for items in held if len(items) >= 3
            ]
            if sharded:
                # every process evaluates the same (small) global query set
                parts = ctx.allgather_obj([
                    (list(q.recent_items), q.num, a.next_item)
                    for q, a in local_qa
                ])
                qa = [
                    (Query(recent_items=tuple(r), num=num), ActualResult(nx))
                    for part in parts for r, num, nx in part
                ]
            else:
                qa = local_qa
            folds.append((td, {"fold": fold}, qa))
        return folds


@dataclasses.dataclass(frozen=True)
class TransformerAlgorithmParams(Params):
    app_name: str = "sequential"
    max_len: int = 32
    d_model: int = 64
    n_heads: int = 2
    n_layers: int = 2
    learning_rate: float = 1e-3
    batch_size: int = 256
    epochs: int = 10
    seed: int = 0
    attention: str = "auto"  # "auto" | "local" | "ring"
    # mixture-of-experts FFN: 0 = dense; >0 switches to top-1 routed experts
    # sharded over the mesh's "expert" axis when present
    num_experts: int = 0
    # pipeline parallelism: stage count over the mesh's "pipe" axis (0 = off)
    pipeline_stages: int = 0
    pipeline_microbatches: int = 0
    # recompute activations in backward (jax.checkpoint): fits longer
    # sequences in HBM for ~1 extra forward of FLOPs
    remat: bool = False
    # Megatron-style tensor parallelism over the mesh's "model" axis
    tensor_parallel: bool = False
    recent_events: tuple[str, ...] = ("view", "buy")
    checkpoint_dir: Optional[str] = None   # mid-training resume (utils/checkpoint.py)
    checkpoint_every: int = 0


class TransformerAlgorithm(PAlgorithm):
    params_class = TransformerAlgorithmParams
    serving_thread_safe = True  # jit dispatch + read-only served arrays
    query_cls = Query

    def __init__(self, params: TransformerAlgorithmParams):
        super().__init__(params)
        self._levents = LEventStore()

    def train(self, ctx: MeshContext, pd: TrainingData) -> TransformerModel:
        p = self.params
        cfg = TransformerConfig(
            vocab_size=len(pd.item_map) + 1,
            max_len=p.max_len,
            d_model=p.d_model,
            n_heads=p.n_heads,
            n_layers=p.n_layers,
            learning_rate=p.learning_rate,
            batch_size=p.batch_size,
            epochs=p.epochs,
            seed=p.seed,
            attention=p.attention,
            n_experts=p.num_experts,
            pipeline_stages=p.pipeline_stages,
            pipeline_microbatches=p.pipeline_microbatches,
            remat=p.remat,
            tensor_parallel=p.tensor_parallel,
            checkpoint_dir=p.checkpoint_dir,
            checkpoint_every=p.checkpoint_every,
        )
        return TransformerRecommender(cfg).fit(
            ctx, pd.sequences, pd.item_map,
            rows_are_local=pd.rows_are_local)

    def _history(self, query: Query, model: TransformerModel) -> list[str]:
        if query.recent_items is not None:
            return list(query.recent_items)
        if query.user is None:
            return []
        try:
            events = list(self._levents.find_by_entity(
                self.params.app_name, "user", query.user,
                event_names=tuple(self.params.recent_events),
                target_entity_type="item",
                limit=model.config.max_len, latest=True,
            ))
        except ValueError:
            return []
        return [e.target_entity_id for e in reversed(events) if e.target_entity_id]

    def predict(self, model: TransformerModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(
        self, model: TransformerModel, queries: Sequence[tuple[int, Query]]
    ) -> list[tuple[int, PredictedResult]]:
        if not queries:
            return []
        histories = [self._history(q, model) for _, q in queries]
        rows = np.stack([
            encode_session(h, model.item_map, model.config.max_len)
            for h in histories
        ])
        scores = TransformerRecommender.next_item_scores(model, rows)
        inv = model.item_map.inverse()
        out = []
        for (qi, q), h, row_scores in zip(queries, histories, scores):
            if not any(i in model.item_map for i in h):
                out.append((qi, PredictedResult()))  # cold session
                continue
            s = row_scores.copy()
            s[0] = -np.inf  # padding token
            for i in h:     # exclude history items
                tok = model.item_map.get(i)
                if tok is not None:
                    s[tok] = -np.inf
            num = min(q.num, len(s) - 1)
            top = np.argpartition(-s, num - 1)[:num]
            top = top[np.argsort(-s[top])]
            out.append((qi, PredictedResult(tuple(
                ItemScore(inv[int(t)], float(s[t]))
                for t in top if np.isfinite(s[t])
            ))))
        return out


class SequentialEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            DataSource,
            IdentityPreparator,
            {"transformer": TransformerAlgorithm, "": TransformerAlgorithm},
            FirstServing,
        )


# -- evaluation -------------------------------------------------------------

class HitRateAtK(OptionAverageMetric):
    """Fraction of held-out sessions whose true next item appears in the
    top-k (the standard next-item metric; the serving path's unseen-only
    policy applies, so repeat-item sessions count as misses)."""

    def __init__(self, k: int = 10):
        self.k = k

    @property
    def header(self) -> str:
        return f"HitRate@K (k={self.k})"

    def calculate_qpa(self, q: Query, p: PredictedResult, a: ActualResult):
        if not p.item_scores:
            return 0.0  # cold/unknown-vocab session: a miss, not a skip
        return 1.0 if a.next_item in {
            s.item for s in p.item_scores[: self.k]} else 0.0


class SequentialEvaluation(Evaluation, EngineParamsGenerator):
    """HitRate@10 over a small schedule grid — makes ``pio-tpu eval`` work
    on the long-context flagship like it does on the recommendation
    template."""

    def __init__(self, app_name: str = "sequential", eval_k: int = 3):
        from incubator_predictionio_tpu.core import EngineParams

        self.engine = SequentialEngine().apply()
        self.evaluator = MetricEvaluator(metric=HitRateAtK(k=10))
        self.engine_params_list = [
            EngineParams.create(
                data_source=DataSourceParams(app_name=app_name, eval_k=eval_k),
                algorithms=[("transformer", TransformerAlgorithmParams(
                    app_name=app_name, d_model=32, n_layers=1,
                    epochs=epochs, learning_rate=lr, batch_size=64))],
            )
            for epochs in (10, 30)
            for lr in (1e-3, 5e-3)
        ]
