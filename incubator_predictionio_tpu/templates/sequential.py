"""Sequential recommender template — next-item prediction over session events.

New capability relative to the reference (whose only sequence model is
``e2.engine.MarkovChain``): a Transformer4Rec-style causal transformer
(models/transformer.py) trained on per-user item sequences, with optional
ring-attention sequence parallelism on meshes with a ``seq`` axis. The DASE
wiring mirrors the other templates: events in, engine params from variant
JSON, /queries.json out.

Query: ``{"recent_items": [...], "num": N}`` scores the next item after an
explicit session, or ``{"user": U, "num": N}`` reads the user's recent
view/buy events live from the event store (LEventStore, like the ecommerce
template's serving-time reads).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Sequence

import numpy as np

from incubator_predictionio_tpu.core import (
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    PAlgorithm,
    Params,
    PDataSource,
    SanityCheck,
)
from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.data.store import LEventStore, PEventStore
from incubator_predictionio_tpu.models.transformer import (
    TransformerConfig,
    TransformerModel,
    TransformerRecommender,
)
from incubator_predictionio_tpu.parallel.mesh import MeshContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    user: Optional[str] = None
    recent_items: Optional[tuple[str, ...]] = None
    num: int = 10


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...] = ()


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "sequential"
    max_len: int = 32
    events: tuple[str, ...] = ("view", "buy")


@dataclasses.dataclass
class TrainingData(SanityCheck):
    sequences: np.ndarray  # [n, max_len+1] int32 tokens, 0-padded left
    item_map: BiMap        # item id → token (1-based; 0 = padding)
    # multi-process sharded read: sequences are THIS process's user shard
    # only (sessions never cross shards; item_map/tokens are global)
    rows_are_local: bool = False
    n_rows_global: Optional[int] = None

    def sanity_check(self) -> None:
        total = (self.n_rows_global if self.n_rows_global is not None
                 else len(self.sequences))
        if total == 0:
            raise ValueError("no sessions found")


def encode_session(items: Sequence[str], item_map: BiMap, width: int) -> np.ndarray:
    """Left-pad a session's tokens to ``width`` (newest item last)."""
    tokens = [item_map[i] for i in items if i in item_map][-width:]
    out = np.zeros(width, np.int32)
    if tokens:
        out[-len(tokens):] = tokens
    return out


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)
        self._store = PEventStore()

    def read_training(self, ctx: MeshContext) -> TrainingData:
        p = self.params
        procs, pid = ctx.process_count, ctx.process_index
        sharded = procs > 1
        sessions: dict[str, list[str]] = {}
        item_ids: list[str] = []
        if sharded:
            # sessions are per-user, users are entity-sharded → each process
            # reads whole sessions for 1/P of the users (never splits one)
            events = self._store.find_sharded(
                p.app_name, procs, entity_type="user",
                event_names=tuple(p.events))[pid]
        else:
            events = self._store.find(
                p.app_name, entity_type="user", event_names=tuple(p.events),
                target_entity_type="item",
            )
        for e in events:  # find() is event-time ordered
            if e.target_entity_type != "item":
                continue
            sessions.setdefault(e.entity_id, []).append(e.target_entity_id)
            item_ids.append(e.target_entity_id)
        # token 0 reserved for padding → 1-based item tokens
        base = BiMap.string_int(item_ids)
        n_rows_global = None
        if sharded:
            from incubator_predictionio_tpu.data.sharded import (
                global_row_count,
                union_vocab,
            )

            # global token space: first-seen union over shards in process
            # order (one vocab-sized allgather)
            vocab, _ = union_vocab(ctx, list(base))
            base = BiMap({v: i for i, v in enumerate(vocab.tolist())})
        item_map = BiMap({k: v + 1 for k, v in base.items()})
        width = p.max_len + 1
        rows = [
            encode_session(items, item_map, width)
            for items in sessions.values()
            if len(items) >= 2
        ]
        if sharded:
            n_rows_global = global_row_count(ctx, len(rows))
            logger.info(
                "sharded read: %d of %d rows (shard %d/%d)",
                len(rows), n_rows_global, pid, procs)
        return TrainingData(
            sequences=np.stack(rows) if rows else np.zeros((0, width), np.int32),
            item_map=item_map,
            rows_are_local=sharded,
            n_rows_global=n_rows_global,
        )


@dataclasses.dataclass(frozen=True)
class TransformerAlgorithmParams(Params):
    app_name: str = "sequential"
    max_len: int = 32
    d_model: int = 64
    n_heads: int = 2
    n_layers: int = 2
    learning_rate: float = 1e-3
    batch_size: int = 256
    epochs: int = 10
    seed: int = 0
    attention: str = "auto"  # "auto" | "local" | "ring"
    # mixture-of-experts FFN: 0 = dense; >0 switches to top-1 routed experts
    # sharded over the mesh's "expert" axis when present
    num_experts: int = 0
    # pipeline parallelism: stage count over the mesh's "pipe" axis (0 = off)
    pipeline_stages: int = 0
    pipeline_microbatches: int = 0
    recent_events: tuple[str, ...] = ("view", "buy")
    checkpoint_dir: Optional[str] = None   # mid-training resume (utils/checkpoint.py)
    checkpoint_every: int = 0


class TransformerAlgorithm(PAlgorithm):
    params_class = TransformerAlgorithmParams
    query_cls = Query

    def __init__(self, params: TransformerAlgorithmParams):
        super().__init__(params)
        self._levents = LEventStore()

    def train(self, ctx: MeshContext, pd: TrainingData) -> TransformerModel:
        p = self.params
        cfg = TransformerConfig(
            vocab_size=len(pd.item_map) + 1,
            max_len=p.max_len,
            d_model=p.d_model,
            n_heads=p.n_heads,
            n_layers=p.n_layers,
            learning_rate=p.learning_rate,
            batch_size=p.batch_size,
            epochs=p.epochs,
            seed=p.seed,
            attention=p.attention,
            n_experts=p.num_experts,
            pipeline_stages=p.pipeline_stages,
            pipeline_microbatches=p.pipeline_microbatches,
            checkpoint_dir=p.checkpoint_dir,
            checkpoint_every=p.checkpoint_every,
        )
        return TransformerRecommender(cfg).fit(
            ctx, pd.sequences, pd.item_map,
            rows_are_local=pd.rows_are_local)

    def _history(self, query: Query, model: TransformerModel) -> list[str]:
        if query.recent_items is not None:
            return list(query.recent_items)
        if query.user is None:
            return []
        try:
            events = list(self._levents.find_by_entity(
                self.params.app_name, "user", query.user,
                event_names=tuple(self.params.recent_events),
                target_entity_type="item",
                limit=model.config.max_len, latest=True,
            ))
        except ValueError:
            return []
        return [e.target_entity_id for e in reversed(events) if e.target_entity_id]

    def predict(self, model: TransformerModel, query: Query) -> PredictedResult:
        return self.batch_predict(model, [(0, query)])[0][1]

    def batch_predict(
        self, model: TransformerModel, queries: Sequence[tuple[int, Query]]
    ) -> list[tuple[int, PredictedResult]]:
        if not queries:
            return []
        histories = [self._history(q, model) for _, q in queries]
        rows = np.stack([
            encode_session(h, model.item_map, model.config.max_len)
            for h in histories
        ])
        scores = TransformerRecommender.next_item_scores(model, rows)
        inv = model.item_map.inverse()
        out = []
        for (qi, q), h, row_scores in zip(queries, histories, scores):
            if not any(i in model.item_map for i in h):
                out.append((qi, PredictedResult()))  # cold session
                continue
            s = row_scores.copy()
            s[0] = -np.inf  # padding token
            for i in h:     # exclude history items
                tok = model.item_map.get(i)
                if tok is not None:
                    s[tok] = -np.inf
            num = min(q.num, len(s) - 1)
            top = np.argpartition(-s, num - 1)[:num]
            top = top[np.argsort(-s[top])]
            out.append((qi, PredictedResult(tuple(
                ItemScore(inv[int(t)], float(s[t]))
                for t in top if np.isfinite(s[t])
            ))))
        return out


class SequentialEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            DataSource,
            IdentityPreparator,
            {"transformer": TransformerAlgorithm, "": TransformerAlgorithm},
            FirstServing,
        )
