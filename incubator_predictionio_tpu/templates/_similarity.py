"""Shared cosine-similarity serving path for the similarproduct family
(similarproduct, recommended_user).

One jitted bf16 MXU matmul scores every candidate against the summed query
vectors; filters ride as an additive -inf mask (the reference's per-candidate
cosine loops: similarproduct ALSAlgorithm.scala:150-175, recommended-user
ALSAlgorithm.scala:150-160).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def l2_normalize(v: np.ndarray) -> np.ndarray:
    return v / (np.linalg.norm(v, axis=1, keepdims=True) + 1e-9)


@jax.jit
def sim_scores(qvecs, cand_vt, mask):
    """[q, k] query rows × [k, n] candidate columns → [n] summed cosine
    scores (+ mask). Rows must be L2-normalized for cosine semantics."""
    scores = (
        (qvecs.astype(jnp.bfloat16) @ cand_vt.astype(jnp.bfloat16)).astype(jnp.float32)
    )
    return scores.sum(axis=0) + mask
