"""Shared cosine-similarity serving path for the similarproduct family
(similarproduct, recommended_user).

One jitted bf16 MXU matmul scores every candidate against the query vectors;
filters ride as an additive -inf mask (the reference's per-candidate cosine
loops: similarproduct ALSAlgorithm.scala:150-175, recommended-user
ALSAlgorithm.scala:150-160).

Batching contract: the matmul is the ONLY device op — the per-query sum over
its vectors' score rows happens host-side. XLA's row results are invariant
to how many rows share the dispatch, so a whole coalesced batch's query
vectors can stack into one ``[ΣQ, k] × [k, n]`` dispatch
(:func:`sim_scores_stacked`) and reproduce the per-query
:func:`sim_scores` results bitwise — that equality is what the
batched-vs-serial parity tests pin. (The pre-batching version summed inside
the jit; XLA fuses that reduction differently for different stackings,
which is exactly the bitwise drift this layout avoids.)
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def l2_normalize(v: np.ndarray) -> np.ndarray:
    return v / (np.linalg.norm(v, axis=1, keepdims=True) + 1e-9)


@jax.jit
def qv_scores(qvecs, cand_vt):
    """[q, k] query rows × [k, n] candidate columns → [q, n] per-row scores
    (bf16 MXU matmul, fp32 result). Rows must be L2-normalized for cosine
    semantics."""
    return (
        (qvecs.astype(jnp.bfloat16) @ cand_vt.astype(jnp.bfloat16))
        .astype(jnp.float32)
    )


def _matmul_rows(qvecs: np.ndarray, cand_vt) -> np.ndarray:
    """One bucket-padded :func:`qv_scores` dispatch → host [q, n] rows.

    Row counts pad up to the serving bucket ladder (zero rows score zero
    and are sliced off), so the executable count stays bounded instead of
    one compile per distinct stack height."""
    from incubator_predictionio_tpu.models.two_tower import serve_bucket

    q = qvecs.shape[0]
    bucket = serve_bucket(max(q, 1))
    if bucket != q:
        qvecs = np.concatenate(
            [qvecs, np.zeros((bucket - q, qvecs.shape[1]), qvecs.dtype)])
    return np.asarray(qv_scores(jnp.asarray(qvecs), cand_vt))[:q]


def sim_scores(qvecs, cand_vt, mask) -> np.ndarray:
    """[q, k] query rows → [n] summed cosine scores (+ mask), host sum."""
    rows = _matmul_rows(np.asarray(qvecs, np.float32), cand_vt)
    return rows.sum(axis=0) + np.asarray(mask)


def sim_scores_stacked(
    qvecs: np.ndarray,
    counts: Sequence[int],
    cand_vt,
    masks: Optional[np.ndarray] = None,
) -> np.ndarray:
    """A whole batch in ONE matmul dispatch.

    ``qvecs`` is every query's vectors concatenated ([ΣQ, k], query i owning
    ``counts[i]`` consecutive rows); ``masks`` an optional [B, n] additive
    mask. Returns [B, n] summed scores — row-for-row bitwise equal to
    calling :func:`sim_scores` per query."""
    rows = _matmul_rows(np.asarray(qvecs, np.float32), cand_vt)
    out = np.empty((len(counts), rows.shape[1]), np.float32)
    off = 0
    for i, c in enumerate(counts):
        row = rows[off:off + c].sum(axis=0)
        out[i] = row + masks[i] if masks is not None else row
        off += c
    return out
