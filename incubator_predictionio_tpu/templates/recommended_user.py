"""RecommendedUser template — the scala-parallel-similarproduct/recommended-user
variant: recommend USERS to follow, from user→user "follow" events.

Reference behavior (examples/scala-parallel-similarproduct/recommended-user/):
- DataSource reads user ``$set`` events plus "follow" user→user events
  (DataSource.scala:55-85);
- ALSAlgorithm runs implicit MF over (follower, followedUser) pairs and keeps
  the followed-side factor matrix (ALSAlgorithm.scala:104-124
  ``ALS.trainImplicit`` → ``m.productFeatures``);
- Query {"users": […], "num": N, "whiteList"?, "blackList"?} → top-N similar
  users by the SUM of cosine similarities against every query user's vector,
  excluding the query users themselves (ALSAlgorithm.scala:127-185).

TPU mapping: identical to the item-similarity path — the reference's
per-candidate parallel-collection cosine loop (ALSAlgorithm.scala:150-160)
becomes one bf16 ``[q, k] × [k, n]`` MXU matmul over the L2-normalized
followed-user table, plus an additive -inf filter mask.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Sequence

import jax
import numpy as np

from incubator_predictionio_tpu.core import (
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    PAlgorithm,
    Params,
    PDataSource,
    SanityCheck,
)
from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.data.store import PEventStore
from incubator_predictionio_tpu.models.two_tower import TwoTowerConfig, TwoTowerMF
from incubator_predictionio_tpu.parallel.mesh import MeshContext
from incubator_predictionio_tpu.serving import ban_rows, grouped_topk, whitelist_vec
from incubator_predictionio_tpu.templates._similarity import (
    l2_normalize,
    sim_scores,
    sim_scores_stacked,
)

logger = logging.getLogger(__name__)


# -- query / result ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Query:
    users: tuple[str, ...]
    num: int = 10
    white_list: Optional[tuple[str, ...]] = None
    black_list: Optional[tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class SimilarUserScore:
    user: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    similar_user_scores: tuple[SimilarUserScore, ...] = ()


# -- data source ------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "recommendeduser"


@dataclasses.dataclass
class TrainingData(SanityCheck):
    users: BiMap                 # user id ↔ index (followers and followed share it)
    follow_u: np.ndarray         # [n_follows] follower idx
    follow_t: np.ndarray         # [n_follows] followed idx
    # multi-process sharded read: follow rows are THIS process's follower
    # shard only (the BiMap is global); n_follows_global is the job-wide count
    rows_are_local: bool = False
    n_follows_global: Optional[int] = None

    def sanity_check(self) -> None:
        if len(self.users) == 0:
            raise ValueError("no users found ($set events on entityType 'user')")
        n = (self.n_follows_global if self.n_follows_global is not None
             else len(self.follow_u))
        if n == 0:
            raise ValueError("no follow events found")


class DataSource(PDataSource):
    """DataSource.scala:40-86 — users + follow events, sharded per process."""

    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)
        self._store = PEventStore()

    def read_training(self, ctx: MeshContext) -> TrainingData:
        app = self.params.app_name
        procs, pid = ctx.process_count, ctx.process_index
        sharded = procs > 1
        user_props = self._store.aggregate_properties(app, "user")
        if sharded:
            events = self._store.find_sharded(
                app, procs, entity_type="user", event_names=("follow",))[pid]
        else:
            events = self._store.find(
                app, entity_type="user", event_names=("follow",),
                target_entity_type="user")
        follows: list[tuple[str, str]] = []
        local_users: set[str] = set()
        for e in events:
            if e.target_entity_type != "user" or e.target_entity_id is None:
                continue
            local_users.add(e.entity_id)
            local_users.add(e.target_entity_id)
            follows.append((e.entity_id, e.target_entity_id))
        user_ids = set(user_props.keys())
        n_follows_global = None
        if sharded:
            from incubator_predictionio_tpu.data.sharded import (
                global_row_count,
                union_label_set,
            )

            # global vocabulary: $set users ∪ union of per-shard event users
            # (followed ids can live outside this follower shard)
            user_ids |= set(union_label_set(ctx, local_users))
            n_follows_global = global_row_count(ctx, len(follows))
            logger.info("sharded read: %d of %d rows (shard %d/%d)",
                        len(follows), n_follows_global, pid, procs)
        else:
            user_ids |= local_users
        users = BiMap.string_int(sorted(user_ids))
        return TrainingData(
            users=users,
            follow_u=users.lookup_array([u for u, _ in follows]),
            follow_t=users.lookup_array([t for _, t in follows]),
            rows_are_local=sharded,
            n_follows_global=n_follows_global,
        )


# -- model + algorithm ------------------------------------------------------

@dataclasses.dataclass
class SimilarUserModel:
    """L2-normalized followed-user vectors (the reference keeps
    ``productFeatures`` — ALSAlgorithm.scala:119-124)."""

    user_vecs: np.ndarray        # [n_users, k] L2-normalized
    user_map: BiMap

    _device_vt = None

    def prepare_for_serving(self) -> "SimilarUserModel":
        self._device_vt = jax.device_put(np.ascontiguousarray(self.user_vecs.T))
        return self

    def serving_info(self) -> dict:
        return {"path": "device-bf16", "catalog_rows": len(self.user_map)}


@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 16
    num_iterations: int = 20
    learning_rate: float = 3e-2
    negatives_per_positive: int = 4
    seed: Optional[int] = None


class ALSAlgorithm(PAlgorithm):
    """Implicit MF over follow pairs; cosine-sum scoring
    (ALSAlgorithm.scala:104-185)."""

    params_class = ALSAlgorithmParams
    serving_thread_safe = True  # jit dispatch + read-only served arrays
    query_cls = Query

    def train(self, ctx: MeshContext, pd: TrainingData) -> SimilarUserModel:
        from incubator_predictionio_tpu.models.negative_sampling import sample_negatives

        p = self.params
        rng = np.random.default_rng(p.seed if p.seed is not None else 0)
        pos_u, pos_t = pd.follow_u, pd.follow_t
        neg_u, neg_t = sample_negatives(
            pos_u, pos_t, len(pd.users), p.negatives_per_positive, rng)
        mf = TwoTowerMF(TwoTowerConfig(
            rank=p.rank, epochs=p.num_iterations, learning_rate=p.learning_rate,
            batch_size=8192, seed=p.seed if p.seed is not None else 0,
        )).fit(
            ctx,
            np.concatenate([pos_u, neg_u]),
            np.concatenate([pos_t, neg_t]),
            np.concatenate([np.ones(len(pos_u), np.float32),
                            np.zeros(len(neg_u), np.float32)]),
            len(pd.users), len(pd.users),
            rows_are_local=pd.rows_are_local,
        )
        # followed-side tower = the reference's productFeatures
        # (cosine model is a host build: materialize if device-resident)
        mf.ensure_host()
        return SimilarUserModel(
            user_vecs=l2_normalize(mf.item_emb),
            user_map=pd.users,
        )

    def predict(self, model: SimilarUserModel, query: Query) -> PredictedResult:
        known = [model.user_map[u] for u in query.users if u in model.user_map]
        if not known:
            logger.info("no feature vectors for query users %s", query.users)
            return PredictedResult()
        if model._device_vt is None:
            model.prepare_for_serving()
        mask = self._filter_mask(model, query)
        qvecs = model.user_vecs[np.asarray(known)]
        scores = sim_scores(qvecs, model._device_vt, mask)
        num = min(query.num, len(scores))
        if num <= 0:  # degenerate query, not a catalog dump
            return PredictedResult()
        top = np.argpartition(-scores, num - 1)[:num]
        top = top[np.argsort(-scores[top])]
        inv = model.user_map.inverse()
        # score > 0 cut is reference behavior for THIS variant: "keep
        # similarUsers with score > 0" (ALSAlgorithm.scala:160)
        return PredictedResult(tuple(
            SimilarUserScore(inv[int(i)], float(scores[i]))
            for i in top if np.isfinite(scores[i]) and scores[i] > 0
        ))

    @staticmethod
    def _filter_mask(model: SimilarUserModel, query: Query) -> np.ndarray:
        """-inf mask: whitelist/blacklist + query-user self-exclusion
        (isCandidateSimilarUser, ALSAlgorithm.scala:200-230) — vectorized
        ``lookup_array`` scatters (serving/masks.py)."""
        n = len(model.user_map)
        mask = np.zeros(n, np.float32)
        if query.white_list is not None:
            mask += whitelist_vec(model.user_map, query.white_list)
        ban_rows(mask, model.user_map, query.black_list)
        # never recommend the query users themselves
        ban_rows(mask, model.user_map, query.users)
        return mask

    def batch_predict(self, model, queries):
        """Batched serving: one stacked scoring dispatch for the whole
        coalesced batch (bitwise equal per row to the serial path — see
        ``sim_scores_stacked``), vectorized [B, n] masks, axis-wise top-k
        per ``num`` group, and the serial score>0 cut per row."""
        queries = list(queries)
        if not queries:
            return []
        if model._device_vt is None:
            model.prepare_for_serving()
        qs = [q for _, q in queries]
        known = [
            np.asarray([model.user_map[u] for u in q.users
                        if u in model.user_map], np.int64)
            for q in qs
        ]
        results: list[PredictedResult] = [PredictedResult()] * len(qs)
        live = [b for b, k in enumerate(known) if len(k)]
        if live:
            masks = np.stack([self._filter_mask(model, qs[b]) for b in live])
            counts = [len(known[b]) for b in live]
            qvecs = model.user_vecs[np.concatenate([known[b] for b in live])]
            scored = sim_scores_stacked(qvecs, counts, model._device_vt, masks)
            inv = model.user_map.inverse()
            n = scored.shape[1]
            for r, (idx_row, score_row) in enumerate(grouped_topk(
                    scored, [min(qs[b].num, n) for b in live])):
                keep = np.isfinite(score_row) & (score_row > 0)
                results[live[r]] = PredictedResult(tuple(
                    SimilarUserScore(inv[int(i)], float(v))
                    for i, v, k in zip(idx_row, score_row, keep) if k
                ))
        return [(qi, results[b]) for b, (qi, _) in enumerate(queries)]


class RecommendedUserEngine(EngineFactory):
    """Engine.scala:41-48."""

    def apply(self) -> Engine:
        return Engine(
            DataSource,
            IdentityPreparator,
            {"als": ALSAlgorithm, "": ALSAlgorithm},
            {"": FirstServing},
        )
