"""E-commerce recommendation template — the scala-parallel-ecommercerecommendation counterpart.

Reference behavior (examples/scala-parallel-ecommercerecommendation/.../ECommAlgorithm.scala:79-597):
- trains implicit MF on view (+ optional buy) events and keeps per-item
  ``ProductModel``s with popularity counts (``trainDefault`` :211);
- query-time business rules: category filter, whitelist/blacklist,
  **unavailable items** read live from the event store ("constraint"
  ``$set`` events, latest wins :150-180), and unseen-only filtering of the
  user's view/buy history (:429-470);
- prediction fallbacks: predictKnownUser (:429) → predictSimilar from the
  user's recent views (:505) → predictDefault popularity (:475).

The live reads ride :class:`LEventStore` exactly like the reference — this is
the low-latency serving-time storage path (SURVEY §7 hard part on
LEventStore-equivalent reads at predict time). Dynamic candidate filters
become -inf masks over the static item axis before one on-device top-k.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Sequence

import numpy as np

from incubator_predictionio_tpu.core import (
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    PAlgorithm,
    Params,
    PDataSource,
    SanityCheck,
)
from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.data.store import LEventStore, PEventStore
from incubator_predictionio_tpu.models.two_tower import (
    TwoTowerConfig,
    TwoTowerMF,
    TwoTowerModel,
)
from incubator_predictionio_tpu.parallel.mesh import MeshContext
from incubator_predictionio_tpu.serving import (
    HasCategoryIndex,
    TTLCache,
    ban_rows,
    constraint_ttl_sec,
    grouped_topk,
    whitelist_vec,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 10
    categories: Optional[tuple[str, ...]] = None
    white_list: Optional[tuple[str, ...]] = None
    black_list: Optional[tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...] = ()


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "ecommerce"
    # train-with-rate-event variant: which events count as view/buy signal,
    # and the implicit buy weight (examples/scala-parallel-
    # ecommercerecommendation/train-with-rate-event)
    view_event_names: tuple[str, ...] = ("view",)
    buy_event_names: tuple[str, ...] = ("buy",)
    buy_weight: float = 2.0


@dataclasses.dataclass
class TrainingData(SanityCheck):
    users: BiMap
    items: BiMap
    categories: dict[str, tuple[str, ...]]
    u_idx: np.ndarray       # [n] interaction user idx (views + buys)
    i_idx: np.ndarray       # [n] interaction item idx
    weight: np.ndarray      # [n] 1.0 view / buy_weight buy
    buy_counts: np.ndarray  # [n_items] popularity (always global)
    # multi-process sharded read: interaction rows are THIS process's user
    # shard only (BiMaps/indices/buy_counts are global)
    rows_are_local: bool = False
    n_rows_global: Optional[int] = None

    def sanity_check(self) -> None:
        if len(self.items) == 0:
            raise ValueError("no items found ($set events on entityType 'item')")
        total = (self.n_rows_global if self.n_rows_global is not None
                 else len(self.u_idx))
        if total == 0:
            raise ValueError("no view/buy events found")


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)
        self._store = PEventStore()

    def read_training(self, ctx: MeshContext) -> TrainingData:
        app = self.params.app_name
        procs, pid = ctx.process_count, ctx.process_index
        sharded = procs > 1
        item_props = self._store.aggregate_properties(app, "item")
        items = BiMap.string_int(item_props.keys())
        categories = {
            iid: tuple(pm.get("categories") or ()) for iid, pm in item_props.items()
        }
        inter_u, inter_i, weight = [], [], []
        buy_counts = np.zeros(len(items), np.int64)
        user_ids = set()
        view_names = tuple(self.params.view_event_names)
        buy_names = tuple(self.params.buy_event_names)
        wanted = (*view_names, *buy_names)
        if sharded:
            # per-process entity-disjoint slice (reference: RDD partitions)
            events = self._store.find_sharded(
                app, procs, entity_type="user", event_names=wanted)[pid]
        else:
            events = self._store.find(
                app, entity_type="user", event_names=wanted,
                target_entity_type="item",
            )
        for e in events:
            if e.target_entity_type != "item" or e.target_entity_id not in items:
                continue
            user_ids.add(e.entity_id)
            inter_u.append(e.entity_id)
            inter_i.append(e.target_entity_id)
            is_view = e.event in view_names
            weight.append(1.0 if is_view else self.params.buy_weight)
            if not is_view:
                buy_counts[items[e.target_entity_id]] += 1
        n_rows_global = None
        if sharded:
            from incubator_predictionio_tpu.data.sharded import (
                global_row_count,
                global_sum,
                union_label_set,
            )

            user_ids = set(union_label_set(ctx, user_ids))
            buy_counts = global_sum(ctx, buy_counts)  # popularity is global
            n_rows_global = global_row_count(ctx, len(inter_u))
            logger.info(
                "sharded read: %d of %d rows (shard %d/%d)",
                len(inter_u), n_rows_global, pid, procs)
        users = BiMap.string_int(sorted(user_ids))  # sorted: set order is hash-seed dependent
        return TrainingData(
            users=users,
            items=items,
            categories=categories,
            u_idx=users.lookup_array(inter_u),
            i_idx=items.lookup_array(inter_i),
            weight=np.asarray(weight, np.float32),
            buy_counts=buy_counts,
            rows_are_local=sharded,
            n_rows_global=n_rows_global,
        )


@dataclasses.dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    """(ECommAlgorithm.scala ECommAlgorithmParams: appName, unseenOnly,
    seenEvents, similarEvents, rank, numIterations, lambda, seed)"""

    app_name: str = "ecommerce"
    unseen_only: bool = True
    seen_events: tuple[str, ...] = ("buy", "view")
    similar_events: tuple[str, ...] = ("view",)
    rank: int = 16
    num_iterations: int = 20
    learning_rate: float = 3e-2
    negatives_per_positive: int = 4
    seed: Optional[int] = None


@dataclasses.dataclass
class ECommModel(HasCategoryIndex):
    mf: TwoTowerModel
    user_map: BiMap
    item_map: BiMap
    categories: dict[str, tuple[str, ...]]
    popularity: np.ndarray  # [n_items] buy counts
    item_vecs_norm: np.ndarray  # L2-normalized item factors for predictSimilar

    def prepare_for_serving(self) -> "ECommModel":
        # build_index=False: this template scores through its own
        # mask-compiled host path, never TwoTowerMF.recommend_batch — a
        # two-stage retrieval index would be dead weight at deploy
        self.mf.prepare_for_serving(build_index=False)
        self.category_index()
        return self

    def serving_info(self) -> dict:
        return self.mf.serving_info()


class ECommAlgorithm(PAlgorithm):
    params_class = ECommAlgorithmParams
    serving_thread_safe = True  # jit dispatch + read-only served arrays
    query_cls = Query

    def __init__(self, params: ECommAlgorithmParams):
        super().__init__(params)
        self._levents = LEventStore()
        # TTL + single-flight cache over the per-query constraint read
        # (``PIO_SERVING_CONSTRAINT_TTL_MS=0`` restores the reference's
        # read-per-query semantics; tests swap in a FakeClock-backed cache)
        self._constraint_cache = TTLCache(constraint_ttl_sec())

    def train(self, ctx: MeshContext, pd: TrainingData) -> ECommModel:
        from incubator_predictionio_tpu.models.negative_sampling import sample_negatives

        p = self.params
        rng = np.random.default_rng(p.seed if p.seed is not None else 0)
        k = p.negatives_per_positive
        neg_u, neg_i = sample_negatives(pd.u_idx, pd.i_idx, len(pd.items), k, rng)
        users = np.concatenate([pd.u_idx, neg_u])
        items = np.concatenate([pd.i_idx, neg_i])
        ratings = np.concatenate([pd.weight, np.zeros(len(neg_u), np.float32)])
        mf = TwoTowerMF(TwoTowerConfig(
            rank=p.rank, epochs=p.num_iterations, learning_rate=p.learning_rate,
            batch_size=8192, seed=p.seed if p.seed is not None else 0,
        )).fit(ctx, users, items, ratings, len(pd.users), len(pd.items),
               rows_are_local=pd.rows_are_local)
        mf.ensure_host()  # similarity sidecar + host predict path need numpy
        norm = mf.item_emb / (np.linalg.norm(mf.item_emb, axis=1, keepdims=True) + 1e-9)
        return ECommModel(
            mf=mf,
            user_map=pd.users,
            item_map=pd.items,
            categories=pd.categories,
            popularity=pd.buy_counts.astype(np.float32),
            item_vecs_norm=norm,
        )

    # -- live event-store reads (serving time) ----------------------------
    def _unavailable_items(self) -> set[str]:
        """Latest "constraint/unavailableItems" ``$set`` wins
        (ECommAlgorithm.scala:150-180) — read through the TTL single-flight
        cache, so a query storm costs one storage read per TTL window."""
        return self._constraint_cache.get(
            "unavailableItems", self._read_unavailable_items)

    def _read_unavailable_items(self) -> set[str]:
        try:
            events = list(self._levents.find_by_entity(
                self.params.app_name, "constraint", "unavailableItems",
                event_names=("$set",), limit=1, latest=True,
            ))
        except ValueError:
            return set()
        if not events:
            return set()
        return set(events[0].properties.get("items") or ())

    def _seen_items(self, user: str) -> set[str]:
        """User's view/buy history (ECommAlgorithm.scala:429-470)."""
        try:
            return {
                e.target_entity_id
                for e in self._levents.find_by_entity(
                    self.params.app_name, "user", user,
                    event_names=tuple(self.params.seen_events),
                    target_entity_type="item",
                )
                if e.target_entity_id
            }
        except ValueError:
            return set()

    def _recent_similar_items(self, user: str, limit: int = 10) -> list[str]:
        """User's recent view targets for predictSimilar (:505-530)."""
        try:
            return [
                e.target_entity_id
                for e in self._levents.find_by_entity(
                    self.params.app_name, "user", user,
                    event_names=tuple(self.params.similar_events),
                    target_entity_type="item", limit=limit, latest=True,
                )
                if e.target_entity_id
            ]
        except ValueError:
            return []

    def _recent_similar_items_batch(
        self, users: Sequence[str], limit: int = 10,
    ) -> dict[str, list[str]]:
        """Batched :meth:`_recent_similar_items` for a batch's unknown users."""
        try:
            by_user = self._levents.find_by_entities(
                self.params.app_name, "user", users,
                event_names=tuple(self.params.similar_events),
                target_entity_type="item", limit_per_entity=limit,
                latest=True,
            )
        except ValueError:
            return {}
        return {
            u: [e.target_entity_id for e in evs if e.target_entity_id]
            for u, evs in by_user.items()
        }

    def _histories_batch(
        self, users: Sequence[str], unknown: Sequence[str], limit: int = 10,
    ) -> tuple[dict[str, set[str]], dict[str, list[str]]]:
        """ONE union read serving both per-user derivations: seen-items
        (every user) and the unknown users' recent views. The event-name
        union covers both reads' filters, and filtering a latest-first
        stream by event name preserves each name-subset's order, so the
        derived results equal the dedicated :meth:`_seen_items` /
        :meth:`_recent_similar_items` reads exactly — one storage round
        trip instead of two per batch."""
        seen_names = tuple(self.params.seen_events)
        similar_names = tuple(self.params.similar_events)
        try:
            by_user = self._levents.find_by_entities(
                self.params.app_name, "user", users,
                event_names=tuple(dict.fromkeys((*seen_names, *similar_names))),
                target_entity_type="item", latest=True,
            )
        except ValueError:
            return {}, {}
        seen = {
            u: {e.target_entity_id for e in evs
                if e.event in seen_names and e.target_entity_id}
            for u, evs in by_user.items()
        }
        recent: dict[str, list[str]] = {}
        for u in unknown:
            matching = [e for e in by_user.get(u, ())
                        if e.event in similar_names][:limit]
            recent[u] = [e.target_entity_id for e in matching
                         if e.target_entity_id]
        return seen, recent

    # -- masking ----------------------------------------------------------
    @staticmethod
    def _rule_mask(model: ECommModel, query: Query) -> np.ndarray:
        """[n] additive -inf mask for the query-carried filters (whitelist,
        blacklist, categories) — vectorized index scatters over the compiled
        :class:`CategoryIndex` (serving/masks.py) instead of the seed's
        per-item Python loops. ONE implementation shared verbatim by the
        serial path and the batched per-batch memo, so a new filter added
        here reaches both (the parity contract's single source of truth);
        the read-dependent filters (unavailable, seen) compose on top."""
        n = len(model.item_map)
        mask = np.zeros(n, np.float32)
        if query.white_list is not None:
            mask += whitelist_vec(model.item_map, query.white_list)
        ban_rows(mask, model.item_map, query.black_list)
        if query.categories is not None:
            mask += model.category_index().allow_vec(query.categories)
        return mask

    def _mask(self, model: ECommModel, query: Query) -> np.ndarray:
        """The serial path's full mask: query rules + live store reads."""
        mask = self._rule_mask(model, query)
        ban_rows(mask, model.item_map, tuple(self._unavailable_items()))
        if self.params.unseen_only:
            ban_rows(mask, model.item_map, tuple(self._seen_items(query.user)))
        return mask

    # -- prediction -------------------------------------------------------
    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        mask = self._mask(model, query)
        uidx = model.user_map.get(query.user)
        if uidx is not None:
            scores = (
                model.mf.user_emb[uidx] @ model.mf.item_emb.T
                + model.mf.item_bias + model.mf.user_bias[uidx] + model.mf.mean
            )
        else:
            recent = [model.item_map[i] for i in self._recent_similar_items(query.user)
                      if i in model.item_map]
            if recent:
                logger.info("unknown user %s: predictSimilar from %d recent views",
                            query.user, len(recent))
                qv = model.item_vecs_norm[np.asarray(recent)]
                scores = (qv @ model.item_vecs_norm.T).sum(axis=0)
            else:
                logger.info("unknown user %s: predictDefault popularity", query.user)
                scores = model.popularity.copy()
        scores = scores + mask
        num = min(query.num, len(scores))
        if num <= 0:  # degenerate query, not a catalog dump
            return PredictedResult()
        top = np.argpartition(-scores, num - 1)[:num]
        top = top[np.argsort(-scores[top])]
        inv = model.item_map.inverse()
        return PredictedResult(tuple(
            ItemScore(inv[int(i)], float(scores[i]))
            for i in top if np.isfinite(scores[i])
        ))

    def batch_predict(self, model, queries):
        """Vectorized batch serving: a coalesced micro-batch costs O(1) live
        store reads and one vectorized pass per stage instead of the serial
        path's O(B) reads and O(B × catalog) Python.

        - **reads**: one TTL-cached constraint read + ONE batched
          ``find_by_entities`` for every user's seen history (+ one more for
          unknown users' recent views) — the serial path pays 2 reads/query;
        - **masks**: [B, N] assembled from compiled category rows and
          ``lookup_array`` scatters;
        - **scores**: each known user's row goes through the *same* BLAS
          call chain as the serial path (bitwise-identical scores — the
          parity tests' contract; a stacked GEMM's rows differ in final ulps
          from the per-query GEMV), then ONE axis-wise top-k per ``num``
          group replaces per-query selection. Unknown users take the (rare)
          similar/popularity fallback exactly like the serial path.
        """
        queries = list(queries)
        if not queries:
            return []
        qs = [q for _, q in queries]
        n = len(model.item_map)
        # -- O(1) live reads for the whole batch --------------------------
        unavailable = tuple(self._unavailable_items())
        seen_by_user: dict[str, set[str]] = {}
        unknown = list(dict.fromkeys(
            q.user for q in qs if model.user_map.get(q.user) is None))
        if self.params.unseen_only:
            # one union read covers seen-items AND unknown users' recent
            # views (query users include the unknown ones)
            users = list(dict.fromkeys(q.user for q in qs))
            seen_by_user, recent_by_user = self._histories_batch(
                users, unknown)
        else:
            recent_by_user = (
                self._recent_similar_items_batch(unknown) if unknown else {})
        if unknown:
            logger.info("batch of %d: %d unknown users take the "
                        "similar/popularity fallback", len(qs), len(unknown))
        # -- [chunk, N] mask + scores + axis-wise top-k -------------------
        # rule masks (whitelist/blacklist/categories) memoized per distinct
        # filter tuple — live traffic repeats a handful of filters per batch;
        # the shared unavailable-items vector is built once. Every component
        # is {0, -inf}, so composing by addition matches the serial path's
        # scatter order exactly. The dense scored buffer is capped at
        # ROW_MASK_MAX_ELEMENTS (the device path's bound) by chunking the
        # batch — a deep micro-batch over a huge catalog must not balloon
        # host memory to O(B × N); chunking changes no result.
        from incubator_predictionio_tpu.models.two_tower import (
            ROW_MASK_MAX_ELEMENTS,
        )

        unavail_vec = np.zeros(n, np.float32)
        ban_rows(unavail_vec, model.item_map, unavailable)
        rule_cache: dict = {}
        inv = model.item_map.inverse()
        ue, ub = model.mf.user_emb, model.mf.user_bias
        ie_t, ib = model.mf.item_emb.T, model.mf.item_bias
        results: list[Optional[PredictedResult]] = [None] * len(qs)
        chunk = max(1, ROW_MASK_MAX_ELEMENTS // max(n, 1))
        for start in range(0, len(qs), chunk):
            rows = range(start, min(start + chunk, len(qs)))
            scored = np.empty((len(rows), n), np.float32)
            for r, b in enumerate(rows):
                q = qs[b]
                # wire-bound queries carry filter fields as LISTS
                # (bind_query does not coerce JSON arrays) — normalize to
                # tuples or the cache key is unhashable and every filtered
                # live batch crashes out of the vectorized path
                key = tuple(
                    tuple(f) if f is not None else None
                    for f in (q.white_list, q.black_list, q.categories))
                rules = rule_cache.get(key)
                if rules is None:
                    rules = rule_cache[key] = self._rule_mask(model, q)
                mask = rules + unavail_vec
                if self.params.unseen_only:
                    ban_rows(mask, model.item_map,
                             seen_by_user.get(q.user, ()))
                uidx = model.user_map.get(q.user)
                if uidx is not None:
                    scores = ue[uidx] @ ie_t + ib + ub[uidx] + model.mf.mean
                else:
                    recent = [model.item_map[i]
                              for i in recent_by_user.get(q.user, [])
                              if i in model.item_map]
                    if recent:
                        qv = model.item_vecs_norm[np.asarray(recent)]
                        scores = (qv @ model.item_vecs_norm.T).sum(axis=0)
                    else:
                        scores = model.popularity.copy()
                scored[r] = scores + mask
            for r, (idx_row, score_row) in enumerate(grouped_topk(
                    scored, [min(qs[b].num, n) for b in rows])):
                finite = np.isfinite(score_row)
                results[start + r] = PredictedResult(tuple(
                    ItemScore(inv[int(i)], float(v))
                    for i, v, f in zip(idx_row, score_row, finite) if f
                ))
        return [(qi, results[b]) for b, (qi, _) in enumerate(queries)]


class ECommerceEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            DataSource,
            IdentityPreparator,
            {"ecomm": ECommAlgorithm, "": ECommAlgorithm},
            FirstServing,
        )
