"""E-commerce recommendation template — the scala-parallel-ecommercerecommendation counterpart.

Reference behavior (examples/scala-parallel-ecommercerecommendation/.../ECommAlgorithm.scala:79-597):
- trains implicit MF on view (+ optional buy) events and keeps per-item
  ``ProductModel``s with popularity counts (``trainDefault`` :211);
- query-time business rules: category filter, whitelist/blacklist,
  **unavailable items** read live from the event store ("constraint"
  ``$set`` events, latest wins :150-180), and unseen-only filtering of the
  user's view/buy history (:429-470);
- prediction fallbacks: predictKnownUser (:429) → predictSimilar from the
  user's recent views (:505) → predictDefault popularity (:475).

The live reads ride :class:`LEventStore` exactly like the reference — this is
the low-latency serving-time storage path (SURVEY §7 hard part on
LEventStore-equivalent reads at predict time). Dynamic candidate filters
become -inf masks over the static item axis before one on-device top-k.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Sequence

import numpy as np

from incubator_predictionio_tpu.core import (
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    PAlgorithm,
    Params,
    PDataSource,
    SanityCheck,
)
from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.data.store import LEventStore, PEventStore
from incubator_predictionio_tpu.models.two_tower import (
    TwoTowerConfig,
    TwoTowerMF,
    TwoTowerModel,
)
from incubator_predictionio_tpu.parallel.mesh import MeshContext

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 10
    categories: Optional[tuple[str, ...]] = None
    white_list: Optional[tuple[str, ...]] = None
    black_list: Optional[tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...] = ()


@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "ecommerce"
    # train-with-rate-event variant: which events count as view/buy signal,
    # and the implicit buy weight (examples/scala-parallel-
    # ecommercerecommendation/train-with-rate-event)
    view_event_names: tuple[str, ...] = ("view",)
    buy_event_names: tuple[str, ...] = ("buy",)
    buy_weight: float = 2.0


@dataclasses.dataclass
class TrainingData(SanityCheck):
    users: BiMap
    items: BiMap
    categories: dict[str, tuple[str, ...]]
    u_idx: np.ndarray       # [n] interaction user idx (views + buys)
    i_idx: np.ndarray       # [n] interaction item idx
    weight: np.ndarray      # [n] 1.0 view / buy_weight buy
    buy_counts: np.ndarray  # [n_items] popularity (always global)
    # multi-process sharded read: interaction rows are THIS process's user
    # shard only (BiMaps/indices/buy_counts are global)
    rows_are_local: bool = False
    n_rows_global: Optional[int] = None

    def sanity_check(self) -> None:
        if len(self.items) == 0:
            raise ValueError("no items found ($set events on entityType 'item')")
        total = (self.n_rows_global if self.n_rows_global is not None
                 else len(self.u_idx))
        if total == 0:
            raise ValueError("no view/buy events found")


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)
        self._store = PEventStore()

    def read_training(self, ctx: MeshContext) -> TrainingData:
        app = self.params.app_name
        procs, pid = ctx.process_count, ctx.process_index
        sharded = procs > 1
        item_props = self._store.aggregate_properties(app, "item")
        items = BiMap.string_int(item_props.keys())
        categories = {
            iid: tuple(pm.get("categories") or ()) for iid, pm in item_props.items()
        }
        inter_u, inter_i, weight = [], [], []
        buy_counts = np.zeros(len(items), np.int64)
        user_ids = set()
        view_names = tuple(self.params.view_event_names)
        buy_names = tuple(self.params.buy_event_names)
        wanted = (*view_names, *buy_names)
        if sharded:
            # per-process entity-disjoint slice (reference: RDD partitions)
            events = self._store.find_sharded(
                app, procs, entity_type="user", event_names=wanted)[pid]
        else:
            events = self._store.find(
                app, entity_type="user", event_names=wanted,
                target_entity_type="item",
            )
        for e in events:
            if e.target_entity_type != "item" or e.target_entity_id not in items:
                continue
            user_ids.add(e.entity_id)
            inter_u.append(e.entity_id)
            inter_i.append(e.target_entity_id)
            is_view = e.event in view_names
            weight.append(1.0 if is_view else self.params.buy_weight)
            if not is_view:
                buy_counts[items[e.target_entity_id]] += 1
        n_rows_global = None
        if sharded:
            from incubator_predictionio_tpu.data.sharded import (
                global_row_count,
                global_sum,
                union_label_set,
            )

            user_ids = set(union_label_set(ctx, user_ids))
            buy_counts = global_sum(ctx, buy_counts)  # popularity is global
            n_rows_global = global_row_count(ctx, len(inter_u))
            logger.info(
                "sharded read: %d of %d rows (shard %d/%d)",
                len(inter_u), n_rows_global, pid, procs)
        users = BiMap.string_int(sorted(user_ids))  # sorted: set order is hash-seed dependent
        return TrainingData(
            users=users,
            items=items,
            categories=categories,
            u_idx=users.lookup_array(inter_u),
            i_idx=items.lookup_array(inter_i),
            weight=np.asarray(weight, np.float32),
            buy_counts=buy_counts,
            rows_are_local=sharded,
            n_rows_global=n_rows_global,
        )


@dataclasses.dataclass(frozen=True)
class ECommAlgorithmParams(Params):
    """(ECommAlgorithm.scala ECommAlgorithmParams: appName, unseenOnly,
    seenEvents, similarEvents, rank, numIterations, lambda, seed)"""

    app_name: str = "ecommerce"
    unseen_only: bool = True
    seen_events: tuple[str, ...] = ("buy", "view")
    similar_events: tuple[str, ...] = ("view",)
    rank: int = 16
    num_iterations: int = 20
    learning_rate: float = 3e-2
    negatives_per_positive: int = 4
    seed: Optional[int] = None


@dataclasses.dataclass
class ECommModel:
    mf: TwoTowerModel
    user_map: BiMap
    item_map: BiMap
    categories: dict[str, tuple[str, ...]]
    popularity: np.ndarray  # [n_items] buy counts
    item_vecs_norm: np.ndarray  # L2-normalized item factors for predictSimilar

    def prepare_for_serving(self) -> "ECommModel":
        self.mf.prepare_for_serving()
        return self

    def serving_info(self) -> dict:
        return self.mf.serving_info()


class ECommAlgorithm(PAlgorithm):
    params_class = ECommAlgorithmParams
    serving_thread_safe = True  # jit dispatch + read-only served arrays
    query_cls = Query

    def __init__(self, params: ECommAlgorithmParams):
        super().__init__(params)
        self._levents = LEventStore()

    def train(self, ctx: MeshContext, pd: TrainingData) -> ECommModel:
        from incubator_predictionio_tpu.models.negative_sampling import sample_negatives

        p = self.params
        rng = np.random.default_rng(p.seed if p.seed is not None else 0)
        k = p.negatives_per_positive
        neg_u, neg_i = sample_negatives(pd.u_idx, pd.i_idx, len(pd.items), k, rng)
        users = np.concatenate([pd.u_idx, neg_u])
        items = np.concatenate([pd.i_idx, neg_i])
        ratings = np.concatenate([pd.weight, np.zeros(len(neg_u), np.float32)])
        mf = TwoTowerMF(TwoTowerConfig(
            rank=p.rank, epochs=p.num_iterations, learning_rate=p.learning_rate,
            batch_size=8192, seed=p.seed if p.seed is not None else 0,
        )).fit(ctx, users, items, ratings, len(pd.users), len(pd.items),
               rows_are_local=pd.rows_are_local)
        mf.ensure_host()  # similarity sidecar + host predict path need numpy
        norm = mf.item_emb / (np.linalg.norm(mf.item_emb, axis=1, keepdims=True) + 1e-9)
        return ECommModel(
            mf=mf,
            user_map=pd.users,
            item_map=pd.items,
            categories=pd.categories,
            popularity=pd.buy_counts.astype(np.float32),
            item_vecs_norm=norm,
        )

    # -- live event-store reads (serving time) ----------------------------
    def _unavailable_items(self) -> set[str]:
        """Latest "constraint/unavailableItems" ``$set`` wins
        (ECommAlgorithm.scala:150-180)."""
        try:
            events = list(self._levents.find_by_entity(
                self.params.app_name, "constraint", "unavailableItems",
                event_names=("$set",), limit=1, latest=True,
            ))
        except ValueError:
            return set()
        if not events:
            return set()
        return set(events[0].properties.get("items") or ())

    def _seen_items(self, user: str) -> set[str]:
        """User's view/buy history (ECommAlgorithm.scala:429-470)."""
        try:
            return {
                e.target_entity_id
                for e in self._levents.find_by_entity(
                    self.params.app_name, "user", user,
                    event_names=tuple(self.params.seen_events),
                    target_entity_type="item",
                )
                if e.target_entity_id
            }
        except ValueError:
            return set()

    def _recent_similar_items(self, user: str, limit: int = 10) -> list[str]:
        """User's recent view targets for predictSimilar (:505-530)."""
        try:
            return [
                e.target_entity_id
                for e in self._levents.find_by_entity(
                    self.params.app_name, "user", user,
                    event_names=tuple(self.params.similar_events),
                    target_entity_type="item", limit=limit, latest=True,
                )
                if e.target_entity_id
            ]
        except ValueError:
            return []

    # -- masking ----------------------------------------------------------
    def _mask(self, model: ECommModel, query: Query) -> np.ndarray:
        n = len(model.item_map)
        mask = np.zeros(n, np.float32)
        if query.white_list is not None:
            allowed = model.item_map.lookup_array(query.white_list)
            white = np.full(n, -np.inf, np.float32)
            white[allowed[allowed >= 0]] = 0.0
            mask += white
        for item in (query.black_list or ()):
            idx = model.item_map.get(item)
            if idx is not None:
                mask[idx] = -np.inf
        if query.categories is not None:
            wanted = set(query.categories)
            for iid, idx in model.item_map.items():
                if not wanted.intersection(model.categories.get(iid, ())):
                    mask[idx] = -np.inf
        for item in self._unavailable_items():
            idx = model.item_map.get(item)
            if idx is not None:
                mask[idx] = -np.inf
        if self.params.unseen_only:
            for item in self._seen_items(query.user):
                idx = model.item_map.get(item)
                if idx is not None:
                    mask[idx] = -np.inf
        return mask

    # -- prediction -------------------------------------------------------
    def predict(self, model: ECommModel, query: Query) -> PredictedResult:
        mask = self._mask(model, query)
        uidx = model.user_map.get(query.user)
        if uidx is not None:
            scores = (
                model.mf.user_emb[uidx] @ model.mf.item_emb.T
                + model.mf.item_bias + model.mf.user_bias[uidx] + model.mf.mean
            )
        else:
            recent = [model.item_map[i] for i in self._recent_similar_items(query.user)
                      if i in model.item_map]
            if recent:
                logger.info("unknown user %s: predictSimilar from %d recent views",
                            query.user, len(recent))
                qv = model.item_vecs_norm[np.asarray(recent)]
                scores = (qv @ model.item_vecs_norm.T).sum(axis=0)
            else:
                logger.info("unknown user %s: predictDefault popularity", query.user)
                scores = model.popularity.copy()
        scores = scores + mask
        num = min(query.num, len(scores))
        top = np.argpartition(-scores, num - 1)[:num]
        top = top[np.argsort(-scores[top])]
        inv = model.item_map.inverse()
        return PredictedResult(tuple(
            ItemScore(inv[int(i)], float(scores[i]))
            for i in top if np.isfinite(scores[i])
        ))

    def batch_predict(self, model, queries):
        return [(i, self.predict(model, q)) for i, q in queries]


class ECommerceEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            DataSource,
            IdentityPreparator,
            {"ecomm": ECommAlgorithm, "": ECommAlgorithm},
            FirstServing,
        )
