"""Classification template — the scala-parallel-classification counterpart.

Reference behavior (examples/scala-parallel-classification/add-algorithm/):
the DataSource reads ``$set`` events on "user" entities carrying numeric
feature properties plus a label property (DataSource.scala reads attr0-2 +
"plan"), NaiveBayes/RandomForest train on LabeledPoints
(NaiveBayesAlgorithm.scala:36-60), queries carry a feature vector and get a
predicted label back.

Here the flagship algorithm is the JAX MLP (models/mlp.py) trained
data-parallel on the mesh; the "add-algorithm" variant of the reference
example (a second algorithm registered next to the first, with serving
combining their answers) is mirrored by :class:`NaiveBayesAlgorithm`
(Gaussian NB over the numeric features, fit/scored on-device) plus
:class:`VoteServing` (majority vote across algorithms). k-fold eval folds
are produced the reference way (readEval) using deterministic hashing.
"""

from __future__ import annotations

import dataclasses
import logging
from collections import Counter
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from incubator_predictionio_tpu.core import (
    Engine,
    EngineFactory,
    EngineParams,
    EngineParamsGenerator,
    Evaluation,
    FirstServing,
    IdentityPreparator,
    LServing,
    MetricEvaluator,
    P2LAlgorithm,
    Params,
    PDataSource,
    SanityCheck,
)
from incubator_predictionio_tpu.core.metric import (
    AverageMetric,
    OptionAverageMetric,
)
from incubator_predictionio_tpu.data.store import PEventStore
from incubator_predictionio_tpu.models.mlp import MLPClassifier, MLPConfig, MLPModel
from incubator_predictionio_tpu.parallel.mesh import MeshContext


logger = logging.getLogger(__name__)


# -- data source ------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "classification"
    attrs: tuple[str, ...] = ("attr0", "attr1", "attr2")
    label: str = "plan"
    eval_k: Optional[int] = None  # k-fold eval when set (reference readEval)


@dataclasses.dataclass
class TrainingData(SanityCheck):
    """Columnar features/labels (the RDD[LabeledPoint] counterpart)."""

    x: np.ndarray  # [n, d] float32
    y: np.ndarray  # [n] labels (original values)
    # multi-process sharded read: rows are THIS process's entity shard only
    rows_are_local: bool = False
    n_rows_global: Optional[int] = None

    def sanity_check(self) -> None:
        total = (self.n_rows_global if self.n_rows_global is not None
                 else len(self.x))
        if total == 0:
            raise ValueError("TrainingData is empty (no labeled entities found)")
        if not np.isfinite(self.x).all():
            raise ValueError("TrainingData contains non-finite features")


@dataclasses.dataclass(frozen=True)
class Query:
    features: tuple[float, ...]


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    label: object
    scores: Optional[dict] = None


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)
        self._store = PEventStore()

    def _read(self, n_shards: Optional[int] = None,
              shard_index: int = 0) -> TrainingData:
        props = self._store.aggregate_properties(
            self.params.app_name,
            "user",
            required=[*self.params.attrs, self.params.label],
            n_shards=n_shards,
            shard_index=shard_index,
        )
        xs, ys = [], []
        for pm in props.values():
            xs.append([float(pm.get(a)) for a in self.params.attrs])
            ys.append(pm.get(self.params.label))
        return TrainingData(
            np.asarray(xs, np.float32).reshape(len(xs), len(self.params.attrs)),
            np.asarray(ys),
        )

    def read_training(self, ctx: MeshContext) -> TrainingData:
        if ctx.process_count > 1:
            return self._read_sharded(ctx)
        return self._read()

    def _read_sharded(self, ctx: MeshContext) -> TrainingData:
        """Per-process entity-disjoint aggregate: each process folds $set
        events for 1/P of the users (property snapshots are per-entity, so a
        shard's fold is exact; reference: RDD partition reads)."""
        from incubator_predictionio_tpu.data.sharded import global_row_count

        td = self._read(n_shards=ctx.process_count,
                        shard_index=ctx.process_index)
        n_global = global_row_count(ctx, len(td.x))
        logger.info(
            "sharded read: %d of %d rows (shard %d/%d)",
            len(td.x), n_global, ctx.process_index, ctx.process_count)
        return TrainingData(td.x, td.y, rows_are_local=True,
                            n_rows_global=n_global)

    def read_eval(self, ctx: MeshContext):
        """k-fold split by stable row hash (reference readEval pattern)."""
        k = self.params.eval_k
        if not k:
            return []
        td = self._read()
        fold_of = np.arange(len(td.y)) % k
        folds = []
        for fold in range(k):
            train_mask = fold_of != fold
            test_mask = ~train_mask
            train = TrainingData(td.x[train_mask], td.y[train_mask])
            qa = [
                (Query(tuple(map(float, row))), label)
                for row, label in zip(td.x[test_mask], td.y[test_mask])
            ]
            folds.append((train, {"fold": fold}, qa))
        return folds


# -- algorithm --------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MLPAlgorithmParams(Params):
    hidden_dims: tuple[int, ...] = (128, 128)
    learning_rate: float = 1e-2
    batch_size: int = 256
    epochs: int = 50
    seed: int = 0


class MLPAlgorithm(P2LAlgorithm):
    """NaiveBayes → MLP (cites NaiveBayesAlgorithm.scala:36-60 for the slot
    it fills, not the math)."""

    params_class = MLPAlgorithmParams
    serving_thread_safe = True  # jit dispatch + read-only served arrays
    query_cls = Query

    def _config(self) -> MLPConfig:
        p = self.params
        return MLPConfig(
            hidden_dims=tuple(p.hidden_dims),
            learning_rate=p.learning_rate,
            batch_size=p.batch_size,
            epochs=p.epochs,
            seed=p.seed,
        )

    def train(self, ctx: MeshContext, pd: TrainingData) -> MLPModel:
        return MLPClassifier(self._config()).fit(
            ctx, pd.x, pd.y, rows_are_local=pd.rows_are_local)

    def predict(self, model: MLPModel, query: Query) -> PredictedResult:
        x = np.asarray([query.features], np.float32)
        logits = MLPClassifier.logits(model, x)[0]
        probs = np.exp(logits - logits.max())
        probs /= probs.sum()
        best = int(logits.argmax())
        return PredictedResult(
            label=model.classes[best],
            scores={str(c): float(p) for c, p in zip(model.classes, probs)},
        )

    def batch_predict(
        self, model: MLPModel, queries: Sequence[tuple[int, Query]]
    ) -> list[tuple[int, PredictedResult]]:
        if not queries:
            return []
        x = np.asarray([q.features for _, q in queries], np.float32)
        labels = MLPClassifier.predict(model, x)
        return [(i, PredictedResult(label=l)) for (i, _), l in zip(queries, labels)]


# -- second algorithm: Gaussian naive Bayes (the "add-algorithm" variant) ---

@dataclasses.dataclass(frozen=True)
class NaiveBayesAlgorithmParams(Params):
    var_smoothing: float = 1e-6
    seed: int = 0  # unused (closed-form fit); kept for params-surface parity


@dataclasses.dataclass
class NaiveBayesModel:
    classes: np.ndarray   # [c] original label values
    means: np.ndarray     # [c, d]
    variances: np.ndarray # [c, d]
    log_priors: np.ndarray  # [c]


def _nb_fit(x, y_idx, n_classes: int, smoothing: float):
    ones = jnp.ones(x.shape[0], jnp.float32)
    counts = jax.ops.segment_sum(ones, y_idx, n_classes)
    means = jax.ops.segment_sum(x, y_idx, n_classes) / counts[:, None]
    # variance as mean squared deviation (E[x²]−E[x]² cancels catastrophically
    # in float32 for large-magnitude/small-spread features), floored at the
    # smoothing so constant columns stay positive
    dev = x - means[y_idx]
    variances = jax.ops.segment_sum(dev * dev, y_idx, n_classes) / counts[:, None]
    variances = jnp.maximum(variances, smoothing)
    log_priors = jnp.log(counts / counts.sum())
    return means, variances, log_priors


@jax.jit
def _nb_loglik(x, means, variances, log_priors):
    # [b, 1, d] against [c, d]: full Gaussian log-likelihood per class
    quad = (x[:, None, :] - means[None]) ** 2 / variances[None]
    ll = -0.5 * (jnp.log(2.0 * jnp.pi * variances)[None] + quad).sum(-1)
    return ll + log_priors[None, :]


class NaiveBayesAlgorithm(P2LAlgorithm):
    """Second algorithm of the reference add-algorithm example
    (examples/scala-parallel-classification/add-algorithm/): MLlib NaiveBayes
    there; Gaussian NB over the numeric feature columns here, with the
    closed-form fit and the scoring pass both running as jax ops."""

    params_class = NaiveBayesAlgorithmParams
    serving_thread_safe = True  # jit dispatch + read-only served arrays
    query_cls = Query

    def train(self, ctx: MeshContext, pd: TrainingData) -> NaiveBayesModel:
        if pd.rows_are_local and ctx.process_count > 1:
            return self._train_sharded(ctx, pd)
        classes, y_idx = np.unique(pd.y, return_inverse=True)
        means, variances, log_priors = _nb_fit(
            jnp.asarray(pd.x), jnp.asarray(y_idx.astype(np.int32)),
            len(classes), self.params.var_smoothing,
        )
        return NaiveBayesModel(
            classes=classes,
            means=np.asarray(means),
            variances=np.asarray(variances),
            log_priors=np.asarray(log_priors),
        )

    def _train_sharded(self, ctx: MeshContext, pd: TrainingData) -> NaiveBayesModel:
        """Closed-form fit from globally-summed per-class moments: two passes
        (means first, then squared deviations against the global means) so the
        E[x²]−E[x]² cancellation the single-process fit avoids stays avoided."""
        from incubator_predictionio_tpu.data.sharded import (
            global_sum,
            union_label_set,
        )

        classes = np.asarray(union_label_set(ctx, pd.y.tolist()))
        cls_index = {c: i for i, c in enumerate(classes.tolist())}
        y_idx = np.asarray([cls_index[v] for v in pd.y.tolist()], np.int64)
        c, d = len(classes), pd.x.shape[1] if pd.x.ndim == 2 else 0
        counts = np.zeros(c, np.float64)
        np.add.at(counts, y_idx, 1.0)
        sx = np.zeros((c, d), np.float64)
        np.add.at(sx, y_idx, pd.x.astype(np.float64))
        counts, sx = global_sum(ctx, (counts, sx))
        means = sx / np.maximum(counts[:, None], 1.0)
        dev = pd.x.astype(np.float64) - means[y_idx]
        ssd = np.zeros((c, d), np.float64)
        np.add.at(ssd, y_idx, dev * dev)
        ssd = global_sum(ctx, ssd)
        variances = np.maximum(
            ssd / np.maximum(counts[:, None], 1.0), self.params.var_smoothing)
        log_priors = np.log(counts / counts.sum())
        return NaiveBayesModel(
            classes=classes,
            means=means.astype(np.float32),
            variances=variances.astype(np.float32),
            log_priors=log_priors.astype(np.float32),
        )

    def _scores(self, model: NaiveBayesModel, x: np.ndarray) -> np.ndarray:
        return np.asarray(_nb_loglik(
            x, model.means, model.variances, model.log_priors
        ))

    def predict(self, model: NaiveBayesModel, query: Query) -> PredictedResult:
        ll = self._scores(model, np.asarray([query.features], np.float32))[0]
        probs = np.exp(ll - ll.max())
        probs /= probs.sum()
        return PredictedResult(
            label=model.classes[int(ll.argmax())],
            scores={str(c): float(p) for c, p in zip(model.classes, probs)},
        )

    def batch_predict(
        self, model: NaiveBayesModel, queries: Sequence[tuple[int, Query]]
    ) -> list[tuple[int, PredictedResult]]:
        if not queries:
            return []
        x = np.asarray([q.features for _, q in queries], np.float32)
        ll = self._scores(model, x)
        return [
            (i, PredictedResult(label=model.classes[int(row.argmax())]))
            for (i, _), row in zip(queries, ll)
        ]


class VoteServing(LServing):
    """Majority vote over per-algorithm labels; ties go to the first
    algorithm's answer (the reference example's serving combines multiple
    algorithm outputs — LServing.serve sees one P per algorithm)."""

    def serve(self, query: Query, predictions: Sequence[PredictedResult]) -> PredictedResult:
        if not predictions:
            raise ValueError("no predictions to serve")
        votes = Counter(p.label for p in predictions)
        top = max(votes.values())
        for p in predictions:  # first algorithm wins ties
            if votes[p.label] == top:
                return p
        raise AssertionError("unreachable")


# -- metric -----------------------------------------------------------------

class Accuracy(AverageMetric):
    """(reference AccuracyMetric in the classification template's Evaluation)"""

    def calculate_qpa(self, q, p: PredictedResult, a) -> float:
        return 1.0 if p.label == a else 0.0


class Precision(OptionAverageMetric):
    """Per-label precision (PrecisionEvaluation.scala:25-45): scored only
    where the PREDICTED label is the target — true positive 1.0, false
    positive 0.0, everything else skipped (None)."""

    def __init__(self, label):
        self.label = label

    @property
    def header(self) -> str:
        return f"Precision(label = {self.label})"

    def calculate_qpa(self, q, p: PredictedResult, a):
        if p.label != self.label:
            return None  # unrelated to this label's precision
        return 1.0 if p.label == a else 0.0


# -- engine factory ---------------------------------------------------------

class ClassificationEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            DataSource,
            IdentityPreparator,
            {"mlp": MLPAlgorithm, "nb": NaiveBayesAlgorithm, "": MLPAlgorithm},
            {"first": FirstServing, "vote": VoteServing, "": FirstServing},
        )


# -- evaluations (Evaluation.scala / PrecisionEvaluation.scala /
#    CompleteEvaluation.scala in the add-algorithm example) -----------------

def _classification_grid(app_name: str, eval_k: int):
    return [
        EngineParams.create(
            data_source=DataSourceParams(app_name=app_name, eval_k=eval_k),
            algorithms=[("mlp", MLPAlgorithmParams(
                hidden_dims=dims, learning_rate=lr, epochs=60))],
        )
        for dims in ((16,), (32, 32))
        for lr in (1e-2, 3e-2)
    ]


class AccuracyEvaluation(Evaluation, EngineParamsGenerator):
    """engineMetric = (ClassificationEngine(), Accuracy()) over a small
    MLP grid (Evaluation.scala:36-41 + EngineParamsList)."""

    def __init__(self, app_name: str = "classification", eval_k: int = 3):
        self.engine = ClassificationEngine().apply()
        self.evaluator = MetricEvaluator(metric=Accuracy())
        self.engine_params_list = _classification_grid(app_name, eval_k)


class PrecisionEvaluation(Evaluation, EngineParamsGenerator):
    """engineMetric = (ClassificationEngine(), Precision(label=1.0))
    (PrecisionEvaluation.scala:42-44)."""

    def __init__(self, app_name: str = "classification", eval_k: int = 3,
                 label=1.0):
        self.engine = ClassificationEngine().apply()
        self.evaluator = MetricEvaluator(metric=Precision(label=label))
        self.engine_params_list = _classification_grid(app_name, eval_k)


class CompleteEvaluation(Evaluation, EngineParamsGenerator):
    """Accuracy + per-label precisions, winner recorded to best.json
    (CompleteEvaluation.scala:24-30: otherMetrics = Precision(0/1/2),
    outputPath = "best.json")."""

    def __init__(self, app_name: str = "classification", eval_k: int = 3,
                 labels=(0.0, 1.0, 2.0), output_path: str = "best.json"):
        self.engine = ClassificationEngine().apply()
        self.evaluator = MetricEvaluator(
            metric=Accuracy(),
            other_metrics=[Precision(label=lb) for lb in labels],
            output_path=output_path,
        )
        self.engine_params_list = _classification_grid(app_name, eval_k)
