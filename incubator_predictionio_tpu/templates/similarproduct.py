"""SimilarProduct template — the scala-parallel-similarproduct counterpart.

Reference behavior (examples/scala-parallel-similarproduct/multi-events-multi-algos/):
- DataSource reads users/items ``$set`` events (items carry ``categories``)
  plus "view" and "like"/"dislike" user→item events;
- three algorithms behind one engine: implicit-MF on views (ALSAlgorithm.scala:61-135
  ``ALS.trainImplicit``), item-cooccurrence counts (CooccurrenceAlgorithm.scala:51-133),
  and signed MF on like/dislike (LikeAlgorithm.scala);
- Query {"items": […], "num": N, "categories"?, "categoryBlackList"?,
  "whiteList"?, "blackList"?} → items similar to the query items, filtered;
- Serving sums scores per item across algorithms (multi-algo serving).

TPU mapping: implicit MF = two-tower towers with sampled negatives; item-item
similarity is a normalized [q, k] × [k, n] matmul + masked ``lax.top_k``;
cooccurrence counts are one Uᵀ U MXU matmul over the binary view matrix —
the reference's RDD self-join (CooccurrenceAlgorithm.scala:87) becomes a
single contraction.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from incubator_predictionio_tpu.core import (
    Engine,
    EngineFactory,
    IdentityPreparator,
    LServing,
    PAlgorithm,
    Params,
    PDataSource,
    SanityCheck,
)
from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.data.store import PEventStore
from incubator_predictionio_tpu.models.two_tower import TwoTowerConfig, TwoTowerMF
from incubator_predictionio_tpu.parallel.mesh import MeshContext
from incubator_predictionio_tpu.serving import (
    HasCategoryIndex,
    ban_rows,
    grouped_topk,
    whitelist_vec,
)
from incubator_predictionio_tpu.templates._similarity import (
    l2_normalize,
    sim_scores,
    sim_scores_stacked,
)

logger = logging.getLogger(__name__)


# -- query / result ---------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Query:
    items: tuple[str, ...]
    num: int = 10
    categories: Optional[tuple[str, ...]] = None
    category_black_list: Optional[tuple[str, ...]] = None
    white_list: Optional[tuple[str, ...]] = None
    black_list: Optional[tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    item_scores: tuple[ItemScore, ...] = ()


# -- data source ------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DataSourceParams(Params):
    app_name: str = "similarproduct"
    # train-with-rate-event variant: treat other events (e.g. "rate") as view
    # signal (examples/scala-parallel-similarproduct/train-with-rate-event)
    view_event_names: tuple[str, ...] = ("view",)


@dataclasses.dataclass
class TrainingData(SanityCheck):
    users: BiMap                       # user id ↔ index
    items: BiMap                       # item id ↔ index
    categories: dict[str, tuple[str, ...]]   # item id → categories
    view_u: np.ndarray                 # [n_views] user idx
    view_i: np.ndarray                 # [n_views] item idx
    like_u: np.ndarray                 # [n_likes] user idx
    like_i: np.ndarray                 # [n_likes] item idx
    like_sign: np.ndarray              # [n_likes] +1 like / -1 dislike
    # multi-process sharded read: event rows are THIS process's user shard
    # only (BiMaps and indices are global); *_global are job-wide counts
    rows_are_local: bool = False
    n_views_global: Optional[int] = None
    n_likes_global: Optional[int] = None

    def sanity_check(self) -> None:
        if len(self.items) == 0:
            raise ValueError("no items found ($set events on entityType 'item')")
        n_views = (self.n_views_global if self.n_views_global is not None
                   else len(self.view_u))
        n_likes = (self.n_likes_global if self.n_likes_global is not None
                   else len(self.like_u))
        if n_views == 0 and n_likes == 0:
            raise ValueError("no view/like events found")


class DataSource(PDataSource):
    params_class = DataSourceParams

    def __init__(self, params: DataSourceParams):
        super().__init__(params)
        self._store = PEventStore()

    def read_training(self, ctx: MeshContext) -> TrainingData:
        app = self.params.app_name
        procs, pid = ctx.process_count, ctx.process_index
        sharded = procs > 1
        # item properties → catalog + categories (DataSource.scala itemsRDD);
        # catalog reads stay replicated — vocabulary-sized, every process
        # needs the full id space anyway
        item_props = self._store.aggregate_properties(app, "item")
        items = BiMap.string_int(item_props.keys())
        categories = {
            iid: tuple(pm.get("categories") or ()) for iid, pm in item_props.items()
        }
        user_props = self._store.aggregate_properties(app, "user")
        view_events, like_u, like_i, like_sign = [], [], [], []
        local_users: set[str] = set()
        view_names = tuple(self.params.view_event_names)
        wanted = (*view_names, "like", "dislike")
        if sharded:
            # per-process entity-disjoint slice of the event stream
            events = self._store.find_sharded(
                app, procs, entity_type="user", event_names=wanted)[pid]
        else:
            events = self._store.find(
                app, entity_type="user", event_names=wanted,
                target_entity_type="item",
            )
        for e in events:
            if e.target_entity_type != "item":
                continue
            local_users.add(e.entity_id)
            if e.target_entity_id not in items:
                continue  # events referencing unknown items are dropped
            if e.event in view_names:
                view_events.append((e.entity_id, e.target_entity_id))
            else:
                like_u.append(e.entity_id)
                like_i.append(e.target_entity_id)
                like_sign.append(1.0 if e.event == "like" else -1.0)
        user_ids = set(user_props.keys())
        n_views_global = n_likes_global = None
        if sharded:
            from incubator_predictionio_tpu.data.sharded import (
                global_row_count,
                union_label_set,
            )

            # global user vocabulary: $set users (replicated read) ∪ the
            # union of per-shard event users — one vocab-sized allgather
            user_ids |= set(union_label_set(ctx, local_users))
            n_views_global = global_row_count(ctx, len(view_events))
            n_likes_global = global_row_count(ctx, len(like_u))
            logger.info(
                "sharded read: %d of %d rows (shard %d/%d)",
                len(view_events) + len(like_u),
                n_views_global + n_likes_global, pid, procs)
        else:
            user_ids |= local_users
        users = BiMap.string_int(sorted(user_ids))  # sorted: set order is hash-seed dependent
        view_u = users.lookup_array([u for u, _ in view_events])
        view_i = items.lookup_array([i for _, i in view_events])
        return TrainingData(
            users=users,
            items=items,
            categories=categories,
            view_u=view_u,
            view_i=view_i,
            like_u=users.lookup_array(like_u),
            like_i=items.lookup_array(like_i),
            like_sign=np.asarray(like_sign, np.float32),
            rows_are_local=sharded,
            n_views_global=n_views_global,
            n_likes_global=n_likes_global,
        )


# -- shared model + filtering ----------------------------------------------

@dataclasses.dataclass
class ItemSimModel(HasCategoryIndex):
    """Normalized item vectors + catalog metadata for similarity scoring."""

    item_vecs: np.ndarray            # [n_items, k] L2-normalized
    item_map: BiMap
    categories: dict[str, tuple[str, ...]]

    _device_vt = None

    def prepare_for_serving(self) -> "ItemSimModel":
        self._device_vt = jax.device_put(np.ascontiguousarray(self.item_vecs.T))
        self.category_index()
        return self

    def serving_info(self) -> dict:
        """Status-page observability (see TwoTowerModel.serving_info)."""
        return {"path": "device-bf16", "catalog_rows": len(self.item_map)}


def _category_mask(model, query: Query) -> np.ndarray:
    """-inf mask implementing whitelist/blacklist/category filters + query-item
    exclusion (reference isCandidateItem, ALSAlgorithm.scala:200-230) —
    vectorized scatters over the model's compiled :class:`CategoryIndex`
    instead of the seed's two per-item loops over the whole catalog. Works
    for any model exposing ``item_map`` + ``category_index()``."""
    cat_index = model.category_index()
    n = len(model.item_map)
    mask = np.zeros(n, np.float32)
    if query.white_list is not None:
        mask += whitelist_vec(model.item_map, query.white_list)
    ban_rows(mask, model.item_map, query.black_list)
    if query.categories is not None:
        mask += cat_index.allow_vec(query.categories)
    if query.category_black_list is not None:
        mask += cat_index.ban_vec(query.category_black_list)
    ban_rows(mask, model.item_map, query.items)  # exclude the query items
    return mask


def _topk_result(scores: np.ndarray, num: int, inv) -> PredictedResult:
    """Serial top-k: selection, ordering and finiteness filter — the oracle
    the batched axis-wise form must match row for row."""
    num = min(num, len(scores))
    if num <= 0:  # degenerate query, not a catalog dump
        return PredictedResult()
    top = np.argpartition(-scores, num - 1)[:num]
    top = top[np.argsort(-scores[top])]
    return PredictedResult(tuple(
        ItemScore(inv[int(i)], float(scores[i]))
        for i in top if np.isfinite(scores[i])
    ))


def _similar_items(model: ItemSimModel, query: Query) -> PredictedResult:
    known = [model.item_map[i] for i in query.items if i in model.item_map]
    if not known:
        return PredictedResult()
    if model._device_vt is None:
        model.prepare_for_serving()
    qvecs = model.item_vecs[np.asarray(known)]
    scores = sim_scores(qvecs, model._device_vt, _category_mask(model, query))
    return _topk_result(scores, query.num, model.item_map.inverse())


def _similar_items_batch(
    model: ItemSimModel, queries: Sequence[tuple[int, Query]],
) -> list[tuple[int, PredictedResult]]:
    """Batched :func:`_similar_items`: every query's vectors stack into ONE
    scoring dispatch (`sim_scores_stacked` — bitwise equal per row to the
    serial call), masks assemble as [B, n] vectorized scatters, and top-k
    runs axis-wise per ``num`` group. Queries with no known items return
    empty results exactly like the serial path."""
    queries = list(queries)
    if not queries:
        return []
    if model._device_vt is None:
        model.prepare_for_serving()
    qs = [q for _, q in queries]
    known = [
        np.asarray([model.item_map[i] for i in q.items
                    if i in model.item_map], np.int64)
        for q in qs
    ]
    results: list[PredictedResult] = [PredictedResult()] * len(qs)
    live = [b for b, k in enumerate(known) if len(k)]
    if live:
        masks = np.stack([_category_mask(model, qs[b]) for b in live])
        counts = [len(known[b]) for b in live]
        qvecs = model.item_vecs[np.concatenate([known[b] for b in live])]
        scored = sim_scores_stacked(qvecs, counts, model._device_vt, masks)
        inv = model.item_map.inverse()
        n = scored.shape[1]
        for r, (idx_row, score_row) in enumerate(grouped_topk(
                scored, [min(qs[b].num, n) for b in live])):
            finite = np.isfinite(score_row)
            results[live[r]] = PredictedResult(tuple(
                ItemScore(inv[int(i)], float(v))
                for i, v, f in zip(idx_row, score_row, finite) if f
            ))
    return [(qi, results[b]) for b, (qi, _) in enumerate(queries)]


# -- algorithms -------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ALSAlgorithmParams(Params):
    rank: int = 16
    num_iterations: int = 20
    learning_rate: float = 3e-2
    negatives_per_positive: int = 4
    seed: Optional[int] = None


class ALSAlgorithm(PAlgorithm):
    """Implicit MF on view events (ALSAlgorithm.scala:61-135
    ``ALS.trainImplicit``) via two-tower towers + sampled negatives."""

    params_class = ALSAlgorithmParams
    serving_thread_safe = True  # jit dispatch + read-only served arrays
    query_cls = Query

    def train(self, ctx: MeshContext, pd: TrainingData) -> ItemSimModel:
        from incubator_predictionio_tpu.models.negative_sampling import sample_negatives

        p = self.params
        rng = np.random.default_rng(p.seed if p.seed is not None else 0)
        pos_u, pos_i = pd.view_u, pd.view_i
        k = p.negatives_per_positive
        neg_u, neg_i = sample_negatives(pos_u, pos_i, len(pd.items), k, rng)
        users = np.concatenate([pos_u, neg_u])
        items = np.concatenate([pos_i, neg_i])
        ratings = np.concatenate([
            np.ones(len(pos_u), np.float32), np.zeros(len(neg_u), np.float32)
        ])
        mf = TwoTowerMF(TwoTowerConfig(
            rank=p.rank, epochs=p.num_iterations, learning_rate=p.learning_rate,
            batch_size=8192, seed=p.seed if p.seed is not None else 0,
        )).fit(ctx, users, items, ratings, len(pd.users), len(pd.items),
               rows_are_local=pd.rows_are_local)
        mf.ensure_host()  # cosine model is a host build
        return ItemSimModel(
            item_vecs=l2_normalize(mf.item_emb),
            item_map=pd.items,
            categories=pd.categories,
        )

    def predict(self, model: ItemSimModel, query: Query) -> PredictedResult:
        return _similar_items(model, query)

    def batch_predict(self, model, queries):
        return _similar_items_batch(model, queries)


class LikeAlgorithm(ALSAlgorithm):
    """Signed MF on like/dislike (LikeAlgorithm.scala: like=+1, dislike=-1;
    later event for the same (user, item) wins in the reference — here all
    signals contribute, which is the same MF objective up to weighting)."""

    def train(self, ctx: MeshContext, pd: TrainingData) -> ItemSimModel:
        p = self.params
        n_likes = (pd.n_likes_global if pd.n_likes_global is not None
                   else len(pd.like_u))
        if n_likes == 0:
            raise ValueError("LikeAlgorithm requires like/dislike events")
        mf = TwoTowerMF(TwoTowerConfig(
            rank=p.rank, epochs=p.num_iterations, learning_rate=p.learning_rate,
            batch_size=8192, seed=p.seed if p.seed is not None else 0,
        )).fit(ctx, pd.like_u, pd.like_i, pd.like_sign,
               len(pd.users), len(pd.items),
               rows_are_local=pd.rows_are_local)
        mf.ensure_host()  # cosine model is a host build
        return ItemSimModel(
            item_vecs=l2_normalize(mf.item_emb),
            item_map=pd.items,
            categories=pd.categories,
        )


@dataclasses.dataclass(frozen=True)
class CooccurrenceAlgorithmParams(Params):
    n: int = 20  # top co-occurring items kept per item (CooccurrenceAlgorithm.scala:27)


@dataclasses.dataclass
class CooccurrenceModel(HasCategoryIndex):
    top_cooccurrences: dict[int, list[tuple[int, int]]]  # item → [(item, count)]
    item_map: BiMap
    categories: dict[str, tuple[str, ...]]

    def prepare_for_serving(self) -> "CooccurrenceModel":
        self.category_index()
        return self


class CooccurrenceAlgorithm(PAlgorithm):
    """Item co-view counts (CooccurrenceAlgorithm.scala:51-133). The RDD
    self-join becomes Uᵀ U on the device: U is the binary user×item view
    matrix, so one bf16 matmul yields every pairwise co-count."""

    params_class = CooccurrenceAlgorithmParams
    serving_thread_safe = True  # jit dispatch + read-only served arrays
    query_cls = Query

    def train(self, ctx: MeshContext, pd: TrainingData) -> CooccurrenceModel:
        n_users, n_items = len(pd.users), len(pd.items)
        u = np.zeros((n_users, n_items), np.float32)
        u[pd.view_u, pd.view_i] = 1.0  # de-duplicated views
        cooc = np.array(_cooccur(jnp.asarray(u)))  # copy: jax buffers are read-only
        if pd.rows_are_local:
            # each process counted only its user shard's co-views; users are
            # entity-disjoint, so the global count matrix is the plain sum
            from incubator_predictionio_tpu.data.sharded import global_sum

            cooc = global_sum(ctx, cooc)
        np.fill_diagonal(cooc, 0)
        top_n = self.params.n
        top: dict[int, list[tuple[int, int]]] = {}
        for i in range(n_items):
            row = cooc[i]
            nz = np.nonzero(row)[0]
            if len(nz) == 0:
                continue
            order = nz[np.argsort(-row[nz])][:top_n]
            top[i] = [(int(j), int(row[j])) for j in order]
        return CooccurrenceModel(top, pd.items, pd.categories)

    def predict(self, model: CooccurrenceModel, query: Query) -> PredictedResult:
        counts: dict[int, int] = {}
        for qi in query.items:
            idx = model.item_map.get(qi)
            if idx is None:
                continue
            for j, c in model.top_cooccurrences.get(idx, ()):
                counts[j] = counts.get(j, 0) + c
        mask = _category_mask(model, query)
        scored = [
            (j, c) for j, c in counts.items() if np.isfinite(mask[j])
        ]
        scored.sort(key=lambda t: -t[1])
        inv = model.item_map.inverse()
        return PredictedResult(tuple(
            ItemScore(inv[j], float(c)) for j, c in scored[: query.num]
        ))

    def batch_predict(self, model, queries):
        return [(i, self.predict(model, q)) for i, q in queries]


@jax.jit
def _cooccur(u):
    return (u.T.astype(jnp.bfloat16) @ u.astype(jnp.bfloat16)).astype(jnp.float32)


# -- serving ----------------------------------------------------------------

class Serving(LServing):
    """Multi-algo: sum scores per item across algorithm outputs
    (multi-events-multi-algos Serving.scala: standardize-free sum variant)."""

    def serve(self, query: Query, predictions: Sequence[PredictedResult]) -> PredictedResult:
        combined: dict[str, float] = {}
        for pred in predictions:
            for s in pred.item_scores:
                combined[s.item] = combined.get(s.item, 0.0) + s.score
        top = sorted(combined.items(), key=lambda t: -t[1])[: query.num]
        return PredictedResult(tuple(ItemScore(i, sc) for i, sc in top))


class SimilarProductEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            DataSource,
            IdentityPreparator,
            {"als": ALSAlgorithm, "cooccurrence": CooccurrenceAlgorithm,
             "likealgo": LikeAlgorithm, "": ALSAlgorithm},
            {"": Serving},
        )
