"""Built-in engine templates — counterparts of the reference's examples/ gallery.

Each template is a DASE engine: classification (MLP), recommendation
(two-tower MF), similarproduct (implicit MF + cooccurrence), recommended_user
(user-to-user implicit MF over follow events), ecommerce (retrieval +
business rules), sequential (transformer session recommender).
"""
